"""Runtime lock-order sanitizer: instrumented lock/queue factories.

Product modules create their synchronisation primitives through the
factories here instead of calling ``threading.Lock()`` directly::

    from saturn_tpu.analysis import concurrency as tsan
    self._lock = tsan.rlock("queue.lock")

When tracing is **off** (the default) the factories return the plain
``threading`` / ``queue`` primitives — zero overhead, identical
semantics.  When tracing is **on** (``SATURN_TPU_TSAN=1`` in the
environment, or a deterministic interleaving scheduler is installed by
:mod:`saturn_tpu.analysis.concurrency.interleave`) they return traced
wrappers that

- maintain a per-thread stack of held locks,
- record every *(held → newly acquired)* lock pair into a global
  :class:`LockOrderRecorder` (the runtime half of the SAT-C001
  lock-order-inversion check), and
- flag blocking queue waits performed while holding a lock (the runtime
  half of SAT-C003).

The tracing decision is taken **at creation time**: a lock created while
tracing is off stays untraced for its lifetime.  Tests that want traced
primitives must enable tracing (env var or scheduler) before
constructing the objects under test.

Lock names are the string literals passed to the factories, so the node
names in the runtime graph match the node names the static pass derives
from the same call sites — that is what makes
:meth:`LockOrderRecorder.validate_against` meaningful.

Stdlib-only; this module sits under every hot-path product module.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple, Union

__all__ = [
    "lock",
    "rlock",
    "condition",
    "make_queue",
    "enabled",
    "set_active",
    "held_locks",
    "recorder",
    "LockOrderRecorder",
    "TracedLock",
    "TracedRLock",
    "TracedCondition",
    "TracedQueue",
]

# Flipped by the interleave scheduler (install/uninstall).  Independent of
# the env var so tests can trace without mutating os.environ.
_ACTIVE = False

# Per-thread stack of (lock-name, reentry-count) pairs.
_TLS = threading.local()


def enabled() -> bool:
    """True when newly created primitives should be traced."""
    return _ACTIVE or os.environ.get("SATURN_TPU_TSAN", "") == "1"


def set_active(value: bool) -> None:
    """Force tracing on/off for subsequently created primitives."""
    global _ACTIVE
    _ACTIVE = bool(value)


def _stack() -> List[List[Any]]:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = []
        _TLS.stack = st
    return st


def held_locks() -> Tuple[str, ...]:
    """Names of traced locks held by the calling thread, outermost first."""
    return tuple(name for name, _count in _stack())


class LockOrderRecorder:
    """Accumulates observed (held → acquired) lock pairs across threads.

    Thread-safe; the recorder's own lock is a raw ``threading.Lock`` and
    is deliberately invisible to the tracing machinery.
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # (prev, nxt) -> (count, first-witness thread name)
        self._edges: Dict[Tuple[str, str], Tuple[int, str]] = {}
        # lock-name -> names of threads under which a blocking queue wait
        # happened while the lock was held.
        self._blocking_under_lock: Dict[str, Set[str]] = {}

    def note(self, prev: str, nxt: str) -> None:
        tname = threading.current_thread().name
        with self._mu:
            count, witness = self._edges.get((prev, nxt), (0, tname))
            self._edges[(prev, nxt)] = (count + 1, witness)

    def note_blocking_under_lock(self, lock_name: str) -> None:
        tname = threading.current_thread().name
        with self._mu:
            self._blocking_under_lock.setdefault(lock_name, set()).add(tname)

    def edges(self) -> Set[Tuple[str, str]]:
        with self._mu:
            return set(self._edges)

    def edge_witness(self, prev: str, nxt: str) -> Optional[str]:
        with self._mu:
            hit = self._edges.get((prev, nxt))
        return hit[1] if hit else None

    def blocking_under_lock(self) -> Dict[str, Set[str]]:
        with self._mu:
            return {k: set(v) for k, v in self._blocking_under_lock.items()}

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._blocking_under_lock.clear()

    def cycles(self) -> List[List[str]]:
        """Minimal cycles in the observed-order graph alone."""
        return find_cycles(self.edges())

    def validate_against(
        self, static_pairs: Iterable[Tuple[str, str]]
    ) -> List[List[str]]:
        """Cycles in (observed ∪ static) that use ≥1 observed edge.

        A cycle that exists purely in the static graph is the static
        pass's job to report; this method answers the runtime question
        "did execution realize an ordering that, combined with orders
        the code is statically capable of, closes a deadlock cycle?".
        """
        observed = self.edges()
        union: Set[Tuple[str, str]] = set(static_pairs) | observed
        out: List[List[str]] = []
        seen: Set[Tuple[str, ...]] = set()
        for a, b in sorted(observed):
            cyc = _shortest_cycle_through(union, a, b)
            if cyc is not None:
                key = _normalize_cycle(cyc)
                if key not in seen:
                    seen.add(key)
                    out.append(cyc)
        return out


def find_cycles(edges: Iterable[Tuple[str, str]]) -> List[List[str]]:
    """All distinct minimal cycles, one per participating edge, deduped."""
    edge_set = set(edges)
    out: List[List[str]] = []
    seen: Set[Tuple[str, ...]] = set()
    for a, b in sorted(edge_set):
        cyc = _shortest_cycle_through(edge_set, a, b)
        if cyc is not None:
            key = _normalize_cycle(cyc)
            if key not in seen:
                seen.add(key)
                out.append(cyc)
    return out


def _shortest_cycle_through(
    edges: Set[Tuple[str, str]], a: str, b: str
) -> Optional[List[str]]:
    """Shortest cycle containing edge a→b: BFS a path b ⇝ a, prepend a→b."""
    adj: Dict[str, List[str]] = {}
    for u, v in edges:
        adj.setdefault(u, []).append(v)
    for vs in adj.values():
        vs.sort()
    if a == b:
        return [a, a]
    frontier = [b]
    parent: Dict[str, str] = {b: b}
    while frontier:
        nxt: List[str] = []
        for u in frontier:
            for v in adj.get(u, ()):
                if v in parent:
                    continue
                parent[v] = u
                if v == a:
                    path = [a]
                    while path[-1] != b:
                        path.append(parent[path[-1]])
                    path.reverse()  # b ... a
                    return [a] + path  # a, b, ..., a
                nxt.append(v)
        frontier = nxt
    return None


def _normalize_cycle(cyc: List[str]) -> Tuple[str, ...]:
    """Rotation-invariant key for a cycle given as [n0, n1, ..., n0]."""
    body = cyc[:-1]
    k = body.index(min(body))
    return tuple(body[k:] + body[:k])


# The process-global recorder.  Traced primitives write here; tests and
# the CLI read/validate/reset it.
_RECORDER = LockOrderRecorder()


def recorder() -> LockOrderRecorder:
    return _RECORDER


def _note_intent(name: str) -> None:
    """Record held-lock -> target edges BEFORE attempting the acquire.

    Ordering edges must come from the attempt, not the success: in a real
    deadlock neither thread's second acquire ever succeeds, and a recorder
    that only logs completed acquisitions would see no cycle at all.
    """
    st = _stack()
    if st and st[-1][0] == name:
        return
    for prev, _count in st:
        if prev == name:
            # Re-entrant acquire below other locks: no new ordering edge.
            return
        _RECORDER.note(prev, name)


def _push(name: str) -> None:
    st = _stack()
    if st and st[-1][0] == name:
        st[-1][1] += 1
        return
    for prev, _count in st:
        if prev == name:
            st.append([name, 1])
            return
    st.append([name, 1])


def _pop(name: str) -> None:
    st = _stack()
    for i in range(len(st) - 1, -1, -1):
        if st[i][0] == name:
            st[i][1] -= 1
            if st[i][1] == 0:
                del st[i]
            return


class TracedLock:
    """threading.Lock wrapper recording acquisition order by name."""

    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str) -> None:
        self.name = name
        self._inner = self._factory()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _note_intent(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _push(self.name)
        return got

    def release(self) -> None:
        _pop(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class TracedRLock(TracedLock):
    """threading.RLock wrapper recording acquisition order by name."""

    _factory = staticmethod(threading.RLock)

    def locked(self) -> bool:  # RLock has no .locked() before 3.12
        if self._inner.acquire(blocking=False):
            self._inner.release()
            return False
        return True


class TracedCondition:
    """Condition over a traced lock; wait/notify stay native.

    Built on the traced lock's *underlying* primitive so the stdlib
    wait/notify machinery operates on a real lock, while enter/exit go
    through the wrapper to keep the held-stack accurate.
    """

    def __init__(self, lk: TracedLock, name: str) -> None:
        self.name = name
        self._lk = lk
        self._cond = threading.Condition(lk._inner)

    def __enter__(self) -> "TracedCondition":
        self._lk.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._lk.release()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._lk.acquire(blocking, timeout)

    def release(self) -> None:
        self._lk.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        # wait() releases the lock while blocked: reflect that in the
        # held stack so other threads' acquisitions don't appear ordered
        # under a lock nobody holds.
        _pop(self._lk.name)
        try:
            return self._cond.wait(timeout)
        finally:
            _push(self._lk.name)

    def wait_for(self, predicate: Any, timeout: Optional[float] = None) -> Any:
        _pop(self._lk.name)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            _push(self._lk.name)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self) -> str:
        return f"<TracedCondition {self.name!r} over {self._lk.name!r}>"


class TracedQueue(queue.Queue):  # type: ignore[type-arg]
    """queue.Queue flagging indefinite blocking waits under a held lock."""

    def __init__(self, name: str, maxsize: int = 0) -> None:
        super().__init__(maxsize)
        self.name = name

    def _check(self, blocking: bool, timeout: Optional[float]) -> None:
        if blocking and timeout is None:
            held = held_locks()
            if held:
                _RECORDER.note_blocking_under_lock(held[-1])

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        self._check(block, timeout)
        return super().get(block, timeout)

    def put(
        self, item: Any, block: bool = True, timeout: Optional[float] = None
    ) -> None:
        self._check(block, timeout)
        super().put(item, block, timeout)


LockLike = Union[threading.Lock, "threading.RLock", TracedLock]  # type: ignore[valid-type]


def lock(name: str) -> Any:
    """A mutex: plain ``threading.Lock`` untraced, ``TracedLock`` traced."""
    if enabled():
        return TracedLock(name)
    return threading.Lock()


def rlock(name: str) -> Any:
    """A re-entrant mutex, traced when the sanitizer is enabled."""
    if enabled():
        return TracedRLock(name)
    return threading.RLock()


def condition(lk: Any, name: str) -> Any:
    """A condition variable over ``lk`` (a value returned by lock/rlock)."""
    if isinstance(lk, TracedLock):
        return TracedCondition(lk, name)
    return threading.Condition(lk)


def make_queue(name: str, maxsize: int = 0) -> Any:
    """A FIFO queue, traced when the sanitizer is enabled."""
    if enabled():
        return TracedQueue(name, maxsize)
    return queue.Queue(maxsize)
