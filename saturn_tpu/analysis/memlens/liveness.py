"""Peak-liveness abstract interpreter: static per-device HBM peak.

Subclasses the shardflow :class:`Interpreter` so sharding propagation
(GSPMD implicit rules, shard_map manual regions, control-flow recursion)
comes for free, and layers a byte-exact residency simulation on top:

* every equation output is *allocated* at its defining equation with
  per-shard bytes derived from the propagated spec (global bytes divided
  by the product of mesh-axis sizes it is sharded over; inside a
  shard_map body avals are already per-shard and are charged verbatim);
* every interpreter-allocated value is *freed* at its last read within
  its frame (linear-scan liveness over the equation list);
* donated top-level inputs join the freeable set, so params/opt-state
  release at their last read exactly as XLA's buffer donation aliases
  them — non-donated inputs stay resident for the whole step;
* inner frames (pjit / remat / scan / while bodies) free everything they
  allocated when the frame exits, before the outer equation's outputs
  are charged: a remat body therefore contributes transients only, while
  a scan's carries and stacked outputs persist as the outer outputs;
* explicit/implicit collectives transiently charge their output buffer
  (the shardflow ledger hook reports the payload) so an all-gather whose
  result is consumed immediately still shows up in the peak;
* for pinned-host offload configs the resident param/opt-state copy
  lives in host memory, not HBM, and is excluded from the live set.

The model is deliberately a *peak* model, not an allocator simulation:
no fragmentation, no buffer reuse beyond liveness, no rematerialization
scheduling. The differential suite holds it within a calibrated band of
``compiled.memory_analysis()`` and SAT-M005 audits drift in production.
"""

from __future__ import annotations

import heapq
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from saturn_tpu.analysis.shardflow.interp import (
    Interpreter,
    Spec,
    _axis_group_size,
    _from_pspec,
    _nbytes,
    _provenance,
    _replicated,
)

log = logging.getLogger("saturn_tpu.analysis.memlens")

#: collectives whose output buffer is materialized before the consumer
#: runs — they transiently raise residency even if consumed immediately
_SCRATCH_OPS = frozenset({"all_gather", "all_reduce", "reshard", "all_to_all"})

#: how many live values to snapshot when a new peak is recorded
_TOP_N = 8


def per_shard_bytes(aval: Any, spec: Spec, mesh_axes: Dict[str, int]) -> int:
    """Per-device bytes of ``aval`` under ``spec`` (ceil division)."""
    nb = _nbytes(aval)
    if nb <= 0:
        return 0
    div = 1
    for dims in spec:
        for a in dims:
            div *= max(int(mesh_axes.get(a, 1)), 1)
    return -(-nb // div)


@dataclass
class MemoryProfile:
    """Static per-device HBM residency summary for one traced step."""

    technique: str = "?"
    size: int = 0
    window: int = 1
    peak_bytes: int = 0
    persistent_bytes: int = 0          # state inputs (params/opt-state)
    persistent_out_bytes: int = 0      # the new state tree
    transient_peak_bytes: int = 0      # peak minus resident state
    input_bytes: int = 0               # non-state inputs (the batch)
    const_bytes: int = 0
    host_bytes: int = 0                # pinned-host resident (offload)
    donated_bytes: int = 0
    collective_scratch_peak: int = 0
    largest_temp_bytes: int = 0
    largest_temp_where: str = ""
    peak_contributors: List[Dict[str, Any]] = field(default_factory=list)
    missed_donations: List[Dict[str, Any]] = field(default_factory=list)
    exclude_state: bool = False

    def to_json(self) -> Dict[str, Any]:
        return {
            "technique": self.technique,
            "size": self.size,
            "window": self.window,
            "peak_bytes": self.peak_bytes,
            "persistent_bytes": self.persistent_bytes,
            "persistent_out_bytes": self.persistent_out_bytes,
            "transient_peak_bytes": self.transient_peak_bytes,
            "input_bytes": self.input_bytes,
            "const_bytes": self.const_bytes,
            "host_bytes": self.host_bytes,
            "donated_bytes": self.donated_bytes,
            "collective_scratch_peak": self.collective_scratch_peak,
            "largest_temp_bytes": self.largest_temp_bytes,
            "largest_temp_where": self.largest_temp_where,
            "peak_contributors": list(self.peak_contributors),
            "missed_donations": list(self.missed_donations),
            "exclude_state": self.exclude_state,
        }


class LivenessInterpreter(Interpreter):
    """Shardflow spec propagation + a live-byte counter per frame.

    ``donated`` aligns with ``in_specs`` positionally; the first
    ``n_state_in`` inputs (after any pad the base class inserts) are the
    state tree and the first ``n_state_out`` jaxpr outputs are the new
    state. ``exclude_state`` models pinned-host offload: resident state
    is charged to host memory instead of HBM.
    """

    def __init__(
        self,
        mesh_axes: Dict[str, int],
        donated: Optional[Sequence[bool]] = None,
        n_state_in: int = 0,
        n_state_out: int = 0,
        exclude_state: bool = False,
    ) -> None:
        super().__init__(mesh_axes)
        self._donated_in = list(donated or [])
        self.n_state_in = int(n_state_in)
        self.n_state_out = int(n_state_out)
        self.exclude_state = bool(exclude_state)
        self._live = 0
        self._tbl: Dict[Any, Tuple[int, str, str]] = {}  # var -> (bytes, where, kind)
        self._freeable: set = set()
        self._protect_stack: List[set] = []
        self._depth = 0
        self._snap_floor = 0
        # results
        self.peak_bytes = 0
        self.peak_contributors: List[Dict[str, Any]] = []
        self.persistent_in_bytes = 0
        self.persistent_out_bytes = 0
        self.host_bytes = 0
        self.const_bytes = 0
        self.input_bytes = 0
        self.donated_bytes = 0
        self.collective_scratch_peak = 0
        self.largest_temp: Tuple[int, str] = (0, "")
        self.missed_donations: List[Dict[str, Any]] = []

    # ------------------------------------------------------------ accounting
    def _shard_bytes(self, aval: Any, spec: Spec) -> int:
        if self._shmap_depth > 0:
            # shard_map body avals are already per-shard
            return max(_nbytes(aval), 0)
        return per_shard_bytes(aval, spec, self.mesh_axes)

    def _note_peak(self) -> None:
        if self._live > self.peak_bytes:
            self.peak_bytes = self._live
        if self._live >= self._snap_floor:
            # snapshot the top contributors, but only on ~2% improvements
            # so big jaxprs don't pay O(n) per equation
            self._snap_floor = int(self._live * 1.02) + 1
            self.peak_contributors = [
                {"bytes": b, "where": where, "kind": kind}
                for b, where, kind in heapq.nlargest(
                    _TOP_N, self._tbl.values())
            ]

    def _free(self, v: Any, force: bool = False) -> None:
        row = self._tbl.get(v)
        if row is None:
            return
        if not force:
            if v not in self._freeable:
                return
            for prot in self._protect_stack:
                if v in prot:
                    return
        self._live -= row[0]
        del self._tbl[v]
        self._freeable.discard(v)

    # ------------------------------------------------------------- top level
    def run(self, closed: Any, in_specs: Sequence[Spec]) -> List[Spec]:
        jaxpr = getattr(closed, "jaxpr", closed)
        env: Dict[Any, Spec] = {}
        for cv in jaxpr.constvars:
            env[cv] = _replicated(cv.aval)
            b = self._shard_bytes(cv.aval, env[cv])
            self._tbl[cv] = (b, "constvar", "const")
            self._live += b
            self.const_bytes += b
        invars = list(jaxpr.invars)
        specs = list(in_specs)
        donated = list(self._donated_in)
        if len(donated) < len(specs):
            donated += [False] * (len(specs) - len(donated))
        state_lo, state_hi = 0, self.n_state_in
        if len(specs) < len(invars):
            pad = len(invars) - len(specs)
            specs = [_replicated(v.aval) for v in invars[:pad]] + specs
            donated = [False] * pad + donated
            state_lo += pad
            state_hi += pad
        for i, (v, s) in enumerate(zip(invars, specs)):
            fitted = self._fit(s, v.aval)
            env[v] = fitted
            b = self._shard_bytes(v.aval, fitted)
            is_state = state_lo <= i < state_hi
            if is_state and self.exclude_state:
                self.host_bytes += b
                continue
            self._tbl[v] = (b, f"invar#{i}", "state" if is_state else "input")
            self._live += b
            if is_state:
                self.persistent_in_bytes += b
            else:
                self.input_bytes += b
            if donated[i]:
                self._freeable.add(v)
                self.donated_bytes += b
        self._note_peak()
        self._protect_stack.append({
            v for v in jaxpr.outvars
            if hasattr(v, "aval") and not hasattr(v, "val")
        })
        try:
            self._interpret(jaxpr, env, multiplier=1, scan_depth=0)
        finally:
            self._protect_stack.pop()
        out_specs = [self._read(env, v) for v in jaxpr.outvars]
        for i, (v, s) in enumerate(zip(jaxpr.outvars, out_specs)):
            if i >= self.n_state_out or not hasattr(v, "aval"):
                continue
            self.persistent_out_bytes += self._shard_bytes(
                v.aval, self._fit(s, v.aval))
        self._find_missed_donations(invars, donated, jaxpr.outvars)
        return out_specs

    def _find_missed_donations(self, invars, donated, outvars) -> None:
        out_avals = [v.aval for v in outvars if hasattr(v, "aval")]
        for i, v in enumerate(invars):
            if donated[i] or not hasattr(v, "aval"):
                continue
            aval = v.aval
            shape = tuple(getattr(aval, "shape", ()))
            if not shape:
                continue  # scalars: aliasing saves nothing worth flagging
            dtype = getattr(aval, "dtype", None)
            for w in out_avals:
                if (tuple(getattr(w, "shape", ())) == shape
                        and getattr(w, "dtype", None) == dtype):
                    self.missed_donations.append({
                        "invar": i,
                        "shape": list(shape),
                        "dtype": str(dtype),
                        "bytes": _nbytes(aval),
                    })
                    break

    # ---------------------------------------------------------- interpreter
    def _interpret(self, jaxpr: Any, env: Dict[Any, Spec],
                   multiplier: int, scan_depth: int) -> None:
        is_top = self._depth == 0
        self._depth += 1
        last_use: Dict[Any, int] = {}
        for i, eqn in enumerate(jaxpr.eqns):
            for a in eqn.invars:
                if not hasattr(a, "val"):
                    last_use[a] = i
        frame: List[Any] = []
        if not is_top:
            self._protect_stack.append({
                v for v in getattr(jaxpr, "outvars", ())
                if hasattr(v, "aval") and not hasattr(v, "val")
            })
        try:
            for index, eqn in enumerate(jaxpr.eqns):
                name = eqn.primitive.name
                in_specs = [self._read(env, v) for v in eqn.invars]
                handler = getattr(self, f"_h_{name}", None)
                if handler is None:
                    outs = self._default_outs(eqn, in_specs, index,
                                              multiplier, scan_depth)
                else:
                    outs = handler(eqn, in_specs, index, multiplier,
                                   scan_depth)
                for v, s in zip(eqn.outvars, outs):
                    if not hasattr(v, "aval"):
                        continue
                    fitted = self._fit(s, v.aval)
                    env[v] = fitted
                    if v not in self._tbl:
                        b = self._shard_bytes(v.aval, fitted)
                        where = _provenance(eqn, index)
                        self._tbl[v] = (b, where, "temp")
                        self._live += b
                        self._freeable.add(v)
                        frame.append(v)
                        if b > self.largest_temp[0]:
                            self.largest_temp = (b, where)
                self._note_peak()
                for a in eqn.invars:
                    if not hasattr(a, "val") and last_use.get(a) == index:
                        self._free(a)
                for v in eqn.outvars:
                    # dead outputs (DropVars, unused results) free at once
                    if hasattr(v, "aval") and v not in last_use:
                        self._free(v)
        finally:
            self._depth -= 1
            if not is_top:
                self._protect_stack.pop()
                for v in frame:
                    self._free(v, force=True)

    def _default_outs(self, eqn, in_specs, index, multiplier, scan_depth):
        # mirror the base-class fallback dispatch (it lives inline in the
        # base _interpret loop, so re-dispatch here)
        from saturn_tpu.analysis.shardflow.interp import (
            _ELEMENTWISE, _REDUCERS)
        name = eqn.primitive.name
        if name in _ELEMENTWISE:
            return self._elementwise(eqn, in_specs, index, multiplier,
                                     scan_depth)
        if name in _REDUCERS:
            return self._reduce(eqn, in_specs, index, multiplier, scan_depth)
        return [_replicated(v.aval) for v in eqn.outvars]

    # collective output buffers transiently raise residency
    def _record(self, op, axes, payload, eqn, index, multiplier, scan_depth,
                explicit=False):
        super()._record(op, axes, payload, eqn, index, multiplier,
                        scan_depth, explicit=explicit)
        kept = tuple(a for a in axes if a in self.mesh_axes)
        if op in _SCRATCH_OPS and _axis_group_size(kept, self.mesh_axes) > 1:
            b = max(int(payload), 0)
            if b > self.collective_scratch_peak:
                self.collective_scratch_peak = b
            self._live += b
            self._note_peak()
            self._live -= b


def analyze_closed(
    closed: Any,
    in_specs: Sequence[Spec],
    mesh_axes: Dict[str, int],
    donated: Optional[Sequence[bool]] = None,
    n_state_in: int = 0,
    n_state_out: int = 0,
    exclude_state: bool = False,
    technique: str = "?",
    size: int = 0,
    window: int = 1,
) -> MemoryProfile:
    """Run the liveness simulation over one closed jaxpr."""
    interp = LivenessInterpreter(
        mesh_axes,
        donated=donated,
        n_state_in=n_state_in,
        n_state_out=n_state_out,
        exclude_state=exclude_state,
    )
    interp.run(closed, in_specs)
    peak = interp.peak_bytes
    persistent = interp.persistent_in_bytes
    return MemoryProfile(
        technique=technique,
        size=size,
        window=int(window),
        peak_bytes=peak,
        persistent_bytes=persistent,
        persistent_out_bytes=interp.persistent_out_bytes,
        transient_peak_bytes=max(peak - persistent, 0),
        input_bytes=interp.input_bytes,
        const_bytes=interp.const_bytes,
        host_bytes=interp.host_bytes,
        donated_bytes=interp.donated_bytes,
        collective_scratch_peak=interp.collective_scratch_peak,
        largest_temp_bytes=interp.largest_temp[0],
        largest_temp_where=interp.largest_temp[1],
        peak_contributors=interp.peak_contributors,
        missed_donations=interp.missed_donations,
        exclude_state=exclude_state,
    )


def analyze(traced: Dict[str, Any], window: int = 1) -> MemoryProfile:
    """Static per-device HBM profile for one ``trace_step`` result.

    Mirrors the real dispatch contract: the state tree is donated
    (``donate_argnums=(0,)``), the batch is donated only on the fused
    ``lax.scan`` path, and a fused window of K steps keeps K batch
    shards resident at once (modeled as ``peak + (K-1) x batch shard``).
    """
    from jax.sharding import PartitionSpec
    from jax.tree_util import tree_leaves

    closed = traced["jaxpr"]
    mesh_axes = dict(traced["mesh_axes"])
    window = max(int(window), 1)

    state_leaves = tree_leaves(traced["state_shapes"])
    spec_leaves = tree_leaves(
        traced["state_specs"],
        is_leaf=lambda x: x is None or isinstance(x, PartitionSpec),
    )
    in_specs: List[Spec] = [
        _from_pspec(ps, len(getattr(leaf, "shape", ())))
        for leaf, ps in zip(state_leaves, spec_leaves)
    ]
    batch_sds = traced["batch_sds"]
    batch_spec = _from_pspec(traced["batch_spec"],
                             len(getattr(batch_sds, "shape", ())))
    in_specs.append(batch_spec)

    n_state = len(state_leaves)
    donated = [True] * n_state + [window > 1]
    exclude_state = traced.get("param_memory_kind") == "pinned_host"

    profile = analyze_closed(
        closed,
        in_specs,
        mesh_axes,
        donated=donated,
        n_state_in=n_state,
        n_state_out=n_state,
        exclude_state=exclude_state,
        technique=str(traced.get("technique", "?")),
        size=int(traced.get("size", 0) or 0),
        window=window,
    )
    if window > 1:
        extra = (window - 1) * per_shard_bytes(batch_sds, batch_spec,
                                              mesh_axes)
        profile.peak_bytes += extra
        profile.transient_peak_bytes += extra
    return profile
