"""saturn-memlens: static HBM peak-liveness analysis + zero-compile priors.

Two passes over a technique's traced step function (abstract values only
— CPU, no chip, no compile):

- :mod:`.liveness` — a peak-liveness abstract interpreter riding
  shardflow's PartitionSpec propagation (it subclasses the shardflow
  :class:`~saturn_tpu.analysis.shardflow.interp.Interpreter`): linear-scan
  liveness over the jaxpr's equations with per-shard bytes from the
  propagated specs, donation-aware frees (donated args release at their
  last read), remat/scan/while/pjit recursion (remat bodies contribute
  transient-only, scan carries persist across the trip), collective
  scratch accounting (all-gather / all-reduce buffers from the shardflow
  ledger hooks), pinned-host exclusion for offload configs, and a
  persistent-vs-transient split (params/opt-state vs activations);
- :mod:`.passes` — SAT-M diagnostics with file:line-ish provenance
  (SAT-M001 predicted OOM, SAT-M002 peak dominated by one oversized
  temporary, SAT-M003 missed donation, SAT-M004 headroom below margin,
  SAT-M005 static-vs-compiled drift audit, SAT-M000 untraceable),
  sanctionable via ``# sanctioned-memlens: reason`` markers (downgrade
  to info, never silence), plus the feasibility verdicts the three
  consumers read: the trial runner's pre-lowering grid pruning, the
  admission controller's memory-aware cold-start gate, and the elastic
  replanner's migration destination-fit check.

Import-light at package level (the CLI must be able to set XLA device
flags before jax loads); everything heavier is imported inside functions.
"""

from __future__ import annotations

#: Version of the memlens rule set (liveness model, diagnostic meanings,
#: feasibility margins). Folded into the profile-cache fingerprint and the
#: AOT-cache runtime identity so feasibility entries recorded under one
#: liveness model miss cleanly under another.
PASS_VERSION = 1

__all__ = ["PASS_VERSION"]
