"""Memlens pass 2: SAT-M diagnostics and zero-compile feasibility verdicts.

Diagnostics over one :class:`MemoryProfile` (:func:`analyze_traced`):

- SAT-M001 (error): predicted per-device HBM peak exceeds capacity by
  the OOM margin — deterministic infeasibility before any compile;
- SAT-M002 (warning): the peak is dominated by a single oversized
  temporary;
- SAT-M003 (error): a non-donated input's shape/dtype matches an output
  — XLA could alias it, the buffer is paid twice;
- SAT-M004 (warning): predicted peak lands above the allocator headroom
  margin but under capacity — fragmentation risk;
- SAT-M005 (warning, :func:`audit_point`): static peak vs the compiled
  ``memory_analysis()`` figure drift beyond the calibration ratio;
- SAT-M000: technique untraceable / source unreadable.

A ``# sanctioned-memlens: <reason>`` comment at a finding's file:line
provenance (or the contiguous comment block above it) downgrades it to
``info`` — visible, never gating, never silent. eqn#-style provenance
cannot be sanctioned.

Feasibility verdicts for the three consumers:

- :func:`grid_point_infeasible` — the trial runner's pre-lowering prune
  (conservative: every candidate config must trace AND predict OOM);
- :func:`coldstart_verdict` — the admission controller's zero-trial
  memory gate over all fitting sizes and techniques;
- :func:`task_fits_mesh` / :func:`migration_fits` — the elastic
  replanner's destination checks for degraded meshes and migrations.

All verdicts fail open: unknown capacity, untraceable steps, or any
internal error means "no verdict", never a false prune/reject. The
compile-time ``_fits_memory`` check stays the authoritative backstop.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from saturn_tpu.analysis.diagnostics import AnalysisReport, make

from saturn_tpu.analysis.memlens import liveness
from saturn_tpu.analysis.memlens.liveness import MemoryProfile

log = logging.getLogger("saturn_tpu")

SANCTION_MARKER = "sanctioned-memlens:"

#: env override for per-device HBM capacity in bytes — lets CPU hosts
#: (tests, benches, cold-start planners) reason about a real chip
ENV_CAPACITY = "SATURN_TPU_HBM_BYTES"

#: a point is *infeasible* only when predicted peak > OOM_MARGIN x
#: capacity: static over-prediction within the margin never prunes a
#: point the compiler might still fit
OOM_MARGIN = float(os.environ.get("SATURN_TPU_MEMLENS_PRUNE_MARGIN", "1.15"))

#: the same allocator headroom spmd_base._fits_compiled enforces;
#: predictions between it and capacity get the SAT-M004 warning
HEADROOM_MARGIN = 0.92

#: SAT-M002 fires when one temporary is more than this fraction of the
#: transient peak and at least DOMINANT_FLOOR bytes
DOMINANT_FRACTION = 0.5
DOMINANT_FLOOR = 1 << 24

#: SAT-M005 fires when static and compiled peaks differ by more than
#: this ratio in either direction
DRIFT_RATIO = 2.5


# ----------------------------------------------------------------- sanctions
def _sanction_in_lines(lines: Sequence[str], line: int) -> Optional[str]:
    """Marker on the finding line or the contiguous comment block above
    it (the saturn-tsan/shardflow lookup with the memlens marker)."""
    if 1 <= line <= len(lines):
        text = lines[line - 1]
        if SANCTION_MARKER in text:
            return text.split(SANCTION_MARKER, 1)[1].strip() or "audited"
    ln = line - 1
    while 1 <= ln <= len(lines):
        text = lines[ln - 1]
        if not text.strip().startswith("#"):
            break
        if SANCTION_MARKER in text:
            return text.split(SANCTION_MARKER, 1)[1].strip() or "audited"
        ln -= 1
    return None


def _sanction_at(provenance: str) -> Optional[str]:
    """Resolve ``file:line`` provenance against its source file's
    sanction markers; eqn#-style provenance can never be sanctioned."""
    path, _, line_s = (provenance or "").rpartition(":")
    try:
        line = int(line_s)
    except ValueError:
        return None
    if not path or not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    return _sanction_in_lines(lines, line)


# ------------------------------------------------------------------ capacity
def hbm_capacity_bytes(devices: Optional[Sequence[Any]] = None) -> int:
    """Per-device HBM capacity: the env override first (so CPU hosts can
    model a target chip), then the live device's memory stats; 0 when
    neither knows — all capacity-gated checks then stand down."""
    env = os.environ.get(ENV_CAPACITY)
    if env:
        try:
            return max(int(float(env)), 0)
        except ValueError:
            log.warning("memlens: bad %s=%r ignored", ENV_CAPACITY, env)
    if devices:
        try:
            from saturn_tpu.utils.timing import device_hbm_bytes
            return max(int(device_hbm_bytes(devices[0])), 0)
        except Exception:
            return 0
    return 0


# --------------------------------------------------------------- diagnostics
def analyze_traced(
    traced: Dict[str, Any],
    report: Optional[AnalysisReport] = None,
    capacity_bytes: Optional[int] = None,
    window: int = 1,
) -> Tuple[AnalysisReport, MemoryProfile]:
    """SAT-M001/M002/M003/M004 over one ``trace_step`` result."""
    subject = f"memlens:{traced.get('technique')}@{traced.get('size')}"
    if report is None:
        report = AnalysisReport(subject=subject)
    profile = liveness.analyze(traced, window=window)
    cap = hbm_capacity_bytes() if capacity_bytes is None else int(
        capacity_bytes)
    ctx = {
        "technique": profile.technique,
        "size": profile.size,
        "window": profile.window,
        "peak_bytes": profile.peak_bytes,
        "persistent_bytes": profile.persistent_bytes,
        "transient_peak_bytes": profile.transient_peak_bytes,
    }

    for md in profile.missed_donations:
        report.add(make(
            "SAT-M003", "error",
            f"missed donation: input #{md['invar']} "
            f"({md['dtype']}{md['shape']}, {md['bytes']} bytes) matches an "
            f"output shape/dtype but is not donated — XLA cannot alias it, "
            f"so that buffer is resident twice",
            counterexample={**md, **ctx}, category="memlens",
        ))

    if (profile.largest_temp_bytes >= DOMINANT_FLOOR
            and profile.transient_peak_bytes > 0
            and profile.largest_temp_bytes
            >= DOMINANT_FRACTION * profile.transient_peak_bytes):
        sanction = _sanction_at(profile.largest_temp_where)
        report.add(make(
            "SAT-M002", "info" if sanction else "warning",
            f"peak dominated by one temporary: {profile.largest_temp_bytes} "
            f"bytes is >= {DOMINANT_FRACTION:.0%} of the transient peak "
            f"({profile.transient_peak_bytes} bytes) — a remat or reshard "
            f"of this one value moves the whole peak"
            + (f" [sanctioned: {sanction}]" if sanction else ""),
            counterexample=ctx,
            location=profile.largest_temp_where or None, category="memlens",
        ))

    if cap > 0:
        if profile.peak_bytes > OOM_MARGIN * cap:
            sanction = _sanction_at(profile.largest_temp_where)
            report.add(make(
                "SAT-M001", "info" if sanction else "error",
                f"predicted OOM: static per-device HBM peak "
                f"{profile.peak_bytes} bytes exceeds capacity {cap} bytes "
                f"(margin x{OOM_MARGIN:g}) — deterministically infeasible "
                f"before any compile"
                + (f" [sanctioned: {sanction}]" if sanction else ""),
                counterexample={**ctx, "capacity_bytes": cap},
                location=profile.largest_temp_where or None,
                category="memlens",
            ))
        elif profile.peak_bytes > HEADROOM_MARGIN * cap:
            report.add(make(
                "SAT-M004", "warning",
                f"headroom below margin: predicted peak "
                f"{profile.peak_bytes} bytes is within "
                f"{(1 - HEADROOM_MARGIN):.0%} of capacity {cap} bytes — "
                f"allocator fragmentation can tip this point over",
                counterexample={**ctx, "capacity_bytes": cap},
                category="memlens",
            ))
    return report, profile


def audit_point(
    predicted_bytes: int,
    compiled_bytes: int,
    technique: str,
    size: int,
    k: int = 1,
    ratio: float = DRIFT_RATIO,
):
    """SAT-M005: static-vs-compiled drift audit for one grid point.

    Returns the diagnostic when the two peaks disagree by more than
    ``ratio`` in either direction, else ``None``. Fed for free from
    every compile-time ``_fits_memory`` check."""
    p, c = float(predicted_bytes), float(compiled_bytes)
    if p <= 0 or c <= 0:
        return None
    r = max(p, c) / max(min(p, c), 1.0)
    if r <= ratio:
        return None
    return make(
        "SAT-M005", "warning",
        f"static/compiled drift: memlens predicts {int(p)} bytes but "
        f"memory_analysis() reports {int(c)} bytes for {technique}@{size} "
        f"K={k} ({r:.1f}x apart, ratio gate {ratio:g}) — the liveness "
        f"model is miscalibrated for this workload",
        counterexample={
            "predicted_bytes": int(p), "compiled_bytes": int(c),
            "technique": technique, "size": int(size), "k": int(k),
            "ratio": round(r, 2),
        },
        category="memlens",
    )


# ----------------------------------------------------------------- verdicts
_PRED_CACHE: Dict[Any, Optional[MemoryProfile]] = {}


def pipeline_stash_bytes(
    schedule: str, n_stages: int, n_microbatches: int,
    stage_input_bytes: int,
) -> int:
    """Analytic activation-stash residency of the staged pipeline programs
    (``ops/pipeline.staged_pipeline_loss_and_grads``).

    The schedule's scan carries a depth-``D`` ring of stage-INPUT
    microbatch activations, ``D = min(M, C+1)`` with ``C`` the backward
    launch offset: ``2(S-1)`` for 1F1B — so ``D <= 2S-1``, BOUNDED in the
    microbatch count — and ``M + 2(S-1)`` for the GPipe ordering, where
    every in-flight microbatch stays resident (``D = M``). Backward
    recomputes the stage forward from the stashed input (torchgpipe-style
    checkpointing), so this ring is the dominant schedule-dependent
    liveness term; the generic scan-carry rule in
    :mod:`~saturn_tpu.analysis.memlens.liveness` must reproduce it, and
    the SAT-M regression test (``tests/test_memlens.py``) holds the two
    to each other — a liveness change that stops seeing the stash, or a
    schedule change that silently grows it, breaks the band.
    """
    from saturn_tpu.ops.pipeline import stash_depth

    depth = stash_depth(int(n_stages), int(n_microbatches), str(schedule))
    return int(depth) * int(stage_input_bytes)


def predict_profile(
    tech: Any, task: Any, devices: Sequence[Any],
    config: Optional[Dict[str, Any]] = None, window: int = 1,
) -> Optional[MemoryProfile]:
    """Trace + analyze one grid point; ``None`` when untraceable.

    Memoized per in-process task object — admission and sweeps re-ask
    for the same points many times."""
    key = (
        id(task), getattr(task, "name", ""), getattr(tech, "name", str(tech)),
        len(devices),
        tuple(sorted((k, str(v)) for k, v in (config or {}).items())),
        int(window),
    )
    if key in _PRED_CACHE:
        return _PRED_CACHE[key]
    try:
        traced = tech.trace_step(task, list(devices), dict(config or {}))
        prof: Optional[MemoryProfile] = liveness.analyze(
            traced, window=window)
    except Exception as e:
        log.debug("memlens: %s@%d untraceable: %r",
                  getattr(tech, "name", tech), len(devices), e)
        prof = None
    if len(_PRED_CACHE) > 512:
        _PRED_CACHE.clear()
    _PRED_CACHE[key] = prof
    return prof


def grid_point_infeasible(
    tech: Any, task: Any, devices: Sequence[Any], capacity_bytes: int,
    max_configs: int = 3,
) -> bool:
    """True only when this (technique, task, size) point is statically
    certain not to fit: every candidate config traced AND every predicted
    peak clears the OOM margin. Any unknown keeps the point alive for the
    compile-time backstop."""
    if capacity_bytes <= 0 or not hasattr(tech, "trace_step"):
        return False
    try:
        grid = tech.candidate_configs(task, len(devices))
    except Exception:
        return False
    if not grid or len(grid) > max_configs:
        return False
    for config in grid:
        prof = predict_profile(tech, task, devices, config)
        if prof is None or prof.peak_bytes <= OOM_MARGIN * capacity_bytes:
            return False
    return True


def fused_stack_fits(
    tech: Any,
    task: Any,
    devices: Sequence[Any],
    n_members: int,
    capacity_bytes: Optional[int] = None,
    config: Optional[Dict[str, Any]] = None,
    max_configs: int = 3,
) -> Optional[bool]:
    """Zero-compile residency prior for an N-member fused stack.

    The stacked program shards its leading ``model`` axis across the block's
    devices (``parallel/fused.py``), so each device is resident for
    ``ceil(N / n_dev)`` members' FULL solo state — stacking multiplies the
    single-device peak rather than resharding it. This charges that product
    against the OOM margin and answers the solver's ``fusion_fits`` contract
    (``solver/milp.fusion_priced_groups``):

    - ``False``: the cheapest traceable config's stacked peak statically
      clears the OOM margin — certain not to fit, vetoes the size.
    - ``True``: the stacked peak fits under the margin.
    - ``None``: no safe verdict (capacity unknown, nothing traceable) —
      never prunes; the compile-time backstop decides.

    ``n_dev`` honors the fused program's divisibility walk: the model axis
    only spans a device count that divides N evenly, falling back by powers
    of two (worst case one device carries the whole vmapped stack).
    """
    cap = (hbm_capacity_bytes(devices) if capacity_bytes is None
           else int(capacity_bytes))
    if cap <= 0 or int(n_members) < 2 or not hasattr(tech, "trace_step"):
        return None
    n_dev = max(len(devices), 1)
    while n_dev > 1 and int(n_members) % n_dev != 0:
        n_dev //= 2
    members_per_dev = -(-int(n_members) // n_dev)
    grid: List[Dict[str, Any]]
    if config is not None:
        grid = [dict(config)]
    else:
        try:
            grid = list(tech.candidate_configs(task, 1))
        except Exception:
            return None
        grid = grid[:max_configs]
    peaks: List[int] = []
    for cfg in grid:
        prof = predict_profile(tech, task, list(devices)[:1], cfg)
        if prof is not None:
            peaks.append(int(prof.peak_bytes))
    if not peaks:
        return None
    return bool(members_per_dev * min(peaks) <= OOM_MARGIN * cap)


def coldstart_verdict(
    task: Any, topology: Any,
    techniques: Optional[Dict[str, Any]] = None,
    capacity_bytes: Optional[int] = None,
    max_configs: int = 3,
) -> Optional[Dict[str, Any]]:
    """Admission's zero-trial memory gate over every fitting grid point.

    Returns ``None`` when there is no safe verdict (capacity unknown,
    nothing traceable, or an untraceable point that might still fit);
    otherwise ``{"fits", "min_peak_bytes", "capacity_bytes", "checked"}``
    where ``fits`` is False only when *every* fitting point traced and
    predicted OOM."""
    cap = (hbm_capacity_bytes(getattr(topology, "devices", None))
           if capacity_bytes is None else int(capacity_bytes))
    if cap <= 0:
        return None
    if techniques is None:
        from saturn_tpu.parallel import BUILTIN_TECHNIQUES
        techniques = {
            n: (c() if isinstance(c, type) else c)
            for n, c in BUILTIN_TECHNIQUES.items()
        }
    chip_range = getattr(task, "chip_range", None)
    try:
        sizes = [g for g in topology.valid_sizes()
                 if g <= topology.capacity
                 and (not chip_range or g in chip_range)]
    except Exception:
        return None
    min_peak: Optional[int] = None
    checked = 0
    untraceable = 0
    for g in sorted(sizes, reverse=True):
        try:
            devices = topology.block_devices(topology.blocks(g)[0])
        except Exception:
            untraceable += 1
            continue
        for name in sorted(techniques):
            tech = techniques[name]
            if not hasattr(tech, "trace_step"):
                continue
            try:
                grid = tech.candidate_configs(task, g)
            except Exception:
                untraceable += 1
                continue
            for config in grid[:max_configs]:
                prof = predict_profile(tech, task, devices, config)
                if prof is None:
                    untraceable += 1
                    continue
                checked += 1
                peak = prof.peak_bytes
                min_peak = peak if min_peak is None else min(min_peak, peak)
                if peak <= OOM_MARGIN * cap:
                    return {"fits": True, "min_peak_bytes": int(peak),
                            "capacity_bytes": cap, "checked": checked}
            if len(grid) > max_configs:
                untraceable += 1  # unchecked configs might fit
    if checked == 0 or untraceable > 0:
        return None  # an unknown point might fit: no REJECT on a guess
    return {"fits": False, "min_peak_bytes": int(min_peak or 0),
            "capacity_bytes": cap, "checked": checked}


def task_fits_mesh(task: Any, topology: Any, capacity_bytes: int) -> bool:
    """Replanner keep/evict helper: False only when *every* fitting
    feasible strategy of an already-admitted task is predicted OOM on
    this (possibly degraded) mesh. Fails open on any unknown."""
    if capacity_bytes <= 0:
        return True
    try:
        feas = task.feasible_strategies()
    except Exception:
        return True
    fitting = {g: s for g, s in feas.items() if g <= topology.capacity}
    if not fitting:
        return True  # pure size-fit is the caller's _runnable check
    saw = False
    for g, strat in sorted(fitting.items(), reverse=True):
        tech = getattr(strat, "executor", None)
        if tech is None or not hasattr(tech, "trace_step"):
            return True
        try:
            devices = topology.block_devices(topology.blocks(g)[0])
        except Exception:
            return True
        prof = predict_profile(tech, task, devices,
                               getattr(strat, "params", None) or {})
        if prof is None:
            return True
        saw = True
        if prof.peak_bytes <= OOM_MARGIN * capacity_bytes:
            return True
    return not saw


def migration_fits(
    task: Any, topology: Any, apportionment: int, capacity_bytes: int,
) -> Optional[Dict[str, Any]]:
    """Destination-fit check for one planned migration: the restored
    checkpoint shards (persistent state) plus the steady-state peak must
    fit the destination block. ``None`` = no verdict (fail open)."""
    if capacity_bytes <= 0:
        return None
    try:
        strat = task.feasible_strategies().get(apportionment)
    except Exception:
        return None
    if strat is None or not hasattr(
            getattr(strat, "executor", None), "trace_step"):
        return None
    try:
        devices = topology.block_devices(topology.blocks(apportionment)[0])
    except Exception:
        return None
    prof = predict_profile(strat.executor, task, devices,
                           getattr(strat, "params", None) or {})
    if prof is None:
        return None
    return {
        "fits": prof.peak_bytes <= OOM_MARGIN * capacity_bytes,
        "peak_bytes": int(prof.peak_bytes),
        "restored_shard_bytes": int(prof.persistent_bytes),
        "capacity_bytes": int(capacity_bytes),
    }


# ------------------------------------------------------------ in-tree audit
def audit_intree(
    size: int = 4,
    devices: Optional[Sequence[Any]] = None,
    capacity_bytes: Optional[int] = None,
    window: int = 1,
) -> Tuple[AnalysisReport, Dict[str, MemoryProfile]]:
    """The CLI/gate entry point: SAT-M over every registered in-tree
    technique's traced step at a probe size. Shares shardflow's probe
    tasks; techniques the probes cannot exercise are SAT-M000 warnings,
    not failures."""
    import tempfile

    import jax

    from saturn_tpu.analysis.shardflow.passes import _probe_tasks
    from saturn_tpu.parallel import BUILTIN_TECHNIQUES

    report = AnalysisReport(subject="memlens")
    devs = list(devices) if devices is not None else list(jax.devices())
    probe = min(size, len(devs))
    profiles: Dict[str, MemoryProfile] = {}
    with tempfile.TemporaryDirectory() as tmpdir:
        tasks = _probe_tasks(tmpdir)
        for name, cls in sorted(BUILTIN_TECHNIQUES.items()):
            tech = cls() if isinstance(cls, type) else cls
            if not hasattr(tech, "trace_step"):
                continue  # non-SPMD executor (pipeline): out of scope
            task = tasks["moe" if name == "ep" else "dense"]
            try:
                grid = tech.candidate_configs(task, probe)
                if not grid:
                    continue
                traced = tech.trace_step(task, devs[:probe], grid[0])
                _, profile = analyze_traced(
                    traced, report=report, capacity_bytes=capacity_bytes,
                    window=window,
                )
            except Exception as e:
                report.add(make(
                    "SAT-M000", "warning",
                    f"technique {name!r} could not be traced at size "
                    f"{probe}: {type(e).__name__}: {e}",
                    category="memlens",
                ))
                continue
            profiles[name] = profile
    return report, profiles
