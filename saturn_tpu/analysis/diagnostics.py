"""Structured diagnostics for the static-analysis subsystem.

Every analyzer pass (plan verifier, JAX program lint, journal audit)
emits :class:`Diagnostic` records collected into an
:class:`AnalysisReport` — structured, machine-readable results with
minimal counterexamples, instead of the bare ``RuntimeError`` the
engine's dynamic guard historically raised.

Diagnostic code catalog (the authoritative list; ``docs/analysis.md``
mirrors it for humans):

Plan verifier (``SAT-P*``) — ``plan_verifier.verify_plan``:

========== ========= ===========================================================
code       severity  meaning
========== ========= ===========================================================
SAT-P001   error     device race: blocks overlap with no ordering path or
                     co-schedule edge between the two tasks
SAT-P002   error     dependency cycle through a condensed co-schedule node —
                     the gang launch would deadlock
SAT-P003   error     co-scheduled task depends on its groupmate — an
                     intra-group completion wait deadlocks the shared launcher
SAT-P010   warning   dependency names a task with no assignment in the plan
SAT-P011   warning   co-schedule group names a task with no assignment
SAT-P012   warning   co-schedule group has fewer than two members
SAT-P013   warning   task appears in multiple co-schedule groups (groups merge)
SAT-P020   error     assignment block exceeds the topology's buddy capacity
SAT-P021   error     assignment apportionment differs from its block size
SAT-P022   error     task has no feasible strategy at the assigned apportionment
SAT-P023   warning   co-schedule group members do not share one device block
SAT-P024   warning   co-scheduled task has no host fraction or schedule bubble (> 0)
SAT-P030   error     negative start time or negative runtime
SAT-P031   error     task starts before a task it depends on
SAT-P032   warning   recorded makespan is below the last assignment's end time
SAT-P033   warning   deadline arithmetic: start + runtime overruns the deadline
========== ========= ===========================================================

JAX program lint (``SAT-L*``) — ``jax_lint``:

========== ========= ===========================================================
SAT-L001   warning   retrace risk: novel abstract signature for an already
                     compiled (bundle, K) dispatch key
SAT-L002   error     implicit host sync inside the interval hot loop outside a
                     ``lint: sanctioned-host-sync`` marker
SAT-L003   error     donated window stack referenced after the donating dispatch
SAT-L010   error     PartitionSpec references a mesh axis the mesh doesn't have
SAT-L011   warning   sharded dimension not divisible by its mesh axes (error
                     under ``strict``)
SAT-L012   error     PartitionSpec rank exceeds the tensor rank
========== ========= ===========================================================

Journal audit (``SAT-J*``) — ``plan_verifier.audit_journal``:

========== ========= ===========================================================
SAT-J001   error     replayed plan_commit record fails static verification
                     (quarantined, never adopted)
SAT-J002   error     journal unreadable / plan_commit payload undecodable
========== ========= ===========================================================

Concurrency pass (``SAT-C*``) — ``concurrency.static_pass`` (saturn-tsan):

========== ========= ===========================================================
SAT-C000   error     source file failed to parse (nothing else checked)
SAT-C001   error     lock-order inversion: cycle in the static acquisition
                     graph (potential deadlock), or re-acquiring a held
                     non-reentrant lock (self-deadlock); the counterexample
                     is the minimal cycle with one witness site per edge
SAT-C002   error     shared mutable state (class attribute, closure
                     variable, or lock-managed module global) touched with
                     no common guard across its mutation sites
SAT-C003   error     blocking call — fsync, sleep, Thread.join, blocking
                     queue get/put, Event.wait — executed while holding a
                     lock (directly or via a resolvable callee)
SAT-C004   error     Condition.wait() outside a retest loop (lost-wakeup /
                     spurious-wakeup hazard)
========== ========= ===========================================================

A ``# sanctioned-unlocked: <reason>`` comment on the finding line, in the
contiguous comment block above it, or above the enclosing ``def`` (which
sanctions the whole function) downgrades a SAT-C finding to ``info`` —
audited cases stay visible but do not gate.

Sharding-propagation pass (``SAT-X*``) — ``analysis.shardflow``
(saturn-shardflow):

========== ========= ===========================================================
SAT-X000   error     technique/source untraceable or unparseable (warning when
                     a single technique fails to trace; error for source parse)
SAT-X001   error     implicit reshard: an equation's operands disagree on the
                     mesh axes of a shared dimension inside the fused hot loop
SAT-X002   error     gather-to-replicated / single-writer funnel: a full-tensor
                     ``process_allgather`` or device_put-to-replicated in
                     source (the ``utils/checkpoint.py`` pattern)
SAT-X003   warning   fully-replicated intermediate above the size threshold
                     (default 64 MiB) — per-chip HBM spent on identical bytes
SAT-X004   error     cross-slice collective inside an inner ``scan``: a
                     DCN-crossing mesh axis appears in a collective at scan
                     depth >= 1, multiplying DCN latency by the trip count
SAT-X005   warning   static communication estimate vs. profiled runtime
                     disagreement above 35% — the cold-start prior is
                     miscalibrated for this workload
========== ========= ===========================================================

A ``# sanctioned-shardflow: <reason>`` comment on the finding line or in
the contiguous comment block above it downgrades a SAT-X finding to
``info`` — sanctions explain, they never silence.

Peak-liveness pass (``SAT-M*``) — ``analysis.memlens`` (saturn-memlens):

========== ========= ===========================================================
SAT-M000   warning   technique untraceable at the probe size (nothing else
                     checked for it)
SAT-M001   error     predicted OOM: the static per-device HBM peak exceeds
                     capacity by the prune margin — deterministic
                     infeasibility before any compile
SAT-M002   warning   peak dominated by one oversized temporary (>= 50% of the
                     transient peak and >= 16 MiB) — one remat/reshard moves
                     the whole peak
SAT-M003   error     missed donation: a non-donated input's shape/dtype
                     matches an output, so XLA cannot alias it and the buffer
                     is resident twice
SAT-M004   warning   headroom below the allocator margin (peak within 8% of
                     capacity but under it) — fragmentation risk
SAT-M005   warning   static peak vs ``compiled.memory_analysis()`` drift
                     beyond the calibration ratio — the liveness model is
                     miscalibrated for this workload
========== ========= ===========================================================

A ``# sanctioned-memlens: <reason>`` comment at a finding's file:line
provenance (or the contiguous comment block above it) downgrades a SAT-M
finding to ``info`` — sanctions explain, they never silence; eqn#-style
provenance cannot be sanctioned.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

#: Version of the analyzer's rule set + diagnostic schema. Bumped whenever a
#: check is added/removed or a code changes meaning. Mixed into the profile
#: and AOT cache fingerprints (``utils/profile_cache.py``,
#: ``utils/aot_cache.py``) so a plan repaired under one rule set never reads
#: back cache entries recorded under another. 2 -> 3: saturn-shardflow
#: (SAT-X sharding-propagation pass + cold-start prior). 3 -> 4:
#: saturn-memlens (SAT-M peak-liveness pass + zero-compile feasibility).
SCHEMA_VERSION = 4

#: severity levels, weakest to strongest
SEVERITIES = ("info", "warning", "error")


@dataclass(frozen=True)
class Diagnostic:
    """One analyzer finding.

    ``counterexample`` is the minimal witness — e.g. the two task names and
    their half-open device ranges for a race, or the cycle's node list —
    small JSON-serializable data, never whole plans.  ``location`` is a
    ``file:line`` string for source-level lints (sharding rules, hot-loop
    host syncs) and ``None`` for plan-level checks.
    """

    code: str
    severity: str
    message: str
    counterexample: Optional[Dict[str, Any]] = None
    location: Optional[str] = None
    category: str = "plan"

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "code": self.code,
            "severity": self.severity,
            "message": self.message,
            "category": self.category,
        }
        if self.counterexample is not None:
            out["counterexample"] = self.counterexample
        if self.location is not None:
            out["location"] = self.location
        return out


@dataclass
class AnalysisReport:
    """All diagnostics from one analyzer run over one subject."""

    subject: str = "plan"
    diagnostics: List[Diagnostic] = field(default_factory=list)

    def add(self, diag: Diagnostic) -> None:
        self.diagnostics.append(diag)

    def extend(self, diags: List[Diagnostic]) -> None:
        self.diagnostics.extend(diags)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warning"]

    @property
    def ok(self) -> bool:
        """True when no error-severity diagnostic was emitted."""
        return not self.errors

    def codes(self) -> List[str]:
        return [d.code for d in self.diagnostics]

    def filter(self, category: Optional[str] = None) -> List[Diagnostic]:
        if category is None:
            return list(self.diagnostics)
        return [d for d in self.diagnostics if d.category == category]

    def summary(self) -> str:
        n_e, n_w = len(self.errors), len(self.warnings)
        status = "FAIL" if n_e else "ok"
        return (f"{self.subject}: {status} "
                f"({n_e} error(s), {n_w} warning(s))")

    def to_json(self) -> Dict[str, Any]:
        return {
            "schema": SCHEMA_VERSION,
            "subject": self.subject,
            "ok": self.ok,
            "diagnostics": [d.to_json() for d in self.diagnostics],
        }

    def render(self) -> str:
        """Human-readable multi-line rendering (CLI output)."""
        lines = [self.summary()]
        for d in self.diagnostics:
            loc = f" [{d.location}]" if d.location else ""
            lines.append(f"  {d.code} {d.severity}{loc}: {d.message}")
            if d.counterexample:
                lines.append(
                    "      counterexample: "
                    + json.dumps(d.counterexample, sort_keys=True, default=str)
                )
        return "\n".join(lines)


class PlanVerificationError(RuntimeError):
    """A gated plan path received a plan the static verifier rejects.

    Subclasses ``RuntimeError`` so every existing caller that handled the
    engine's dynamic-guard raise keeps working unchanged; carries the full
    report for callers that quarantine rather than crash.
    """

    def __init__(self, report: AnalysisReport, source: str = "plan") -> None:
        self.report = report
        self.source = source
        first = report.errors[0] if report.errors else None
        detail = first.message if first else "verification failed"
        super().__init__(
            f"static plan verification failed for {source}: {detail} "
            f"({len(report.errors)} error(s); codes: "
            f"{sorted({d.code for d in report.errors})})"
        )


def make(code: str, severity: str, message: str,
         counterexample: Optional[Dict[str, Any]] = None,
         location: Optional[str] = None,
         category: str = "plan") -> Diagnostic:
    """Tiny constructor shim keeping call sites one line."""
    if severity not in SEVERITIES:
        raise ValueError(f"unknown severity {severity!r}")
    return Diagnostic(code=code, severity=severity, message=message,
                      counterexample=counterexample, location=location,
                      category=category)
