"""Journal replay → typed recovery state + checkpoint reconciliation.

Two consumers replay the same write-ahead journal:

- the online service (``SaturnService(durability_dir=...)``) rebuilds its
  job registry: every job ever submitted, its last durable lifecycle state,
  retry/requeue accounting, per-job realized iterations, and the last
  committed plan (which warm-starts the first post-restart re-solve);
- the batch orchestrator (``orchestrate(resume_dir=...)``) rebuilds
  per-task progress so a restarted batch only runs the iterations that were
  never durably recorded.

The recovery state machine is intentionally conservative: only **committed**
journal records count (recovery runs after :func:`journal.recover` has
rolled torn tails back to the last durable cut), so iterations executed but
not yet committed are re-run — re-running work is safe, double-counting it
is not. See ``docs/architecture.md`` ("Crash recovery & durability") for
the full record schema and operator runbook.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from saturn_tpu.durability import journal as jmod

logger = logging.getLogger("saturn_tpu")

#: Lifecycle states that need no resurrection on restart.
_TERMINAL = frozenset({"DONE", "FAILED", "EVICTED"})


@dataclass
class JobReplay:
    """One job's reconstructed durable state."""

    job_id: str
    task: str
    priority: float = 0.0
    deadline_s: Optional[float] = None
    max_retries: int = 1
    total_batches: int = 0         # as submitted (the original budget)
    realized: int = 0              # durably journaled completed iterations
    state: str = "QUEUED"
    attempts: int = 0
    requeues: int = 0
    error: Optional[str] = None
    spec: Optional[dict] = None    # caller-supplied rebuild spec
    dedup_key: Optional[str] = None  # gateway idempotency key (if any)
    tenant: Optional[str] = None   # billing/fairness principal (if any)

    @property
    def terminal(self) -> bool:
        return self.state in _TERMINAL

    @property
    def remaining(self) -> int:
        return max(0, self.total_batches - self.realized)


@dataclass
class ServiceRecovery:
    """Everything the service needs to resume from the durable cut."""

    jobs: Dict[str, JobReplay] = field(default_factory=dict)
    plan: Optional[dict] = None          # last committed plan (to_json form)
    checkpoints: Dict[str, List[str]] = field(default_factory=dict)
    last_seq: int = 0
    n_records: int = 0
    incarnations: int = 0
    # Health-guardian state (task name keyed): quarantined dataset indices
    # and co-schedule detachments, replayed from health_* records.
    quarantined: Dict[str, List[int]] = field(default_factory=dict)
    detached: List[str] = field(default_factory=list)
    #: Gateway idempotency table: dedup_key -> job_id, folded from
    #: ``job_submitted`` records (the key rides the submission record, so a
    #: key and its admission are durable atomically). The gateway seeds its
    #: in-memory dedup map from this on restart — a client retrying a
    #: submit whose ACK died with the previous incarnation gets the
    #: original job id back, exactly-once across restarts.
    dedup: Dict[str, str] = field(default_factory=dict)
    #: Replica-lease fencing state, folded from ``gateway_lease`` records:
    #: the max journaled epoch (and its owner). A restarted control plane
    #: seeds its ReplicaLease from this so fenced epochs are never reused.
    lease_epoch: int = 0
    lease_owner: Optional[str] = None
    #: Full journaled acquisition history [(epoch, owner, prev_owner)] in
    #: replay order — the operator CLI's failover audit trail.
    lease_history: List[Any] = field(default_factory=list)
    #: tenant -> cumulative chip-seconds burned, folded from
    #: ``tenant_charge`` records; TenantLedger.restore() re-seats budgets.
    tenant_charges: Dict[str, float] = field(default_factory=dict)
    #: compile_ahead event status -> count (requested/ready/error/hit/miss)
    #: — the durable half of the compile-ahead hit/miss ledger.
    compile_ahead: Dict[str, int] = field(default_factory=dict)
    #: Defrag-wave two-phase migration ledger: (wave, task) -> intent record
    #: for every ``migration_intent`` that never saw a ``migration_done`` /
    #: ``migration_rollback``. The restarting service closes each exactly
    #: once: resume (done) iff a ``ckpt_published`` for the task landed
    #: *after* the intent, else roll back.
    pending_migrations: Dict[Any, dict] = field(default_factory=dict)
    migrations_done: int = 0
    migrations_rolled_back: int = 0
    #: Grow-path counters folded from grow_event / backlog_drain records.
    grow_events: int = 0
    backlog_drained: int = 0
    #: Highest wave sequence number seen in any wave-bearing record
    #: (``wave-<interval>-<seq>``): the restarting coordinator seeds its
    #: sequence past this so wave ids never collide across incarnations
    #: (the interval counter alone restarts from zero). Folded from
    #: ``migration_intent`` too, not just the ``defrag_wave`` summary —
    #: a kill mid-wave dies before the summary lands.
    defrag_waves: int = 0
    #: job_id -> latest job_deferred record (left for visibility even after
    #: the job admits; admission drops pool entries live, the journal view
    #: keeps history).
    deferred: Dict[str, dict] = field(default_factory=dict)
    #: task -> seq of its newest ckpt_published record (resume/rollback
    #: arbitration for pending migrations).
    last_ckpt_seq: Dict[str, int] = field(default_factory=dict)

    def live_jobs(self) -> List[JobReplay]:
        return [j for j in self.jobs.values() if not j.terminal]

    def resolve_pending_migrations(self):
        """Split unclosed migration intents into (resume, rollback) lists.

        A move whose victim's checkpoint was durably published *after* the
        intent record is safe to close as done — the state the move needed
        on disk is there; everything else rolls back (device-resident live
        state died with the process either way, so rollback is a pure
        journal closure: the next restore reads the last checkpoint). The
        caller journals one ``migration_done`` / ``migration_rollback``
        per entry — exactly once, because closed intents never re-enter
        ``pending_migrations`` on the next replay.
        """
        resume, rollback = [], []
        for (wave, task), rec in sorted(self.pending_migrations.items()):
            if self.last_ckpt_seq.get(task, -1) > rec["seq"]:
                resume.append(rec)
            else:
                rollback.append(rec)
        return resume, rollback


@dataclass
class BatchRecovery:
    """Per-task durable progress for ``orchestrate(resume_dir=...)``."""

    progress: Dict[str, int] = field(default_factory=dict)
    completed: List[str] = field(default_factory=list)
    failed: Dict[str, str] = field(default_factory=dict)
    plan: Optional[dict] = None
    checkpoints: Dict[str, List[str]] = field(default_factory=dict)
    last_seq: int = 0
    n_records: int = 0
    quarantined: Dict[str, List[int]] = field(default_factory=dict)
    detached: List[str] = field(default_factory=list)


def fold_health_record(
    kind: str,
    d: Dict[str, Any],
    quarantined: Dict[str, List[int]],
    detached: List[str],
) -> bool:
    """Fold one ``health_*`` journal record into recovery state.

    Shared by both replay paths (and the analysis CLI) so quarantine /
    detach semantics cannot drift: ``health_quarantine`` unions dataset
    indices into the task's sorted skip-list, ``health_unquarantine`` with
    ``indices=None`` clears the task entirely (else subtracts, dropping the
    key when empty), ``health_detach`` marks the task excluded from future
    co-schedule groups. Returns True when the record was a health record.
    """
    task = d.get("task", "")
    if kind == "health_quarantine":
        cur = set(quarantined.get(task, ()))
        cur.update(int(i) for i in d.get("indices", ()))
        quarantined[task] = sorted(cur)
    elif kind == "health_unquarantine":
        indices = d.get("indices")
        if indices is None:
            quarantined.pop(task, None)
        else:
            cur = set(quarantined.get(task, ()))
            cur.difference_update(int(i) for i in indices)
            if cur:
                quarantined[task] = sorted(cur)
            else:
                quarantined.pop(task, None)
    elif kind == "health_detach":
        if task not in detached:
            detached.append(task)
    else:
        return False
    return True


def _wave_seq(wave_id: str) -> int:
    """Trailing sequence number of a ``wave-<interval>-<seq>`` id (0 when
    the id doesn't parse — foreign or hand-written journals stay legible)."""
    try:
        return int(str(wave_id).rsplit("-", 1)[-1])
    except (TypeError, ValueError):
        return 0


def replay_service_state(root: str) -> ServiceRecovery:
    """Fold the durable journal into the service's recovery state.

    Pure read — call :func:`journal.recover` first so torn tails are
    already rolled back. Handles multi-incarnation journals: a job
    submitted in incarnation 1, recovered in 2 and finished in 3 folds into
    one :class:`JobReplay` keyed by its stable ``job_id``.
    """
    state = ServiceRecovery()
    for rec in jmod.replay(root):
        kind, d = rec["kind"], rec.get("data", {})
        state.n_records += 1
        state.last_seq = rec["seq"]
        if kind == "segment_open":
            continue
        if kind == "recovery":
            state.incarnations += 1
        elif kind == "job_submitted":
            state.jobs[d["job"]] = JobReplay(
                job_id=d["job"],
                task=d["task"],
                priority=float(d.get("priority", 0.0)),
                deadline_s=d.get("deadline_s"),
                max_retries=int(d.get("max_retries", 1)),
                total_batches=int(d.get("total_batches") or 0),
                spec=d.get("spec"),
                dedup_key=d.get("dedup_key"),
                tenant=d.get("tenant"),
            )
            if d.get("dedup_key") is not None:
                state.dedup[d["dedup_key"]] = d["job"]
        elif kind == "job_recovered":
            j = state.jobs.get(d["job"])
            if j is not None:
                j.state = "QUEUED"
                j.requeues = int(d.get("requeues", j.requeues))
        elif kind == "job_state":
            j = state.jobs.get(d["job"])
            if j is not None:
                j.state = d["state"]
                j.attempts = int(d.get("attempts", j.attempts))
                j.requeues = int(d.get("requeues", j.requeues))
                if d.get("error") is not None:
                    j.error = d["error"]
        elif kind == "task_progress":
            j = state.jobs.get(d.get("job", ""))
            if j is not None:
                j.realized += int(d.get("batches", 0))
        elif kind == "plan_commit":
            if d.get("plan") is not None:
                state.plan = d["plan"]
        elif kind == "ckpt_published":
            task = d.get("task") or d.get("path", "")
            state.checkpoints.setdefault(task, []).append(d.get("path", ""))
            state.last_ckpt_seq[task] = rec["seq"]
        elif kind == "migration_intent":
            key = (d.get("wave", ""), d.get("task", ""))
            state.pending_migrations[key] = dict(d, seq=rec["seq"])
            state.defrag_waves = max(state.defrag_waves,
                                     _wave_seq(d.get("wave", "")))
        elif kind == "migration_done":
            state.pending_migrations.pop(
                (d.get("wave", ""), d.get("task", "")), None)
            state.migrations_done += 1
        elif kind == "migration_rollback":
            state.pending_migrations.pop(
                (d.get("wave", ""), d.get("task", "")), None)
            state.migrations_rolled_back += 1
        elif kind == "grow_event":
            state.grow_events += 1
        elif kind == "backlog_drain":
            state.backlog_drained += len(d.get("jobs", ()))
        elif kind == "job_deferred":
            state.deferred[d.get("job", "")] = dict(d)
        elif kind == "defrag_wave":
            # The per-move ledger above is authoritative for closure; the
            # summary only advances the cross-incarnation wave sequence.
            state.defrag_waves = max(state.defrag_waves,
                                     _wave_seq(d.get("wave", "")))
        elif kind == "gateway_lease":
            epoch = int(d.get("epoch", 0))
            owner = d.get("owner")
            state.lease_history.append(
                (epoch, owner, d.get("prev_owner"))
            )
            # Max, not last: two replicas racing a takeover may journal out
            # of order (the record is written outside the lease lock), and
            # only the highest epoch ever fences anything.
            if epoch > state.lease_epoch:
                state.lease_epoch = epoch
                state.lease_owner = owner
        elif kind == "tenant_charge":
            t = d.get("tenant") or "default"
            state.tenant_charges[t] = (
                state.tenant_charges.get(t, 0.0) + float(d.get("chip_s", 0.0))
            )
        elif kind == "compile_ahead":
            s = d.get("status", "unknown")
            state.compile_ahead[s] = state.compile_ahead.get(s, 0) + 1
        else:
            fold_health_record(kind, d, state.quarantined, state.detached)
    return state


def replay_batch_state(root: str) -> BatchRecovery:
    """Fold the journal into the batch orchestrator's per-task progress."""
    state = BatchRecovery()
    for rec in jmod.replay(root):
        kind, d = rec["kind"], rec.get("data", {})
        state.n_records += 1
        state.last_seq = rec["seq"]
        if kind == "task_progress":
            name = d.get("task", "")
            state.progress[name] = state.progress.get(name, 0) + int(
                d.get("batches", 0)
            )
        elif kind == "task_completed":
            if d["task"] not in state.completed:
                state.completed.append(d["task"])
        elif kind == "task_failed":
            state.failed[d["task"]] = d.get("error", "journaled failure")
        elif kind == "plan_commit":
            if d.get("plan") is not None:
                state.plan = d["plan"]
        elif kind == "ckpt_published":
            task = d.get("task") or d.get("path", "")
            state.checkpoints.setdefault(task, []).append(d.get("path", ""))
        else:
            fold_health_record(kind, d, state.quarantined, state.detached)
    return state


def reconcile_checkpoints(
    checkpoints: Dict[str, List[str]],
) -> Dict[str, Optional[str]]:
    """Verify journaled checkpoint publications against the disk.

    For each task, walk its publications newest-first: a checkpoint that is
    missing is skipped, one that fails verification is quarantined to
    ``*.corrupt``, and the newest *valid* one wins — recovery falls back to
    the previous durable publication rather than dying on a torn write.
    ``checkpoint.verify`` covers both formats: for a sharded manifest it
    checks the manifest checksum AND that every referenced shard file
    exists, is a sound archive, and together the shards cover each leaf
    (a partial shard set from a mid-write crash fails here); for a legacy
    single-file archive it checks the zip CRCs. Returns
    ``{task: authoritative path or None}``.
    """
    import os

    from saturn_tpu.utils import checkpoint as ckpt

    out: Dict[str, Optional[str]] = {}
    for task, paths in checkpoints.items():
        out[task] = None
        for path in reversed(paths):
            if not os.path.exists(path):
                continue
            if ckpt.verify(path):
                out[task] = path
                break
            quarantined = ckpt.quarantine(path)
            logger.warning(
                "recovery: checkpoint %s for %s failed verification — "
                "quarantined to %s, falling back to the previous "
                "publication", path, task, quarantined,
            )
    return out


class RecoveredTaskStub:
    """Placeholder task for a journaled job that needs no execution (it is
    already terminal) — keeps the queue registry's duck-typed contract
    (``.name`` / ``.total_batches``) without a rebuildable model closure."""

    def __init__(self, name: str, total_batches: int = 0):
        self.name = name
        self.total_batches = total_batches
        self.strategies: Dict[int, Any] = {}

    def feasible_strategies(self) -> Dict[int, Any]:
        return {}


def build_restore_records(
    state: ServiceRecovery,
    task_provider: Optional[Callable[[dict], Any]],
) -> List:
    """Turn replayed jobs into queue-restorable :class:`JobRecord`s.

    Live (non-terminal) jobs are resurrected through ``task_provider``,
    which receives the job's durable spec (including ``remaining_batches``,
    the original budget minus durably journaled iterations) and returns a
    fresh task object; the record re-enters the queue as QUEUED and
    re-admits warm through the profile cache (zero trials for a previously
    profiled fingerprint). Terminal jobs are restored as inert registry
    entries so ``status``/``wait`` keep answering and their names stay
    released for reuse. Raises if live jobs exist but no provider does —
    silently dropping admitted work is the exact failure this package
    exists to prevent.
    """
    import time

    from saturn_tpu.service.queue import JobRecord, JobRequest, JobState

    live = state.live_jobs()
    if live and task_provider is None:
        raise RuntimeError(
            f"journal holds {len(live)} live job(s) "
            f"({', '.join(j.job_id for j in live)}) but no task_provider was "
            "given — pass SaturnService(task_provider=...) so recovery can "
            "rebuild their task objects"
        )
    out: List = []
    now = time.monotonic()
    for j in state.jobs.values():
        # A live job whose every iteration is durably journaled already
        # finished — only the terminal verdict died with the crash. Restore
        # it DONE instead of re-queueing a zero-batch task (the caller
        # re-journals the verdict so the next incarnation replays it
        # directly).
        finished = (
            not j.terminal and j.total_batches > 0
            and j.realized >= j.total_batches
        )
        if j.terminal or finished:
            req = JobRequest(
                task=RecoveredTaskStub(j.task, j.total_batches),
                priority=j.priority, deadline_s=j.deadline_s,
                max_retries=j.max_retries, spec=j.spec,
                dedup_key=j.dedup_key, tenant=j.tenant,
            )
            rec = JobRecord(
                job_id=j.job_id, request=req,
                state=JobState.DONE if finished else JobState(j.state),
                submitted_at=now, finished_at=now, attempts=j.attempts,
                requeues=j.requeues, error=j.error,
            )
            out.append(rec)
            continue
        task = task_provider({
            "job_id": j.job_id,
            "task": j.task,
            "total_batches": j.total_batches,
            "remaining_batches": j.remaining,
            "priority": j.priority,
            "deadline_s": j.deadline_s,
            "max_retries": j.max_retries,
            "spec": j.spec,
            "tenant": j.tenant,
        })
        if getattr(task, "name", None) != j.task:
            raise ValueError(
                f"task_provider returned task named "
                f"{getattr(task, 'name', None)!r} for journaled job "
                f"{j.job_id} ({j.task!r}) — names must match"
            )
        # The journal is authoritative for progress: durably completed
        # iterations are never re-run.
        task.total_batches = j.remaining
        req = JobRequest(
            task=task, priority=j.priority, deadline_s=j.deadline_s,
            max_retries=j.max_retries, spec=j.spec,
            dedup_key=j.dedup_key, tenant=j.tenant,
        )
        rec = JobRecord(
            job_id=j.job_id, request=req, state=JobState.QUEUED,
            submitted_at=now,
            deadline_at=(now + j.deadline_s
                         if j.deadline_s is not None else None),
            attempts=j.attempts,
            requeues=j.requeues + (1 if j.state in ("RUNNING", "SCHEDULED")
                                   else 0),
        )
        out.append(rec)
    return out


def audit_plan_commits(root: str, topology: Any = None,
                       tasks: Optional[List] = None):
    """Static-verification audit of every ``plan_commit`` in the journal.

    Thin durability-side entry into the analyzer
    (:func:`saturn_tpu.analysis.plan_verifier.audit_journal`): recovery
    callers and the ``python -m saturn_tpu.analysis journal`` CLI share one
    implementation. Returns the :class:`AnalysisReport`; adopting a replayed
    plan that this audit rejects is the service-side quarantine bug this
    hook exists to prevent (``SaturnService._recover_from``).
    """
    from saturn_tpu.analysis import plan_verifier

    return plan_verifier.audit_journal(root, topology=topology, tasks=tasks)
