"""Crash-safe durability: write-ahead journal + restart recovery.

PR 2/3 made the *fleet* elastic (slice preemptions replan, preempted jobs
requeue) but left the orchestrator/service process itself as a single point
of total state loss: queue, admission outcomes, realized iterations and the
live plan all lived in memory. On real TPU fleets the controller host is
preempted as often as the slices are, so this package gives the control
plane the same treatment the data-system Saturn (arXiv:2309.01226) gives
re-derivable state — cache what can be recomputed (profiles, compiled
programs), write-ahead-log what cannot (state transitions):

- :mod:`saturn_tpu.durability.journal` — append-only, CRC-checksummed JSONL
  write-ahead journal with monotonic sequence numbers, fsync'd group
  commits and atomic segment rotation. Torn/corrupt trailing records are
  detected on open, quarantined to ``*.corrupt`` sidecars, and the log is
  rolled back to the last durable cut.
- :mod:`saturn_tpu.durability.recovery` — replays the journal into typed
  recovery state: the online service's job registry (admissions, lifecycle
  edges, per-job realized iterations, last committed plan) or the batch
  orchestrator's per-task progress. Published checkpoints are reconciled
  against disk (corrupt ones quarantined, falling back to the previous
  publication).

The kill-replay crash harness that drives this under test lives in
:mod:`saturn_tpu.resilience.crash`; the wiring into the service loop and
``orchestrate(resume_dir=...)`` is documented in ``docs/architecture.md``
("Crash recovery & durability").
"""

from saturn_tpu.durability.journal import (
    Journal,
    JournalCorruptError,
    recover,
    replay,
)
from saturn_tpu.durability.recovery import (
    BatchRecovery,
    JobReplay,
    ServiceRecovery,
    build_restore_records,
    reconcile_checkpoints,
    replay_batch_state,
    replay_service_state,
)

__all__ = [
    "Journal",
    "JournalCorruptError",
    "recover",
    "replay",
    "BatchRecovery",
    "JobReplay",
    "ServiceRecovery",
    "build_restore_records",
    "reconcile_checkpoints",
    "replay_batch_state",
    "replay_service_state",
]
