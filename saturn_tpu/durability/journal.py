"""Append-only, checksummed write-ahead journal (JSONL segments).

Record format — one JSON object per line::

    {"crc": "9a3f01c2", "data": {...}, "kind": "job_state",
     "seq": 412, "ts": 1754390400.123456}

``seq`` is strictly monotonic across segments AND process incarnations (a
restarted writer continues from the last durable sequence number), ``crc``
is the CRC32 of the record serialized without its ``crc`` field (sorted
keys, compact separators — the exact bytes :func:`_encode` produces, which
``json.loads``/``json.dumps`` round-trips deterministically). A record that
fails either check marks the durable cut: everything from that byte offset
on is a torn tail (the writer died mid-append) or corruption, and
:func:`recover` quarantines it to a ``*.corrupt`` sidecar instead of
letting replay raise.

Write path:

- ``append()`` buffers an encoded record (thread-safe: engine launcher
  threads journal per-task progress while the loop thread owns commits).
- ``commit()`` is a **group commit**: one ``write`` + one ``fsync`` for
  every record buffered since the last commit. Anything appended but not
  yet committed dies with the process — by design, the durability contract
  is "committed means survives SIGKILL", nothing weaker or stronger.
- Segments rotate atomically once they pass ``segment_max_bytes``: the new
  segment is created as a ``.tmp`` with its ``segment_open`` header record
  already fsync'd, then renamed into place and the directory fsync'd. A
  crash mid-rotation leaves only a ``.tmp`` (ignored and deleted by
  recovery) — never a half-initialized live segment.

Crash-harness hook: ``barrier(point, **ctx)`` fires the injected callback
at every durability-critical edge (``pre-commit``, ``mid-fsync``,
``post-commit``, ``pre-rotate``, ``post-rename``) plus any caller-defined
points (the service loop adds ``mid-interval`` / ``post-checkpoint``). The
kill-replay harness (:mod:`saturn_tpu.resilience.crash`) raises a simulated
SIGKILL from these callbacks — including tearing the tail of a mid-fsync
write to model a lost page cache.
"""

from __future__ import annotations

import json
import logging
import os
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from saturn_tpu.analysis import concurrency as tsan
from saturn_tpu.analysis.concurrency import sched_point

logger = logging.getLogger("saturn_tpu")

_SEGMENT_PREFIX = "wal-"
_SEGMENT_SUFFIX = ".jsonl"
_JSON_OPTS = {"sort_keys": True, "separators": (",", ":"), "default": str}


class JournalCorruptError(RuntimeError):
    """A journal record failed its CRC/sequence check where recovery cannot
    roll it back (i.e. the caller asked for strict replay)."""


def _segment_path(root: str, index: int) -> str:
    return os.path.join(root, f"{_SEGMENT_PREFIX}{index:06d}{_SEGMENT_SUFFIX}")


def _segment_index(name: str) -> Optional[int]:
    if not (name.startswith(_SEGMENT_PREFIX) and name.endswith(_SEGMENT_SUFFIX)):
        return None
    try:
        return int(name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)])
    except ValueError:
        return None


def _crc_of(body: Dict[str, Any]) -> str:
    return format(
        zlib.crc32(json.dumps(body, **_JSON_OPTS).encode("utf-8")), "08x"
    )


def _verify_line(line: str, prev_seq: Optional[int]) -> Optional[Dict[str, Any]]:
    """Parse + checksum + sequence-check one record line; None = corrupt."""
    try:
        rec = json.loads(line)
    except (json.JSONDecodeError, ValueError):
        return None
    if not isinstance(rec, dict) or "crc" not in rec or "seq" not in rec:
        return None
    claimed = rec.pop("crc")
    if _crc_of(rec) != claimed:
        return None
    if prev_seq is not None and rec["seq"] != prev_seq + 1:
        return None  # a gap or repeat means an earlier durable cut was lost
    return rec


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without directory fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _quarantine_bytes(seg_path: str, offset: int) -> str:
    """Move everything from ``offset`` on into a ``.corrupt`` sidecar and
    truncate the live segment back to the durable cut.

    The sidecar gets the same atomic tmp+rename+dir-fsync treatment as
    segment rotation (``Journal._open_segment``): a crash DURING recovery
    must never leave a half-written ``.corrupt`` file that a later recovery
    (or an operator reading the incident) mistakes for the full quarantined
    tail — a ``.corrupt.tmp`` is deleted on the next pass like any other
    ``.tmp``.
    """
    sidecar = seg_path + ".corrupt"
    n = 1
    while os.path.exists(sidecar):
        n += 1
        sidecar = f"{seg_path}.corrupt.{n}"
    with open(seg_path, "rb") as f:
        f.seek(offset)
        bad = f.read()
    tmp = sidecar + ".tmp"
    with open(tmp, "wb") as f:
        f.write(bad)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, sidecar)
    _fsync_dir(os.path.dirname(sidecar) or ".")
    with open(seg_path, "r+b") as f:
        f.truncate(offset)
        f.flush()
        os.fsync(f.fileno())
    return sidecar


def recover(root: str) -> Dict[str, Any]:
    """Scan the journal directory, quarantine anything past the last durable
    cut, and report what survived.

    Mutating and idempotent: half-rotated ``.tmp`` segments are deleted,
    a torn/corrupt tail is moved to ``<segment>.corrupt`` (the live segment
    is truncated back to the cut), and — because a mid-file corruption
    invalidates everything after it — whole later segments are quarantined
    by rename. Returns ``{"segments", "records", "last_seq",
    "quarantined": [sidecar paths]}``.
    """
    report: Dict[str, Any] = {
        "segments": 0, "records": 0, "last_seq": None, "quarantined": [],
    }
    if not os.path.isdir(root):
        return report
    names = sorted(os.listdir(root))
    for name in names:
        if name.endswith(".tmp"):
            os.unlink(os.path.join(root, name))  # crashed mid-rotation
    segments = sorted(
        (idx, name) for name in names
        if (idx := _segment_index(name)) is not None
    )
    prev_seq: Optional[int] = None
    cut_found = False
    for idx, name in segments:
        seg_path = os.path.join(root, name)
        if cut_found:
            # corruption in an earlier segment: everything after the durable
            # cut rolls back, even structurally-valid later segments
            sidecar = seg_path + ".corrupt"
            n = 1
            while os.path.exists(sidecar):
                n += 1
                sidecar = f"{seg_path}.corrupt.{n}"
            os.replace(seg_path, sidecar)
            # Make the rename durable like rotation does: a crash here must
            # not resurrect the quarantined segment under its live name.
            _fsync_dir(root)
            report["quarantined"].append(sidecar)
            continue
        report["segments"] += 1
        with open(seg_path, "rb") as f:
            raw = f.read()
        offset = 0
        while offset < len(raw):
            nl = raw.find(b"\n", offset)
            if nl < 0:
                break  # trailing bytes without a newline: torn append
            rec = _verify_line(raw[offset:nl].decode("utf-8", "replace"),
                               prev_seq)
            if rec is None:
                break
            prev_seq = rec["seq"]
            report["records"] += 1
            offset = nl + 1
        if offset < len(raw):
            sidecar = _quarantine_bytes(seg_path, offset)
            report["quarantined"].append(sidecar)
            logger.warning(
                "journal recovery: quarantined %d torn/corrupt byte(s) of "
                "%s to %s (rolled back to seq %s)",
                len(raw) - offset, seg_path, sidecar, prev_seq,
            )
            cut_found = True
    report["last_seq"] = prev_seq
    return report


def replay(root: str, strict: bool = False) -> List[Dict[str, Any]]:
    """Read every durable record back, in sequence order.

    Non-mutating. With ``strict=False`` (default) replay stops silently at
    the first bad record — call :func:`recover` first if you want the bad
    tail quarantined; ``strict=True`` raises :class:`JournalCorruptError`
    instead (integrity audits, the crash tests' assertions).
    """
    out: List[Dict[str, Any]] = []
    if not os.path.isdir(root):
        return out
    segments = sorted(
        (idx, name) for name in os.listdir(root)
        if (idx := _segment_index(name)) is not None
    )
    prev_seq: Optional[int] = None
    for _idx, name in segments:
        seg_path = os.path.join(root, name)
        with open(seg_path, "rb") as f:
            raw = f.read()
        offset = 0
        while offset < len(raw):
            nl = raw.find(b"\n", offset)
            if nl < 0:
                break
            line = raw[offset:nl].decode("utf-8", "replace")
            rec = _verify_line(line, prev_seq)
            if rec is None:
                if strict:
                    raise JournalCorruptError(
                        f"corrupt journal record in {seg_path} at byte "
                        f"{offset} (after seq {prev_seq})"
                    )
                return out
            prev_seq = rec["seq"]
            out.append(rec)
            offset = nl + 1
        if offset < len(raw):
            if strict:
                raise JournalCorruptError(
                    f"torn trailing record in {seg_path} at byte {offset}"
                )
            return out
    return out


def replay_reconciled(root: str) -> List[Dict[str, Any]]:
    """Read every durable record across *all* writer incarnations, in a
    stable ``(seq, incarnation)`` order, deduplicating overlapping sequence
    ranges in favor of the latest incarnation.

    :func:`replay` assumes a single totally-ordered writer history: it
    demands ``seq == prev_seq + 1`` across segment boundaries and silently
    stops at the first discontinuity. That is the right paranoia for crash
    *recovery* — but it silently discards valid history when a restarted
    incarnation began from an **older durable cut** than the bytes a reader
    can now see (an unacknowledged tail that later became visible, an
    ``sync=False`` page-cache survivor, a split-brain writer): the second
    incarnation's segments re-use sequence numbers the first already
    emitted, so strict replay drops the entire later incarnation.

    For trace analysis (the twin's journal loader) we want the union
    instead: each segment is CRC-verified and read up to its own torn tail,
    segments are grouped into incarnations (a new incarnation starts
    whenever a segment's first sequence number does not continue the
    previous segment's), and the merged stream is stable-sorted by
    ``(seq, incarnation)``. Where two incarnations emitted the same ``seq``,
    the later incarnation's record wins — it is the one whose writer went on
    to extend the history. Non-mutating; never raises on corruption.
    """
    tagged: List[Tuple[int, int, Dict[str, Any]]] = []
    if not os.path.isdir(root):
        return []
    segments = sorted(
        (idx, name) for name in os.listdir(root)
        if (idx := _segment_index(name)) is not None
    )
    incarnation = -1
    prev_last: Optional[int] = None
    for _idx, name in segments:
        seg_path = os.path.join(root, name)
        with open(seg_path, "rb") as f:
            raw = f.read()
        offset = 0
        seg_prev: Optional[int] = None
        seg_records: List[Dict[str, Any]] = []
        while offset < len(raw):
            nl = raw.find(b"\n", offset)
            if nl < 0:
                break
            rec = _verify_line(raw[offset:nl].decode("utf-8", "replace"),
                               seg_prev)
            if rec is None:
                break  # torn tail / corruption: keep the segment's prefix
            seg_prev = rec["seq"]
            seg_records.append(rec)
            offset = nl + 1
        if not seg_records:
            continue
        first = seg_records[0]["seq"]
        if prev_last is None or first != prev_last + 1:
            incarnation += 1  # seq discontinuity = a writer (re)start
        prev_last = seg_records[-1]["seq"]
        for rec in seg_records:
            tagged.append((rec["seq"], incarnation, rec))
    tagged.sort(key=lambda t: (t[0], t[1]))
    out: List[Dict[str, Any]] = []
    for seq, _inc, rec in tagged:
        if out and out[-1]["seq"] == seq:
            out[-1] = rec  # later incarnation overwrites the same seq
        else:
            out.append(rec)
    return out


class Journal:
    """The write-ahead journal: append/commit over rotating segments.

    Opening a journal directory first runs :func:`recover` (quarantining any
    torn tail), then starts a **fresh segment** whose sequence numbers
    continue from the last durable record — prior segments are immutable
    from that point on, so a crashed incarnation can never dirty a healthy
    one's files.
    """

    def __init__(
        self,
        root: str,
        segment_max_bytes: int = 4 * 1024 * 1024,
        barrier: Optional[Callable[[str, Dict[str, Any]], None]] = None,
        sync: bool = True,
    ):
        self.root = root
        self.segment_max_bytes = segment_max_bytes
        self.sync = sync
        self._barrier_cb = barrier
        self._lock = tsan.rlock("journal.lock")
        self._buf: List[bytes] = []
        self._closed = False
        os.makedirs(root, exist_ok=True)
        self.recovery_report = recover(root)
        self._seq = self.recovery_report["last_seq"] or 0
        taken = [
            idx for name in os.listdir(root)
            if (idx := _segment_index(name.split(".corrupt")[0])) is not None
        ]
        self._segment_index = (max(taken) + 1) if taken else 1
        self._fh = None
        self._path = ""
        self._size = 0
        self._open_segment()

    # ------------------------------------------------------------- barriers
    def barrier(self, point: str, **ctx) -> None:
        """Cross a named durability barrier; the crash harness hooks here."""
        cb = self._barrier_cb
        if cb is not None:
            cb(point, ctx)

    # -------------------------------------------------------------- segments
    def _encode(self, kind: str, data: Dict[str, Any]) -> bytes:
        self._seq += 1
        body = {
            "seq": self._seq,
            "ts": round(time.time(), 6),
            "kind": kind,
            "data": data,
        }
        rec = dict(body, crc=_crc_of(body))
        return (json.dumps(rec, **_JSON_OPTS) + "\n").encode("utf-8")

    # sanctioned-unlocked: segment creation fsyncs under the journal lock —
    # the atomic-rotation contract (header durable before rename) requires it
    def _open_segment(self) -> None:
        path = _segment_path(self.root, self._segment_index)
        tmp = path + ".tmp"
        header = self._encode(
            "segment_open",
            {"segment": self._segment_index, "pid": os.getpid()},
        )
        with open(tmp, "wb") as f:
            f.write(header)
            f.flush()
            if self.sync:
                os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic rotation: never a half-written segment
        if self.sync:
            _fsync_dir(self.root)
        self._path = path
        self._fh = open(path, "ab")
        self._size = os.path.getsize(path)
        self.barrier("post-rename", path=path, segment=self._segment_index)

    # sanctioned-unlocked: rotation flush+fsync under the journal lock is the
    # durability point that makes the old segment immutable before switching
    def _rotate(self) -> None:
        self.barrier("pre-rotate", path=self._path)
        self._fh.flush()
        if self.sync:
            os.fsync(self._fh.fileno())
        self._fh.close()
        self._segment_index += 1
        self._open_segment()

    # --------------------------------------------------------------- writes
    def append(self, kind: str, **data) -> int:
        """Buffer one record; returns its sequence number. NOT durable until
        the next :meth:`commit` — callers choose the group-commit cadence."""
        sched_point("journal.append")
        with self._lock:
            if self._closed:
                raise RuntimeError("journal is closed")
            line = self._encode(kind, data)
            self._buf.append(line)
            return self._seq

    def log(self, kind: str, **data) -> int:
        """``append`` + immediate ``commit`` — for records that must be
        durable before the caller returns (e.g. a client-acknowledged job
        submission)."""
        with self._lock:
            seq = self.append(kind, **data)
            self.commit()
            return seq

    # sanctioned-unlocked: the fsync under the lock IS the group-commit —
    # "committed means survives SIGKILL" requires appenders to wait out the
    # sync rather than interleave records into a half-durable batch
    def commit(self) -> int:
        """Group-commit every buffered record: one write, one fsync.
        Returns the number of records made durable."""
        sched_point("journal.commit")
        with self._lock:
            if self._closed:
                raise RuntimeError("journal is closed")
            if not self._buf:
                return 0
            self.barrier("pre-commit", path=self._path, pending=len(self._buf))
            payload = b"".join(self._buf)
            n = len(self._buf)
            self._buf.clear()
            start = self._size
            self._fh.write(payload)
            self._fh.flush()
            # Between flush and fsync the bytes live in the page cache: a
            # power cut here is exactly the torn-tail case recovery handles.
            self.barrier(
                "mid-fsync", path=self._path, start=start,
                end=start + len(payload),
            )
            if self.sync:
                os.fsync(self._fh.fileno())
            self._size += len(payload)
            self.barrier("post-commit", path=self._path, seq=self._seq)
            if self._size >= self.segment_max_bytes:
                self._rotate()
            return n

    @property
    def last_seq(self) -> int:
        return self._seq

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._buf)

    # sanctioned-unlocked: final drain — close holds the lock across its
    # fsync so no append can slip in after the last committed byte
    def close(self) -> None:
        """Commit anything buffered, fsync, close. NOT called on a simulated
        kill — a dead process flushes nothing."""
        with self._lock:
            if self._closed:
                return
            self.commit()
            self._closed = True
            self._fh.flush()
            if self.sync:
                os.fsync(self._fh.fileno())
            self._fh.close()

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
