"""In-process client API + the ``python -m saturn_tpu.service`` CLI.

The client is a thin veneer over the service's queue: ``submit`` enqueues a
:class:`JobRequest`, ``status``/``wait`` read the job's lifecycle record,
``cancel`` requests eviction. It is in-process by design — the service is
single-host, and the queue's condition variable gives cheap blocking waits;
a network front-end would wrap exactly this surface.

The CLI needs no live service at all: it tails the JSONL metrics stream
(``utils.metrics.tail_events``) that any service run appends to, folds the
``job_*`` lifecycle events into a queue view, and prints it — so an operator
can watch (or post-mortem) a run from a separate process.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

from saturn_tpu.service.queue import JobRequest


class ServiceClient:
    """submit / status / wait / cancel against a running SaturnService."""

    def __init__(self, service):
        self._service = service

    def submit(self, task, priority: float = 0.0,
               deadline_s: Optional[float] = None,
               max_retries: int = 1,
               spec: Optional[dict] = None) -> str:
        """Enqueue a task; returns the job id.

        ``spec`` is an optional JSON-serializable rebuild payload: when the
        service runs with ``durability_dir``, it is journaled with the
        submission and handed back to ``task_provider(spec)`` after a crash
        so the task object can be reconstructed. On a durable service,
        ``submit`` returning means the submission survived — it was fsync'd
        to the write-ahead journal before this call unblocked."""
        rec = self._service.queue.submit(JobRequest(
            task=task, priority=priority, deadline_s=deadline_s,
            max_retries=max_retries, spec=spec,
        ))
        return rec.job_id

    def status(self, job_id: str) -> dict:
        """Point-in-time snapshot of the job's lifecycle record."""
        return self._service.queue.get(job_id).snapshot()

    def wait(self, job_id: str, timeout: Optional[float] = None) -> dict:
        """Block until the job is DONE/FAILED/EVICTED; raises
        ``TimeoutError`` otherwise."""
        return self._service.queue.wait(job_id, timeout).snapshot()

    def cancel(self, job_id: str) -> bool:
        """Request eviction; False if the job already reached a terminal
        state."""
        return self._service.queue.cancel(job_id)


# ------------------------------------------------------------------- CLI
_LIFECYCLE_KINDS = (
    "job_submitted", "job_admitted", "job_scheduled", "job_completed",
    "job_failed", "job_evicted", "queue_depth",
)


def _fold(rec: dict, jobs: dict) -> None:
    kind, job = rec.get("kind"), rec.get("job")
    if not job:
        return
    j = jobs.setdefault(job, {"job": job, "task": rec.get("task"),
                              "state": "QUEUED", "detail": ""})
    if kind == "job_admitted":
        dec = rec.get("decision", "admit")
        if dec == "admit":
            j["state"] = "ADMITTED"
            j["detail"] = ("warm" if rec.get("warm") else
                           f"{rec.get('trials_run', 0)} trials")
        elif dec == "defer":
            j["state"] = "DEFERRED"
            j["detail"] = rec.get("reason", "")
        else:
            j["state"] = "REJECTED"
            j["detail"] = rec.get("reason", "")
    elif kind == "job_scheduled":
        j["state"] = "SCHEDULED"
        start = rec.get("start_s")
        j["detail"] = f"start +{start:.1f}s" if start is not None else ""
    elif kind == "job_completed":
        j["state"] = "DONE"
        wait = rec.get("wait_s")
        j["detail"] = f"wait {wait:.2f}s" if wait is not None else ""
    elif kind == "job_failed":
        j["state"] = "FAILED"
        j["detail"] = rec.get("error", "")
    elif kind == "job_evicted":
        j["state"] = "EVICTED"
        j["detail"] = rec.get("reason", "")


def _render(jobs: dict, depth) -> str:
    lines = [f"{'JOB':<22} {'TASK':<14} {'STATE':<10} DETAIL"]
    for j in jobs.values():
        lines.append(
            f"{j['job']:<22} {str(j['task']):<14} {j['state']:<10} "
            f"{j['detail']}"
        )
    if depth is not None:
        lines.append(f"queue depth: {depth.get('depth')} waiting, "
                     f"{depth.get('live')} live, "
                     f"{depth.get('active')} in plan")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m saturn_tpu.service",
        description="Tail a saturn_tpu service's JSONL metrics stream as a "
                    "live queue view.",
    )
    p.add_argument("metrics_path", help="JSONL file the service writes "
                                        "(SaturnService(metrics_path=...))")
    p.add_argument("--follow", "-f", action="store_true",
                   help="keep tailing for new events (Ctrl-C to stop)")
    p.add_argument("--events", action="store_true",
                   help="print raw lifecycle events instead of the table")
    args = p.parse_args(argv)

    from saturn_tpu.utils.metrics import tail_events

    jobs: dict = {}
    depth = None
    try:
        for rec in tail_events(args.metrics_path, follow=args.follow):
            if rec.get("kind") not in _LIFECYCLE_KINDS:
                continue
            if args.events:
                print({k: v for k, v in rec.items() if k != "ts"})
                continue
            if rec["kind"] == "queue_depth":
                depth = rec
            else:
                _fold(rec, jobs)
            if args.follow:
                print(f"-- {rec['kind']}: "
                      f"{rec.get('job') or ''} {rec.get('task') or ''}")
    except KeyboardInterrupt:
        pass
    if not args.events:
        print(_render(jobs, depth))
    return 0


if __name__ == "__main__":
    sys.exit(main())
