"""Submission queue + job lifecycle state machine for the online service.

One queue object is the single source of truth for every job the service has
ever seen: arrivals wait here, the server drains them at interval
boundaries, preemptions requeue *through the same queue* (the requeued job
re-admits warm — its strategies are already profiled), and clients block on
the queue's condition variable in ``wait()``.

State machine (enforced — an illegal transition raises)::

    QUEUED ──► PROFILING ──► SCHEDULED ──► RUNNING ──► DONE
      ▲            │             │            │
      │            ├─► FAILED    │            ├─► FAILED
      │            │             │            │
      └────────────┴◄────────────┴────────────┘   (defer / preemption
                   └─► EVICTED (any non-terminal)  requeue)

``DONE``, ``FAILED`` and ``EVICTED`` are terminal.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from saturn_tpu.analysis import concurrency as tsan
from saturn_tpu.analysis.concurrency import sched_point
from saturn_tpu.tenancy.model import DEFAULT_TENANT
from saturn_tpu.utils import metrics


class JobState(str, enum.Enum):
    QUEUED = "QUEUED"          # submitted, waiting for the admission drain
    PROFILING = "PROFILING"    # admission controller profiling / cache lookup
    SCHEDULED = "SCHEDULED"    # in the live plan, waiting for its start slot
    RUNNING = "RUNNING"        # technique launched at least once
    DONE = "DONE"              # all batches complete
    FAILED = "FAILED"          # rejected, or failed past its retry budget
    EVICTED = "EVICTED"        # cancelled, or shed by a replan/pressure policy


TERMINAL_STATES = frozenset(
    {JobState.DONE, JobState.FAILED, JobState.EVICTED}
)

#: States in which a job holds (or is about to hold) mesh resources. The
#: per-tenant ``max_live_jobs`` admission gate counts these — NOT queued
#: arrivals: gating on all non-terminal jobs would count a burst's own
#: queued siblings and defer the whole burst forever (nothing admitted,
#: nothing completing, nothing ever freeing a slot).
_ADMITTED_STATES = frozenset({JobState.SCHEDULED, JobState.RUNNING})

#: Legal transitions. QUEUED is re-enterable from PROFILING (admission
#: defers work that cannot fit the current mesh), SCHEDULED (replan dropped
#: the slot) and RUNNING (preemption requeues through the queue); EVICTED is
#: reachable from every non-terminal state (cancel / pressure shedding).
_TRANSITIONS: Dict[JobState, frozenset] = {
    JobState.QUEUED: frozenset({JobState.PROFILING, JobState.EVICTED}),
    JobState.PROFILING: frozenset(
        {JobState.SCHEDULED, JobState.QUEUED, JobState.FAILED, JobState.EVICTED}
    ),
    JobState.SCHEDULED: frozenset(
        {JobState.RUNNING, JobState.QUEUED, JobState.FAILED, JobState.EVICTED}
    ),
    JobState.RUNNING: frozenset(
        {JobState.DONE, JobState.FAILED, JobState.QUEUED, JobState.EVICTED}
    ),
    JobState.DONE: frozenset(),
    JobState.FAILED: frozenset(),
    JobState.EVICTED: frozenset(),
}


@dataclass
class JobRequest:
    """What a client submits: a profiled-or-profilable task plus policy."""

    task: object                       # a Task (or duck-typed equivalent)
    priority: float = 0.0              # higher = more urgent (solver weight
    #                                    and eviction ordering)
    deadline_s: Optional[float] = None  # seconds from submission; admission
    #                                     pressure sheds work to protect it
    max_retries: int = 1               # extra attempts after a task failure
    #                                    (preemptions never consume these)
    spec: Optional[dict] = None        # caller-supplied, JSON-serializable
    #                                    rebuild payload: journaled with the
    #                                    submission and handed back to
    #                                    ``task_provider`` on crash recovery
    #                                    so the task object can be rebuilt
    dedup_key: Optional[str] = None    # gateway idempotency key: journaled
    #                                    inside the job_submitted record so a
    #                                    retried network submit (lost ACK,
    #                                    gateway restart) maps back to this
    #                                    job id instead of admitting twice
    tenant: Optional[str] = None       # billing/fairness principal; None
    #                                    folds to the "default" tenant so
    #                                    single-tenant deployments are
    #                                    unchanged. Quotas, fair-share
    #                                    weighting and tenant-aware shedding
    #                                    all key on this


@dataclass
class JobRecord:
    """The queue's view of one job across its whole lifetime."""

    job_id: str
    request: JobRequest
    state: JobState = JobState.QUEUED
    submitted_at: float = 0.0          # time.monotonic() timestamps
    admitted_at: Optional[float] = None
    scheduled_at: Optional[float] = None
    started_at: Optional[float] = None   # first RUNNING transition only
    finished_at: Optional[float] = None
    deadline_at: Optional[float] = None  # submitted_at + deadline_s
    attempts: int = 0                  # failed attempts so far
    requeues: int = 0                  # preemption/defer round-trips
    trials_run: int = 0                # profiling trials admission executed
    weight: float = 0.0                # solver objective weight
    error: Optional[str] = None
    cancel_requested: bool = False

    @property
    def task(self):
        return self.request.task

    @property
    def name(self) -> str:
        return self.request.task.name

    @property
    def tenant(self) -> str:
        return self.request.tenant or DEFAULT_TENANT

    def snapshot(self) -> dict:
        """Client-facing view — plain data, safe to hold across states."""
        return {
            "job_id": self.job_id,
            "task": self.name,
            "tenant": self.tenant,
            "state": self.state.value,
            "priority": self.request.priority,
            "deadline_s": self.request.deadline_s,
            "submitted_at": self.submitted_at,
            "admitted_at": self.admitted_at,
            "scheduled_at": self.scheduled_at,
            "started_at": self.started_at,
            "finished_at": self.finished_at,
            "attempts": self.attempts,
            "requeues": self.requeues,
            "trials_run": self.trials_run,
            "weight": self.weight,
            "error": self.error,
        }


class SubmissionQueue:
    """Thread-safe arrival queue + job registry.

    Clients submit from any thread; the server drains at interval
    boundaries. All state transitions go through :meth:`mark` so the
    lifecycle invariants hold no matter which thread drives them (client,
    server loop, or an engine launcher thread firing ``on_task_start``).
    """

    def __init__(self, observer=None):
        self._lock = tsan.rlock("queue.lock")
        self._cond = tsan.condition(self._lock, "queue.cond")
        self._jobs: Dict[str, JobRecord] = {}
        self._arrivals: List[str] = []   # job_ids waiting for the next drain
        #: name -> job_id for every non-terminal job. The uniqueness check in
        #: submit/restore and the ``live()`` gauge read this instead of
        #: scanning the whole registry — at twin-campaign scale (100k+
        #: submissions) the O(all-jobs-ever) scan per submit is quadratic.
        self._live_names: Dict[str, str] = {}
        #: tenant -> live (non-terminal) job count, maintained alongside
        #: ``_live_names`` so per-tenant windows and fair-share targets are
        #: O(1) lookups instead of registry scans on the gateway hot path.
        self._tenant_live: Dict[str, int] = {}
        #: tenant -> jobs currently in an admitted state (SCHEDULED or
        #: RUNNING); the admission quota gate's O(1) input (see
        #: ``_ADMITTED_STATES`` for why this excludes queued arrivals).
        self._tenant_admitted: Dict[str, int] = {}
        self._seq = 0
        #: Optional ``observer(event, rec, **fields)`` called under the queue
        #: lock after every registry mutation ("submitted" / "state" /
        #: "recovered") — the durability layer's write-ahead hook. Lock
        #: ordering is queue-lock → journal-lock, never the reverse.
        self.observer = observer

    def _notify_observer(self, event: str, rec: JobRecord, **fields) -> None:
        if self.observer is not None:
            self.observer(event, rec, **fields)

    # ------------------------------------------------------------ submission
    def submit(self, request: JobRequest) -> JobRecord:
        """Register a job and place it on the arrival queue.

        Task names must be unique among *live* (non-terminal) jobs — every
        downstream subsystem (plan, engine events, checkpoints) keys on
        ``task.name``. Resubmitting a name whose previous job finished is
        fine.
        """
        name = getattr(request.task, "name", None)
        if not name:
            raise ValueError("JobRequest.task must have a non-empty .name")
        sched_point("queue.submit")
        with self._lock:
            live_id = self._live_names.get(name)
            if live_id is not None:
                rec = self._jobs[live_id]
                raise ValueError(
                    f"task name {name!r} is already live as {rec.job_id} "
                    f"({rec.state.value}) — task names must be unique "
                    "among active jobs"
                )
            self._seq += 1
            now = time.monotonic()
            rec = JobRecord(
                job_id=f"j{self._seq:04d}-{name}",
                request=request,
                submitted_at=now,
                deadline_at=(
                    now + request.deadline_s
                    if request.deadline_s is not None else None
                ),
            )
            self._jobs[rec.job_id] = rec
            self._live_names[name] = rec.job_id
            self._tenant_live[rec.tenant] = (
                self._tenant_live.get(rec.tenant, 0) + 1
            )
            self._arrivals.append(rec.job_id)
            self._notify_observer("submitted", rec)
            self._cond.notify_all()
        metrics.event(
            "job_submitted", job=rec.job_id, task=name, tenant=rec.tenant,
            priority=request.priority, deadline_s=request.deadline_s,
        )
        return rec

    def restore(self, rec: JobRecord) -> JobRecord:
        """Re-register a journal-reconstructed job under its *original*
        ``job_id`` (crash recovery only — new work goes through
        :meth:`submit`).

        Terminal jobs become inert registry entries so ``status``/``wait``
        keep answering for them; live jobs also re-enter the arrival queue
        and re-admit warm. ``_seq`` advances past every recovered id so a
        post-restart submission can never collide with a journaled one.
        """
        name = rec.name
        with self._lock:
            if rec.job_id in self._jobs:
                raise ValueError(f"job id {rec.job_id!r} already registered")
            if rec.state not in TERMINAL_STATES:
                live_id = self._live_names.get(name)
                if live_id is not None:
                    other = self._jobs[live_id]
                    raise ValueError(
                        f"task name {name!r} is already live as "
                        f"{other.job_id} ({other.state.value}) — cannot "
                        f"restore {rec.job_id}"
                    )
            try:  # job_id format: j{seq:04d}-{name}
                recovered_seq = int(rec.job_id[1:].split("-", 1)[0])
            except (ValueError, IndexError):
                recovered_seq = 0
            self._seq = max(self._seq, recovered_seq)
            self._jobs[rec.job_id] = rec
            if rec.state not in TERMINAL_STATES:
                self._live_names[name] = rec.job_id
                self._tenant_live[rec.tenant] = (
                    self._tenant_live.get(rec.tenant, 0) + 1
                )
                if rec.state in _ADMITTED_STATES:
                    self._tenant_admitted[rec.tenant] = (
                        self._tenant_admitted.get(rec.tenant, 0) + 1
                    )
                if rec.job_id not in self._arrivals:
                    self._arrivals.append(rec.job_id)
                self._notify_observer("recovered", rec)
            self._cond.notify_all()
        if rec.state not in TERMINAL_STATES:
            metrics.event(
                "job_recovered", job=rec.job_id, task=name,
                requeues=rec.requeues, attempts=rec.attempts,
                remaining_batches=getattr(rec.task, "total_batches", None),
            )
        return rec

    def requeue(self, rec: JobRecord) -> None:
        """Put an admitted job back on the arrival queue (defer, replan drop,
        or preemption). Re-admission is warm: the task keeps its profiled
        strategies, so the controller readmits in O(cache lookup)."""
        sched_point("queue.requeue")
        with self._lock:
            if rec.state is not JobState.QUEUED:
                self.mark(rec, JobState.QUEUED)
            rec.requeues += 1
            if rec.job_id not in self._arrivals:
                self._arrivals.append(rec.job_id)
            self._cond.notify_all()

    def drain(self) -> List[JobRecord]:
        """Take every waiting arrival (FIFO). Called by the server at each
        interval boundary."""
        sched_point("queue.drain")
        with self._lock:
            ids, self._arrivals = self._arrivals, []
            return [self._jobs[i] for i in ids]

    def wait_for_arrival(self, timeout: Optional[float] = None) -> bool:
        """Block until at least one arrival is waiting (idle-server parking;
        avoids a busy drain loop). Returns whether anything is waiting."""
        sched_point("queue.wait_for_arrival")
        with self._lock:
            if not self._arrivals:
                # Invariant: a single *timed* wait, and the return value is
                # recomputed from _arrivals after waking — spurious wakeups
                # and lost races surface as a False return the server's poll
                # loop retries, never as a missed job.
                # sanctioned-unlocked: timed single wait; caller loop retests
                self._cond.wait(timeout)
            return bool(self._arrivals)

    # ------------------------------------------------------------ lifecycle
    def mark(self, rec: JobRecord, state: JobState, *,
             error: Optional[str] = None) -> None:
        """Transition a job, stamping timestamps. Illegal transitions raise
        — a state-machine violation is a server bug, not a runtime condition
        to paper over."""
        sched_point("queue.mark")
        with self._lock:
            if state not in _TRANSITIONS[rec.state]:
                raise RuntimeError(
                    f"illegal job transition {rec.state.value} -> "
                    f"{state.value} for {rec.job_id}"
                )
            was_admitted = rec.state in _ADMITTED_STATES
            rec.state = state
            if state in _ADMITTED_STATES and not was_admitted:
                self._tenant_admitted[rec.tenant] = (
                    self._tenant_admitted.get(rec.tenant, 0) + 1
                )
            elif was_admitted and state not in _ADMITTED_STATES:
                n = self._tenant_admitted.get(rec.tenant, 0) - 1
                if n > 0:
                    self._tenant_admitted[rec.tenant] = n
                else:
                    self._tenant_admitted.pop(rec.tenant, None)
            if state in TERMINAL_STATES:
                if self._live_names.get(rec.name) == rec.job_id:
                    del self._live_names[rec.name]
                    n = self._tenant_live.get(rec.tenant, 0) - 1
                    if n > 0:
                        self._tenant_live[rec.tenant] = n
                    else:
                        self._tenant_live.pop(rec.tenant, None)
            now = time.monotonic()
            if state is JobState.SCHEDULED:
                if rec.admitted_at is None:  # first admission outcome
                    rec.admitted_at = now
                rec.scheduled_at = now
            elif state is JobState.RUNNING and rec.started_at is None:
                rec.started_at = now
            elif state in TERMINAL_STATES:
                rec.finished_at = now
            if error is not None:
                rec.error = error
            self._notify_observer("state", rec)
            self._cond.notify_all()

    # -------------------------------------------------------------- queries
    def get(self, job_id: str) -> JobRecord:
        with self._lock:
            try:
                return self._jobs[job_id]
            except KeyError:
                raise KeyError(f"unknown job id {job_id!r}") from None

    def jobs(self) -> List[JobRecord]:
        with self._lock:
            return list(self._jobs.values())

    def depth(self) -> int:
        """Jobs waiting for admission (QUEUED or PROFILING) — the
        ``queue_depth`` metric."""
        with self._lock:
            return sum(
                1 for r in self._jobs.values()
                if r.state in (JobState.QUEUED, JobState.PROFILING)
            )

    def live(self) -> int:
        """Jobs in any non-terminal state."""
        with self._lock:
            return len(self._live_names)

    def live_tenant(self, tenant: Optional[str]) -> int:
        """Non-terminal jobs accounted to ``tenant`` (None = default)."""
        with self._lock:
            return self._tenant_live.get(tenant or DEFAULT_TENANT, 0)

    def admitted_tenant(self, tenant: Optional[str]) -> int:
        """Jobs accounted to ``tenant`` in an admitted state (SCHEDULED or
        RUNNING) — what the ``max_live_jobs`` quota gate counts. Queued
        arrivals are deliberately excluded: see ``_ADMITTED_STATES``."""
        with self._lock:
            return self._tenant_admitted.get(tenant or DEFAULT_TENANT, 0)

    def live_by_tenant(self) -> Dict[str, int]:
        """tenant -> live job count (fair-share input; copy, safe to hold)."""
        with self._lock:
            return dict(self._tenant_live)

    def compact(self) -> int:
        """Drop terminal job records from the registry; returns how many were
        removed. ``status``/``wait`` stop answering for compacted ids, so this
        is for long-running campaign drivers (the twin runs 100k+ jobs through
        one queue) — the interactive service keeps its full history."""
        sched_point("queue.compact")
        with self._lock:
            dead = [
                jid for jid, r in self._jobs.items()
                if r.state in TERMINAL_STATES
            ]
            for jid in dead:
                del self._jobs[jid]
            return len(dead)

    def wait(self, job_id: str, timeout: Optional[float] = None) -> JobRecord:
        """Block until the job reaches a terminal state (or raise
        ``TimeoutError``)."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        with self._lock:
            rec = self.get(job_id)
            while rec.state not in TERMINAL_STATES:
                remaining = (
                    deadline - time.monotonic()
                    if deadline is not None else None
                )
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"job {job_id} still {rec.state.value} after "
                        f"{timeout}s"
                    )
                self._cond.wait(remaining)
            return rec

    def cancel(self, job_id: str) -> bool:
        """Request cancellation. A still-QUEUED job is evicted immediately;
        an admitted job is flagged and the server evicts it at the next
        interval boundary. Returns False if the job is already terminal."""
        sched_point("queue.cancel")
        with self._lock:
            rec = self.get(job_id)
            if rec.state in TERMINAL_STATES:
                return False
            rec.cancel_requested = True
            if rec.state is JobState.QUEUED:
                self._arrivals = [i for i in self._arrivals if i != job_id]
                self.mark(rec, JobState.EVICTED, error="cancelled")
                metrics.event("job_evicted", job=rec.job_id, task=rec.name,
                              reason="cancelled")
            return True
