"""``python -m saturn_tpu.service`` — tail a service's JSONL metrics stream."""

import sys

from saturn_tpu.service.client import main

sys.exit(main())
