"""Admission control: profile arrivals, gate on fit, weight the objective.

Profiling rides the existing trial-runner stack end to end — persistent
profile cache first, cost-model (Amdahl) pruning for uncached grids — so a
*warm* arrival (same model/data/optimizer fingerprint seen before, any
priority) admits in O(cache lookup) with **zero** trial executions, while a
cold arrival pays the sweep exactly once across the fleet's lifetime.
Requeued jobs (preemption round-trips) skip profiling entirely: their
strategies are already populated in-process.

Fit gating: a job with no feasible strategy that fits the *current* mesh is
REJECTED on a full-capacity mesh (it will never fit) but DEFERRED when the
mesh is degraded below its base capacity (a grow event may re-admit it).

Weights: each admitted job gets a solver-objective weight

    w = 2^priority * (1 + est_runtime / max(deadline_slack, est_runtime))

— exponential in priority so integer priority classes strictly dominate,
with a deadline-urgency boost capped at 2x (a job whose estimated runtime
already consumes its slack is maximally urgent). ``solver.milp`` folds the
normalized weights into the objective as a weighted-start-time tiebreak.

Tenancy (when the service wires a ``TenantLedger``): before any profiling
spend, the arrival's tenant is gated on its quota — over ``max_live_jobs``
DEFERs (the tenant's own completions free the slot), an exhausted
``chip_seconds`` budget REJECTs — and an admitted job's weight is scaled
by the tenant's weighted-fair-share multiplier, so an over-share tenant's
new work yields the solver's attention to under-share tenants without
overriding priority classes or deadlines.
"""

from __future__ import annotations

import logging
import time
import timeit
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.service.queue import JobRecord, JobState, SubmissionQueue
from saturn_tpu.utils import metrics

logger = logging.getLogger("saturn_tpu")

ADMIT = "admit"
REJECT = "reject"
DEFER = "defer"

# revisit_on hints carried by DEFER decisions: what event can change the
# verdict, so operators (and the grow coordinator) know what a grow or
# defrag wave would drain.
REVISIT_INTERVAL = "interval"  # the tenant's own completions free the slot
REVISIT_GROW = "grow"          # a grow event restores the missing capacity
REVISIT_DEFRAG = "defrag"      # capacity exists; pinned HBM must compact


@dataclass
class AdmissionDecision:
    action: str                  # ADMIT | REJECT | DEFER
    reason: str = ""
    trials_run: int = 0          # trials the profiling sweep executed
    weight: float = 0.0          # solver objective weight (ADMIT only)
    latency_s: float = 0.0       # wall-clock admission latency
    # This decision rests on shardflow cold-start priors: the job's
    # strategies were synthesized from the static sharding/communication
    # analysis (``analysis/shardflow/prior.py``), not from trials. Realized
    # feedback supersedes them; SAT-X005 audits the estimate afterwards.
    static_prior: bool = False
    # DEFER only: which event should re-open this verdict (see REVISIT_*).
    revisit_on: str = ""


def _min_feasible_runtime(task) -> float:
    feas = task.feasible_strategies()
    return min(s.runtime for s in feas.values()) if feas else 0.0


def compute_weight(priority: float, deadline_slack_s: Optional[float],
                   est_runtime_s: float) -> float:
    """Priority/deadline weight for the solver objective (see module doc)."""
    w = 2.0 ** float(priority)
    if deadline_slack_s is not None:
        est = max(est_runtime_s, 1e-9)
        w *= 1.0 + est / max(deadline_slack_s, est)
    return w


class AdmissionController:
    """Profiles and gates arrivals for :class:`~saturn_tpu.service.server.
    SaturnService`. Single-threaded: only the server loop calls it."""

    def __init__(
        self,
        topology: SliceTopology,
        queue: SubmissionQueue,
        technique_names: Optional[List[str]] = None,
        profile_cache: Any = None,
        prune: bool = True,
        parallel_trials: Optional[int] = None,
        static_priors: bool = False,
    ):
        self.base_capacity = topology.capacity
        self.technique_names = technique_names
        self.profile_cache = profile_cache
        self.prune = prune
        self.parallel_trials = parallel_trials
        #: Opt-in shardflow cold-start path: a never-profiled arrival gets
        #: ``static_prior=True`` strategies from the jaxpr-level sharding /
        #: communication analysis instead of paying the trial sweep up
        #: front. ADMIT/DEFER become sharding-aware with zero chip time;
        #: the first realized interval supersedes the prior and SAT-X005
        #: audits it (``_audit_priors``).
        self.static_priors = static_priors
        self.queue = queue
        #: Optional write-ahead journal (set by ``SaturnService`` when
        #: durability is on): every admission outcome becomes a buffered
        #: ``job_admission`` record, durable at the next group commit.
        self.journal = None
        #: Optional TenantLedger (set by ``SaturnService`` when tenancy is
        #: on): quota gates + fair-share weight scaling, see module doc.
        self.tenancy = None
        #: Optional occupancy gate (set by the grow coordinator): called
        #: ``gate(task, topology) -> verdict-dict | None`` after the size
        #: fit passes. A ``{"fits": False, ...}`` verdict DEFERs with
        #: ``revisit_on="defrag"`` — the schedule has room but other tasks'
        #: device-resident live state pins too much HBM; a defrag wave can
        #: free it. ``None`` = no verdict (fail open).
        self.occupancy_gate: Optional[Callable] = None
        #: DEFER pool: job_id -> {task, tenant, reason, revisit_on,
        #: deferred_at, count}. Entries land on every DEFER and leave on
        #: the job's next ADMIT/REJECT; the grow coordinator reads it to
        #: know what a grow event or defrag wave would drain, and the
        #: ``analysis grow``/``tenancy`` views report backlog age from the
        #: journaled ``job_deferred`` records.
        self.deferred: Dict[str, dict] = {}
        #: tenant -> jobs ADMITted in the *current* drain pass. The queue
        #: only counts a job as admitted once the post-solve SCHEDULED mark
        #: lands, so without this a burst draining in one pass would sail
        #: past ``max_live_jobs`` together. The server resets it via
        #: :meth:`begin_pass` before each drain.
        self._pass_admitted: dict = {}

    def begin_pass(self) -> None:
        """Start a new drain pass (resets the in-pass admission tally)."""
        # sanctioned-unlocked: drain-pass scratch owned by the scheduler
        # thread (see admit); cleared here before each drain.
        self._pass_admitted.clear()

    def admit(self, rec: JobRecord, topology: SliceTopology) -> AdmissionDecision:
        """Profile (if needed) and decide one arrival.

        Transitions the record QUEUED -> PROFILING here; the *caller* applies
        the decision (SCHEDULED on admit after the re-solve, QUEUED on defer,
        FAILED on reject) — admission decides, the server owns the plan.
        """
        t0 = timeit.default_timer()
        self.queue.mark(rec, JobState.PROFILING)
        task = rec.task

        # Tenant quota gate: before a single trial or compile is spent on
        # this arrival. Both verdicts are cheap ledger lookups.
        if self.tenancy is not None:
            dec = self._tenant_gate(rec, t0)
            if dec is not None:
                self._note(rec, dec)
                return dec

        # Memlens cold-start memory gate: before any trial or compile, the
        # static liveness analysis checks every fitting (technique, size,
        # config) grid point against per-device HBM capacity. A verdict
        # only exists when capacity is known AND every point traced and
        # predicted OOM — anything unknown falls through to the sweep, and
        # the compile-time check stays the authoritative backstop.
        mem = self._memlens_verdict(task, topology)
        if mem is not None and not mem["fits"]:
            degraded = topology.capacity < self.base_capacity
            dec = AdmissionDecision(
                DEFER if degraded else REJECT,
                reason=(
                    f"memlens: predicted per-device HBM peak "
                    f"{mem['min_peak_bytes']} B exceeds capacity "
                    f"{mem['capacity_bytes']} B at every fitting size "
                    f"({mem['checked']} grid points, zero trials)"
                ),
                latency_s=timeit.default_timer() - t0,
                revisit_on=REVISIT_GROW if degraded else "",
            )
            self._note(rec, dec)
            return dec

        trials = 0
        used_prior = False
        if self.static_priors and not task.feasible_strategies():
            # Shardflow cold-start path: synthesize static-prior strategies
            # from the jaxpr-level analysis — zero trials, zero compiles.
            used_prior = self._synthesize_priors(rec, task, topology)
        if not task.feasible_strategies():
            # Cold (or never-seen) arrival: run the sweep. Warm fingerprints
            # resolve entirely from the profile cache — zero trials.
            from saturn_tpu.trial_runner import evaluator

            try:
                stats = evaluator.search(
                    [task],
                    technique_names=self.technique_names,
                    topology=topology,
                    profile_cache=self.profile_cache,
                    prune=self.prune,
                    parallel_trials=self.parallel_trials,
                )
            except Exception as e:
                dec = AdmissionDecision(
                    REJECT, reason=f"profiling failed: {e!r}",
                    latency_s=timeit.default_timer() - t0,
                )
                self._note(rec, dec)
                return dec
            trials = int((stats or {}).get("trials_run", 0))
        rec.trials_run += trials
        if self.static_priors:
            # SAT-X005: any strategy whose prior has since been superseded
            # by real evidence gets its static estimate audited now, while
            # the job is back in front of the controller.
            self._audit_priors(rec, task)

        fits = any(
            g <= topology.capacity for g in task.feasible_strategies()
        )
        if not fits and rec.requeues > 0:
            # A preempted job re-entering through the queue was already
            # running: instead of stranding it in DEFER until the mesh
            # grows back, synthesize a fitting strategy from its measured
            # anchors — the same Amdahl extrapolation the replanner applies
            # to jobs that were live when the topology shrank.
            from saturn_tpu.resilience.replan import ElasticReplanner

            added = ElasticReplanner()._synthesize(task, topology.capacity)
            if added:
                logger.info(
                    "admission: synthesized size(s) %s for requeued %s on "
                    "the %d-chip mesh", added, rec.job_id, topology.capacity,
                )
            fits = any(
                g <= topology.capacity for g in task.feasible_strategies()
            )
        if not fits:
            degraded = topology.capacity < self.base_capacity
            dec = AdmissionDecision(
                DEFER if degraded else REJECT,
                reason=(
                    "no feasible strategy fits the degraded mesh "
                    f"({topology.capacity}/{self.base_capacity} chips)"
                    if degraded else
                    f"no feasible strategy fits the mesh "
                    f"({topology.capacity} chips)"
                ),
                trials_run=trials,
                latency_s=timeit.default_timer() - t0,
                static_prior=used_prior,
                revisit_on=REVISIT_GROW if degraded else "",
            )
            self._note(rec, dec)
            return dec

        # Occupancy gate (grow coordinator): the gang fits the schedule,
        # but does its HBM footprint fit around other tasks' pinned live
        # state? A negative verdict is DEFER, never REJECT — a defrag wave
        # (or a completion releasing its state) re-opens it.
        if self.occupancy_gate is not None:
            try:
                occ = self.occupancy_gate(task, topology)
            except Exception as e:
                logger.debug("admission: occupancy gate skipped: %r", e)
                occ = None
            if occ is not None and not occ.get("fits", True):
                dec = AdmissionDecision(
                    DEFER,
                    reason=(
                        "occupancy: pinned live state leaves "
                        f"{occ.get('free_bytes', 0)} B free on every "
                        f"fitting block, need {occ.get('need_bytes', 0)} B "
                        "— a defrag wave can compact it"
                    ),
                    trials_run=trials,
                    latency_s=timeit.default_timer() - t0,
                    static_prior=used_prior,
                    revisit_on=REVISIT_DEFRAG,
                )
                self._note(rec, dec)
                return dec

        slack = None
        if rec.deadline_at is not None:
            import time as _time

            slack = rec.deadline_at - _time.monotonic()
        weight = compute_weight(
            rec.request.priority, slack, _min_feasible_runtime(task)
        )
        if self.tenancy is not None:
            # Weighted fair share: scale (never override) the priority/
            # deadline weight by how far the tenant sits from its slice.
            weight *= self.tenancy.fair_share_multiplier(
                rec.tenant, self.queue.live_by_tenant()
            )
        rec.weight = weight
        # Scheduling-only hints: the replanner's eviction policies order by
        # task.hints["priority"]; profile_cache.task_signature excludes both
        # keys so they never perturb warm cache hits.
        hints = getattr(task, "hints", None)
        if isinstance(hints, dict):
            hints["priority"] = float(rec.request.priority)
            if rec.request.deadline_s is not None:
                hints["deadline"] = float(rec.request.deadline_s)
        dec = AdmissionDecision(
            ADMIT, reason="static prior" if used_prior else "ok",
            trials_run=trials, weight=weight,
            latency_s=timeit.default_timer() - t0,
            static_prior=used_prior,
        )
        if self.tenancy is not None:
            self.tenancy.note_admit(rec.tenant)
            # sanctioned-unlocked: _pass_admitted is drain-pass scratch,
            # touched only by the single scheduler thread that calls
            # begin_pass()/admit() back-to-back — no concurrent access.
            self._pass_admitted[rec.tenant] = (
                self._pass_admitted.get(rec.tenant, 0) + 1
            )
        self._note(rec, dec)
        return dec

    # -------------------------------------------------------------- tenancy
    def _tenant_gate(self, rec: JobRecord, t0: float):
        """Quota verdict for the arrival's tenant, or None to proceed.

        Chip-seconds exhaustion is terminal (REJECT: the budget never
        refills by waiting); a full ``max_live_jobs`` window DEFERs — the
        tenant's own completions free slots, and the requeue re-admits
        warm. The gate counts *admitted* (SCHEDULED/RUNNING) jobs, not
        queued arrivals: counting a burst's own queued siblings would
        defer the whole burst forever.
        """
        tenant = rec.tenant
        quota = self.tenancy.quota(tenant)
        if self.tenancy.budget_exhausted(tenant):
            return AdmissionDecision(
                REJECT,
                reason=(
                    f"tenant {tenant!r} chip-seconds budget exhausted "
                    f"({self.tenancy.charged(tenant):.1f}s burned of "
                    f"{quota.chip_seconds:.1f}s)"
                ),
                latency_s=timeit.default_timer() - t0,
            )
        if quota.max_live_jobs is not None:
            admitted = (self.queue.admitted_tenant(tenant)
                        + self._pass_admitted.get(tenant, 0))
            if admitted >= quota.max_live_jobs:
                return AdmissionDecision(
                    DEFER,
                    reason=(
                        f"tenant {tenant!r} has {admitted} admitted job(s), "
                        f"at its max_live_jobs quota {quota.max_live_jobs}"
                    ),
                    latency_s=timeit.default_timer() - t0,
                    revisit_on=REVISIT_INTERVAL,
                )
        return None

    # -------------------------------------------------------------- memlens
    def _memlens_verdict(self, task, topology: SliceTopology):
        """Zero-trial memory verdict (or None). Restricted to this
        controller's technique roster; fails open on any error."""
        try:
            from saturn_tpu.analysis.memlens import passes as ml_passes
            from saturn_tpu.parallel import BUILTIN_TECHNIQUES

            names = self.technique_names or sorted(BUILTIN_TECHNIQUES)
            techniques = {
                n: (BUILTIN_TECHNIQUES[n]()
                    if isinstance(BUILTIN_TECHNIQUES[n], type)
                    else BUILTIN_TECHNIQUES[n])
                for n in names if n in BUILTIN_TECHNIQUES
            }
            return ml_passes.coldstart_verdict(
                task, topology, techniques=techniques)
        except Exception as e:
            logger.debug("admission: memlens verdict skipped: %r", e)
            return None

    # ------------------------------------------------------------ shardflow
    def _synthesize_priors(self, rec: JobRecord, task,
                           topology: SliceTopology) -> bool:
        """Fill the task's grid with static-prior strategies; never raises
        (an untraceable task just falls through to the trial sweep)."""
        try:
            from saturn_tpu.analysis.shardflow import prior as sf_prior

            added = sf_prior.synthesize_strategies(
                task, topology, technique_names=self.technique_names,
            )
        except Exception as e:
            logger.warning(
                "admission: shardflow prior failed for %s (%r); falling "
                "back to the trial sweep", rec.job_id, e,
            )
            return False
        if added:
            logger.info(
                "admission: %s admitted on shardflow static priors at "
                "sizes %s (no trials)", rec.job_id, added,
            )
        return bool(added)

    def _audit_priors(self, rec: JobRecord, task) -> None:
        """Emit SAT-X005 for superseded priors (warn-only, never gates)."""
        try:
            from saturn_tpu.analysis.shardflow import prior as sf_prior

            diags = sf_prior.audit_task(task)
            # Same audit stream, second consumer: measured step times on
            # formerly-overlapped priors move the per-op-class overlap
            # factors, so the next cold-start/admission/solver pass prices
            # overlap from evidence instead of the static seeds. Warn-only
            # path — a calibration failure must never gate admission.
            sf_prior.calibrate_overlap_factors([task])
        except Exception:
            return
        for d in diags:
            logger.warning("admission: %s %s", rec.job_id, d.message)
            metrics.event(
                "shardflow_audit", job=rec.job_id, task=rec.name,
                **d.to_json(),
            )

    def _note(self, rec: JobRecord, dec: AdmissionDecision) -> None:
        if self.journal is not None:
            # sanctioned-unlocked: journal buffering is internally locked;
            # admission runs only on the scheduler thread (see begin_pass)
            self.journal.append(
                "job_admission", job=rec.job_id, task=rec.name,
                decision=dec.action, reason=dec.reason,
                trials_run=dec.trials_run, weight=round(dec.weight, 6),
                static_prior=dec.static_prior, tenant=rec.tenant,
            )
        self._note_deferred(rec, dec)
        metrics.event(
            "job_admitted", job=rec.job_id, task=rec.name,
            decision=dec.action, reason=dec.reason,
            trials_run=dec.trials_run, warm=dec.trials_run == 0,
            weight=round(dec.weight, 6), latency_s=round(dec.latency_s, 6),
            static_prior=dec.static_prior,
        )
        logger.info(
            "admission: %s %s (%s; %d trials, weight %.3f, %.3fs)",
            rec.job_id, dec.action, dec.reason or "ok", dec.trials_run,
            dec.weight, dec.latency_s,
        )

    def _note_deferred(self, rec: JobRecord, dec: AdmissionDecision) -> None:
        """Maintain the DEFER pool + journal ``job_deferred`` visibility
        records. A record lands only on the *first* defer of a job or when
        its reason class (revisit_on) changes — re-defers on the same
        grounds would otherwise flood the journal every interval."""
        if dec.action != DEFER:
            self.deferred.pop(rec.job_id, None)
            return
        prev = self.deferred.get(rec.job_id)
        entry = {
            "task": rec.name,
            "tenant": rec.tenant,
            "reason": dec.reason,
            "revisit_on": dec.revisit_on,
            "deferred_at": prev["deferred_at"] if prev else time.time(),
            "count": (prev["count"] + 1) if prev else 1,
        }
        self.deferred[rec.job_id] = entry
        changed = prev is None or prev["revisit_on"] != dec.revisit_on
        if changed and self.journal is not None:
            # sanctioned-unlocked: journal buffering is internally locked;
            # admission runs only on the scheduler thread (see begin_pass)
            self.journal.append(
                "job_deferred", job=rec.job_id, task=rec.name,
                tenant=rec.tenant, reason=dec.reason,
                revisit_on=dec.revisit_on,
                at=round(entry["deferred_at"], 6),
            )
