"""Online job service: the always-on face of the batch orchestrator.

The reference (and our ``orchestrate``) solves SPASE for a *fixed batch* of
tasks — a closed world. This package turns the same machinery (interval loop,
persistent profile cache, ElasticReplanner) into a long-running scheduler
that accepts work over time:

- :mod:`saturn_tpu.service.queue` — thread-safe submission queue with typed
  :class:`JobRequest` and the job lifecycle state machine
  (QUEUED → PROFILING → SCHEDULED → RUNNING → DONE/FAILED/EVICTED).
- :mod:`saturn_tpu.service.admission` — admission controller: profiles
  arrivals through the profile cache / cost-model pruning (warm arrivals
  admit in O(cache lookup), zero trials), rejects or defers work that cannot
  fit the mesh, computes priority/deadline weights for the solver objective.
- :mod:`saturn_tpu.service.server` — the service loop: drain arrivals,
  retire completions, incremental warm-started re-solve each interval,
  ElasticReplanner fallback on admission pressure or topology change.
- :mod:`saturn_tpu.service.client` — in-process client
  (``submit / status / wait / cancel``) and the ``python -m
  saturn_tpu.service`` CLI that tails the JSONL metrics stream.
- :mod:`saturn_tpu.service.gateway` — JSONL-over-TCP network front door:
  :class:`GatewayServer` (idempotent submission, per-request deadlines,
  backpressure windows, graceful drain) and the retrying
  :class:`GatewayClient` with the same client surface.

See ``docs/architecture.md`` ("Online service") for the state machine and
the divergence notes in ``docs/parity.md``.
"""

from saturn_tpu.service.admission import AdmissionController, AdmissionDecision
from saturn_tpu.service.client import ServiceClient
from saturn_tpu.service.gateway import GatewayClient, GatewayError, GatewayServer
from saturn_tpu.service.queue import (
    JobRecord,
    JobRequest,
    JobState,
    SubmissionQueue,
)
from saturn_tpu.service.server import SaturnService

__all__ = [
    "AdmissionController",
    "AdmissionDecision",
    "GatewayClient",
    "GatewayError",
    "GatewayServer",
    "JobRecord",
    "JobRequest",
    "JobState",
    "SaturnService",
    "ServiceClient",
    "SubmissionQueue",
]
