"""The service loop: an always-on incremental SPASE scheduler.

Reuses the batch orchestrator's machinery wholesale — ``engine.forecast`` /
``engine.execute`` for the gang-executed interval, ``milp.resolve`` for the
introspective re-solve, ``fold_realized_feedback`` for the estimate loop,
the ElasticReplanner for topology changes — but runs forever, folding queue
arrivals into the live plan at every interval boundary:

    loop:  health poll -> drain arrivals (admission) -> cancel sweep ->
           admission-pressure shed -> incremental warm-started re-solve ->
           forecast -> gang-execute -> feedback fold ->
           requeue preempted / retry failed / retire completed

The re-solve is *incremental*: ``milp.solve`` extends the previous plan's
fix-and-optimize warm start by inserting new arrivals into free
(block, time) slots (``warm_schedule(insert_missing=True)``), so an arrival
never degrades the incumbent the solver starts from, and per-job
priority/deadline weights bias the objective's start-time tiebreak.

Single-host only (the service mutates the task set from one process's
view; multi-controller queue consensus is future work — see
``docs/parity.md``).
"""

from __future__ import annotations

import logging
import threading
import time
import timeit
from typing import Any, Dict, List, Optional

from saturn_tpu.analysis.concurrency import sched_point
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.executor import engine
from saturn_tpu.executor.orchestrator import (
    _handle_topology_change,
    fold_realized_feedback,
)
from saturn_tpu.service.admission import (
    ADMIT,
    DEFER,
    AdmissionController,
    compute_weight,
)
from saturn_tpu.service.queue import (
    TERMINAL_STATES,
    JobRecord,
    JobState,
    SubmissionQueue,
)
from saturn_tpu.solver import anytime, milp
from saturn_tpu.utils import metrics

logger = logging.getLogger("saturn_tpu")


def _estimate_chip_seconds(task, batches: int) -> float:
    """Chip-second burn for ``batches`` realized iterations: the task's
    cheapest feasible (size, strategy) point prices one batch at
    ``per_batch_time * size`` — a minimum-burn basis, so billing reflects
    the job's own cost floor rather than whatever placement the scheduler
    happened to pick this interval. Priced off ``per_batch_time`` (stable
    across the run), NOT ``runtime``: the feedback fold re-derives runtime
    from *remaining* work, so at completion — exactly when this bills —
    runtime has decayed to zero."""
    feas = task.feasible_strategies()
    if not feas or batches <= 0:
        return 0.0
    rates = [s.per_batch_time * g for g, s in feas.items()
             if (s.per_batch_time or 0.0) > 0.0]
    if not rates:
        return 0.0
    return batches * min(rates)


class SaturnService:
    """Long-running scheduler over one slice topology.

    ``start()`` launches the loop on a daemon thread; submit through a
    :class:`~saturn_tpu.service.client.ServiceClient` (or ``self.queue``
    directly); ``stop()`` drains live work then exits (``stop(abort=True)``
    evicts everything still live).
    """

    def __init__(
        self,
        topology: Optional[SliceTopology] = None,
        interval: float = 1.0,
        threshold: float = 0.0,
        solver_time_limit: Optional[float] = None,
        metrics_path: Optional[str] = None,
        technique_names: Optional[List[str]] = None,
        profile_cache: Any = None,
        prune: bool = True,
        parallel_trials: Optional[int] = None,
        health_monitor=None,
        fault_injector=None,
        recovery_policy: str = "pause-resolve-resume",
        replan_degrade_factor: float = 2.0,
        pressure_policy: str = "evict-lowest-priority",
        durability_dir: Optional[str] = None,
        task_provider=None,
        crash_barrier=None,
        health_guardian=None,
        tenancy=None,
        compile_ahead=None,
        poll_s: float = 0.05,
        log: bool = False,
    ):
        if log:
            logging.basicConfig(level=logging.INFO)
        from saturn_tpu.core import distributed

        if distributed.is_multihost():
            raise ValueError("the online service is single-host only")
        self.topology = topology if topology is not None else SliceTopology()
        self._base_topo = self.topology
        self.interval = interval
        self.threshold = threshold
        self.solver_time_limit = (
            solver_time_limit if solver_time_limit is not None
            else interval / 2
        )
        self.metrics_path = metrics_path
        self.poll_s = poll_s
        self.pressure_policy = pressure_policy

        self.queue = SubmissionQueue()
        self.admission = AdmissionController(
            self.topology, self.queue, technique_names=technique_names,
            profile_cache=profile_cache, prune=prune,
            parallel_trials=parallel_trials,
        )

        if fault_injector is None:
            from saturn_tpu.resilience.faults import FaultInjector

            fault_injector = FaultInjector.from_env()
        if fault_injector is not None and health_monitor is None:
            from saturn_tpu.resilience.health import FleetHealthMonitor

            health_monitor = FleetHealthMonitor.for_topology(self.topology)
        self.health = health_monitor
        self.faults = fault_injector
        self.replanner = None
        if self.health is not None:
            from saturn_tpu.resilience.replan import ElasticReplanner

            self.replanner = ElasticReplanner(
                policy=recovery_policy, degrade_factor=replan_degrade_factor
            )

        self._stop = threading.Event()
        self._abort = threading.Event()
        self._ready = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

        # Crash-safe durability: open (and recover) the write-ahead journal,
        # replay it into the queue, and warm-start the first re-solve from
        # the last committed plan. ``killed`` is set only by the crash
        # harness's simulated SIGKILL.
        self.journal = None
        self.task_provider = task_provider
        self.killed = False
        self._recovered_plan: Optional[milp.Plan] = None
        self._recovered_health: Optional[tuple] = None
        #: dedup_key -> job_id replayed from the journal: the network
        #: gateway seeds its idempotency table from this so a client retry
        #: that straddles a restart still maps to the original admission.
        self.recovered_dedup: Dict[str, str] = {}
        #: monotonic timestamp of the last admission-pressure eviction; the
        #: gateway reads it to shrink its inflight window while the shedder
        #: is active (wire-level backpressure follows mesh-level pressure).
        self.last_pressure_shed: Optional[float] = None
        #: Tenancy subsystem (``saturn_tpu/tenancy``): the fairness/quota
        #: ledger shared by admission, the gateway replicas, and the
        #: pressure shedder — plus the background compile-ahead pool that
        #: starts AOT compilation the moment admission picks a strategy.
        #: Wired *before* recovery so replayed ``tenant_charge`` records
        #: re-seat the ledger and both components get the journal.
        self.tenancy = tenancy
        self.compile_ahead = compile_ahead
        self.admission.tenancy = tenancy
        #: Lease epoch/owner folded from journaled ``gateway_lease``
        #: records: a restarted gateway replica set seeds its
        #: ``ReplicaLease`` from these, so a deposed pre-crash epoch can
        #: never be re-issued after a failover + restart.
        self.recovered_lease_epoch = 0
        self.recovered_lease_owner: Optional[str] = None
        # Grow coordinator (resilience/grow.py): grow-event journaling,
        # guardian unbench, DEFER-backlog drain attribution and two-phase
        # defrag waves. Built before recovery (which seeds its wave
        # sequence and the checkpoint map below); the journal attaches
        # right after recovery opens it.
        from saturn_tpu.resilience.grow import GrowCoordinator

        self.grow = GrowCoordinator(journal=None)
        #: task name -> last published checkpoint path (fed by the publish
        #: hook in ``_run``, re-seeded from the journal on recovery); the
        #: defrag wave's publish phase re-journals the victim's current
        #: publication durably after its intent.
        self._last_ckpt: Dict[str, str] = {}
        if durability_dir is not None:
            self._recover_from(durability_dir, crash_barrier)
        elif crash_barrier is not None:
            raise ValueError("crash_barrier requires durability_dir")
        self.grow.journal = self.journal
        #: current committed plan, mirrored from the loop local so the
        #: admission occupancy gate can read it.
        self._plan: Optional[milp.Plan] = None

        # Training-health guardian (sentinel policy + hung-dispatch
        # watchdog). On by default; pass ``health_guardian=False`` to
        # disable, or a preconfigured TrainingGuardian to tune budgets.
        self.guardian = None
        if health_guardian is not False:
            from saturn_tpu.health import TrainingGuardian

            g = health_guardian
            if g is None:
                g = TrainingGuardian(journal=self.journal)
            elif g.journal is None and self.journal is not None:
                g.journal = self.journal
            self.guardian = g
            if self._recovered_health is not None:
                quarantined, detached, live_tasks = self._recovered_health
                g.restore(quarantined, detached, live_tasks)
        self._recovered_health = None

    def _recover_from(self, durability_dir: str, crash_barrier) -> None:
        """Open the journal (rolling torn tails back to the durable cut),
        rebuild the job registry from the committed records, and reconcile
        journaled checkpoint publications against the disk."""
        from saturn_tpu.durability import journal as jmod
        from saturn_tpu.durability import recovery as rmod

        self.journal = jmod.Journal(durability_dir, barrier=crash_barrier)
        self.queue.observer = self._observe_job
        self.admission.journal = self.journal
        if self.tenancy is not None and self.tenancy.journal is None:
            self.tenancy.journal = self.journal
        if (self.compile_ahead is not None
                and getattr(self.compile_ahead, "journal", None) is None):
            self.compile_ahead.journal = self.journal
        state = rmod.replay_service_state(durability_dir)
        self.recovered_dedup = dict(state.dedup)
        self.recovered_lease_epoch = state.lease_epoch
        self.recovered_lease_owner = state.lease_owner
        if self.tenancy is not None and state.tenant_charges:
            # Exactly-once burn accounting across the crash: the folded
            # journal totals replace (never add to) the fresh counters.
            self.tenancy.restore(state.tenant_charges)
        if state.checkpoints:
            # The newest checkpoint that survives verification becomes the
            # task's authoritative publication again: a post-restart defrag
            # wave verifies the victim's checkpoint through this map, so
            # leaving it empty would roll back every wave until the next
            # fresh publication.
            for name, path in rmod.reconcile_checkpoints(
                    state.checkpoints).items():
                if path is not None:
                    self._last_ckpt[name] = path
        # Wave ids embed (interval, seq) and the interval counter restarts
        # from zero: seed the sequence past the journal's highest so a
        # post-restart wave can never reuse a closed (wave, task) key.
        self.grow.seed_wave_seq(state.defrag_waves)
        # Close every defrag move the crash left half-done — exactly once:
        # resume (done) iff the victim's checkpoint was durably published
        # AFTER the intent, else roll back. Closed intents never re-enter
        # pending_migrations on later replays, so a second restart is a
        # no-op here.
        resume, rollback = state.resolve_pending_migrations()
        for rec in resume:
            self.journal.log(
                "migration_done", wave=rec.get("wave", ""),
                task=rec.get("task", ""), recovered=True,
            )
            logger.info(
                "recovery: defrag move %s/%s resumed (checkpoint published "
                "after intent)", rec.get("wave"), rec.get("task"),
            )
        for rec in rollback:
            self.journal.log(
                "migration_rollback", wave=rec.get("wave", ""),
                task=rec.get("task", ""), cause="recovery", recovered=True,
            )
            logger.info(
                "recovery: defrag move %s/%s rolled back (no published "
                "checkpoint after intent)", rec.get("wave"), rec.get("task"),
            )
        if state.jobs:
            restored = rmod.build_restore_records(state, self.task_provider)
            for rec in restored:
                self.queue.restore(rec)
                j = state.jobs.get(rec.job_id)
                if (j is not None and not j.terminal
                        and rec.state is JobState.DONE):
                    # Fully-realized job whose DONE verdict died un-fsync'd:
                    # re-journal the terminal record so later incarnations
                    # replay it as terminal directly.
                    self._observe_job("state", rec)
            if state.quarantined or state.detached:
                # Replayed health state is re-applied once the guardian is
                # built (end of __init__) — only live rebuilt tasks carry a
                # quarantine skip-list (terminal stubs have none).
                self._recovered_health = (
                    state.quarantined, state.detached,
                    [r.task for r in restored
                     if r.state is JobState.QUEUED],
                )
            logger.info(
                "recovery: restored %d job(s) from %s (%d live)",
                len(restored), durability_dir, len(state.live_jobs()),
            )
        if state.plan is not None:
            try:
                self._recovered_plan = milp.Plan.from_json(state.plan)
            except Exception:
                logger.exception(
                    "recovery: last committed plan unusable — first "
                    "re-solve starts cold"
                )
            else:
                # Static-verification quarantine (never trust a replayed
                # plan_commit blindly): a committed plan that fails the
                # verifier — torn by a crash mid-repair, or written by a
                # buggy older build — must not warm-start execution. Drop
                # it on the record and fall back to a fresh solve.
                from saturn_tpu import analysis

                report = analysis.verify_plan(
                    self._recovered_plan, subject="journal-replay"
                )
                if not report.ok:
                    codes = sorted({d.code for d in report.errors})
                    self._recovered_plan = None
                    logger.warning(
                        "recovery: replayed plan fails static verification "
                        "(%s) — quarantined; first re-solve starts cold",
                        codes,
                    )
                    self.journal.log("plan_quarantine",
                                     source="journal-replay", codes=codes)
        self.journal.log(
            "recovery", incarnation=state.incarnations + 1,
            replayed_seq=state.last_seq, replayed_records=state.n_records,
            live_jobs=len(state.live_jobs()),
        )

    def _observe_job(self, event: str, rec: JobRecord, **fields) -> None:
        """Queue observer → write-ahead journal (called under the queue
        lock; lock order is queue → journal, never the reverse).

        Submissions group-commit immediately — ``submit()`` returning is the
        client's durable ack. Lifecycle edges are buffered and ride the next
        group commit, except terminal states which commit so a completed /
        failed / evicted verdict is never lost."""
        jnl = self.journal
        if jnl is None:
            return
        if event == "submitted":
            jnl.log(
                "job_submitted", job=rec.job_id, task=rec.name,
                priority=rec.request.priority,
                deadline_s=rec.request.deadline_s,
                max_retries=rec.request.max_retries,
                total_batches=getattr(rec.task, "total_batches", None),
                spec=rec.request.spec,
                dedup_key=rec.request.dedup_key,
                tenant=rec.request.tenant,
            )
        elif event == "recovered":
            jnl.append(
                "job_recovered", job=rec.job_id, task=rec.name,
                requeues=rec.requeues,
            )
        elif event == "state":
            jnl.append(
                "job_state", job=rec.job_id, state=rec.state.value,
                attempts=rec.attempts, requeues=rec.requeues,
                error=rec.error,
            )
            if rec.state in (
                JobState.DONE, JobState.FAILED, JobState.EVICTED
            ):
                jnl.commit()

    # -------------------------------------------------------------- control
    def start(self) -> "SaturnService":
        if self._thread is not None:
            raise RuntimeError("service already started")
        self._thread = threading.Thread(
            target=self._run_guarded, name="saturn-service", daemon=True
        )
        self._thread.start()
        # Wait for the loop to configure its metrics scope: a submit racing
        # ahead of it would drop the job_submitted event.
        self._ready.wait(timeout=10.0)
        if self._error is not None:
            raise RuntimeError("service failed to start") from self._error
        return self

    def stop(self, abort: bool = False, timeout: Optional[float] = None) -> None:
        """Stop the loop. Default drains: live jobs (and anything already
        queued) run to completion first. ``abort=True`` evicts all live work
        at the next interval boundary instead."""
        if abort:
            self._abort.set()
        self._stop.set()  # an idle loop re-checks every poll_s
        if self._thread is not None:
            self._thread.join(timeout)
        if self.compile_ahead is not None and not self.killed:
            self.compile_ahead.close()
        if self._error is not None and not self.killed:
            raise RuntimeError("service loop crashed") from self._error

    def _run_guarded(self) -> None:
        from saturn_tpu.resilience.crash import SimulatedKill

        try:
            self._run()
        except SimulatedKill as e:
            # Simulated process death: a real SIGKILL runs no handlers, so
            # do NOT fail jobs, flush the journal, or clean anything up —
            # the in-memory state just stops existing. Recovery is the next
            # incarnation's problem (that's the point).
            self.killed = True
            self._error = e
            self._ready.set()
            logger.warning("service loop killed by crash harness: %s", e)
        except BaseException as e:  # surfaced by stop()/wait()
            self._error = e
            self._ready.set()
            logger.exception("service loop crashed")
            # fail every live job so client wait() calls unblock
            for rec in self.queue.jobs():
                if rec.state not in (
                    JobState.DONE, JobState.FAILED, JobState.EVICTED
                ):
                    try:
                        self.queue.mark(
                            rec, JobState.FAILED,
                            error=f"service crashed: {e!r}",
                        )
                    except RuntimeError:
                        pass

    # ----------------------------------------------------------------- loop
    def _run(self) -> None:
        topo = self.topology
        tlimit = self.solver_time_limit
        # The last committed plan warm-starts the first post-restart
        # re-solve — recovered jobs land back in (approximately) the slots
        # they durably held.
        plan: Optional[milp.Plan] = self._recovered_plan
        self._recovered_plan = None
        jobs: Dict[str, JobRecord] = {}   # task name -> live admitted record
        interval_index = 0
        jnl = self.journal

        from saturn_tpu.utils import checkpoint as ckpt_mod

        ckpt_hook = None
        if jnl is not None:
            def ckpt_hook(task_name, path):  # journal every publication
                jnl.append("ckpt_published", task=task_name, path=path)
                self._last_ckpt[task_name] = path

            ckpt_mod.add_publish_hook(ckpt_hook)
        try:
            self._run_loop(topo, tlimit, plan, jobs, interval_index)
        finally:
            if ckpt_hook is not None:
                ckpt_mod.remove_publish_hook(ckpt_hook)

    def _run_loop(self, topo, tlimit, plan, jobs, interval_index) -> None:
        jnl = self.journal
        guardian = self.guardian
        self._plan = plan
        # Occupancy gate: an arrival whose HBM footprint can't fit around
        # running tasks' pinned live state DEFERs (revisit_on="defrag")
        # instead of admitting into an OOM; a defrag wave re-opens it.
        self.admission.occupancy_gate = self.grow.occupancy_gate(
            lambda: [r.task for r in jobs.values()],
            lambda: self._plan,
        )

        with metrics.scoped(self.metrics_path):
            self._ready.set()
            while True:
                sched_point("service.interval")
                if self._stop.is_set():
                    if self._abort.is_set():
                        for rec in list(jobs.values()):
                            self._evict(jobs, rec, "service aborted")
                        for rec in self.queue.drain():
                            self.admission.deferred.pop(rec.job_id, None)
                            self.queue.mark(rec, JobState.EVICTED,
                                            error="service aborted")
                            metrics.event("job_evicted", job=rec.job_id,
                                          task=rec.name,
                                          reason="service aborted")
                    if not jobs and self.queue.depth() == 0:
                        break
                elif not jobs and self.queue.depth() == 0:
                    # idle: park on the queue condition, no busy loop
                    self.queue.wait_for_arrival(timeout=self.poll_s)
                    continue

                # 1. health poll / topology change (elastic hook, as in the
                #    batch loop)
                grew = False
                if self.health is not None:
                    if self.faults is not None:
                        self.faults.apply_due(interval_index, self.health)
                    change = self.health.poll()
                    if change is not None and change.kind in ("shrink", "grow"):
                        evicted_names: dict = {}
                        tasks = [r.task for r in jobs.values()]
                        tasks, topo, plan = _handle_topology_change(
                            tasks, self._base_topo, self.health,
                            self.replanner, change, plan, tlimit,
                            evicted_names,
                        )
                        self._plan = plan
                        for name in evicted_names:
                            rec = jobs.pop(name, None)
                            if rec is not None:
                                self.queue.mark(
                                    rec, JobState.EVICTED,
                                    error=evicted_names[name],
                                )
                                metrics.event(
                                    "job_evicted", job=rec.job_id,
                                    task=name, reason="topology-change",
                                )
                        if jnl is not None:
                            jnl.append("topology_change",
                                       **change.to_fields())
                        if change.kind == "grow":
                            # Recovery half of elasticity: journal the grow
                            # event and short-circuit guardian benches so
                            # parked work re-admits THIS interval (fault
                            # streaks untouched).
                            grew = True
                            self.grow.note_grow(
                                change, interval_index, guardian=guardian,
                                n_deferred=len(self.admission.deferred),
                                capacity=topo.capacity,
                            )
                    elif change is not None:  # degrade: advisory only
                        metrics.event("topology_change", **change.to_fields())

                # 2. drain arrivals through admission (deferred jobs re-enter
                #    here every interval; a grow event or defrag wave below
                #    is what actually changes their verdict)
                deferred_before = set(self.admission.deferred)
                newly_admitted = self._drain_arrivals(
                    jobs, topo, interval_index, guardian
                )

                # 2b. defrag wave: deferred work blocked on pinned HBM
                #     (revisit_on="defrag") gets an active compaction pass —
                #     on every grow event and on the opportunistic poll.
                if self.grow.defrag_due(interval_index, grew):
                    wave_id = self._maybe_defrag_wave(
                        jobs, topo, plan, interval_index
                    )
                    if wave_id is not None:
                        # Re-drain so an unblocked gang admits this interval.
                        newly_admitted.extend(self._drain_arrivals(
                            jobs, topo, interval_index, guardian
                        ))
                drained = sorted(
                    deferred_before
                    & {r.job_id for r in newly_admitted}
                )
                if drained:
                    self.grow.note_drained(
                        drained, interval_index,
                        trigger="grow" if grew else "interval",
                    )

                # 3. cancel sweep over admitted jobs
                for rec in list(jobs.values()):
                    if rec.cancel_requested:
                        self._evict(jobs, rec, "cancelled")

                # 4. admission pressure: if the greedy projection blows the
                #    tightest deadline, shed low-priority work through the
                #    replanner's eviction policy (same code path a topology
                #    shrink uses).
                self._shed_pressure(jobs, topo, plan)

                if not jobs:
                    plan = None
                    self._plan = None
                    metrics.event("queue_depth", depth=self.queue.depth(),
                                  live=self.queue.live(), active=0)
                    interval_index += 1
                    if self.queue.depth():
                        # only deferred work left (e.g. waiting out a
                        # degraded mesh): don't spin the drain/defer cycle
                        time.sleep(self.poll_s)
                    continue

                # 5. incremental re-solve, warm-started from the live plan,
                #    weighted by priority/deadline urgency (recomputed each
                #    cycle: slack shrinks as deadlines approach)
                tasks = [r.task for r in jobs.values()]
                weights = {
                    r.name: self._weight(r) for r in jobs.values()
                }
                t_solve = timeit.default_timer()
                # Anytime tier ladder (solver/anytime.py): the re-solve
                # always lands inside the deadline derived from the interval
                # budget (tlimit = solver_time_limit, default interval/2;
                # SATURN_TPU_SOLVE_DEADLINE overrides), falling down the
                # incremental -> partition -> LP-rounding -> greedy tiers
                # when the queue outgrows the exact MILP.
                candidate = anytime.anytime_resolve(
                    tasks, topo, plan, self.interval, self.threshold,
                    deadline=tlimit, weights=weights,
                    coschedule_exclude=(guardian.detached_names()
                                        if guardian is not None else None),
                    source="service",
                )
                # Mandatory adoption gate (service re-solve path): a
                # candidate the static verifier rejects is quarantined and
                # the service keeps last cycle's verified plan — which also
                # stays the journal's recovery warm start, because the
                # quarantined plan is never committed.
                from saturn_tpu import analysis

                try:
                    analysis.verify_or_raise(
                        candidate, topology=topo, tasks=tasks,
                        source="service-re-solve",
                    )
                except analysis.PlanVerificationError as e:
                    codes = sorted({d.code for d in e.report.errors})
                    logger.error("re-solve plan quarantined (%s): %s",
                                 codes, e)
                    metrics.event("plan_quarantine",
                                  source="service-re-solve", codes=codes)
                    if jnl is not None:
                        jnl.log("plan_quarantine", interval=interval_index,
                                source="service-re-solve", codes=codes)
                    if plan is None:
                        raise  # no verified fallback: surface the failure
                else:
                    plan = candidate
                self._plan = plan
                metrics.event(
                    "solve", makespan_s=plan.makespan, n_tasks=len(tasks),
                    solve_s=round(timeit.default_timer() - t_solve, 6),
                )
                if jnl is not None:
                    # The committed plan is the recovery warm start; commit
                    # here so a kill mid-interval restarts from THIS plan.
                    jnl.append(
                        "plan_commit", interval=interval_index,
                        makespan=plan.makespan, plan=plan.to_json(),
                    )
                    jnl.commit()
                for rec in newly_admitted:
                    if rec.name not in jobs:
                        continue  # evicted by the cancel sweep / load shed
                    a = plan.assignments.get(rec.name)
                    self.queue.mark(rec, JobState.SCHEDULED)
                    metrics.event(
                        "job_scheduled", job=rec.job_id, task=rec.name,
                        start_s=a.start if a else None,
                        size=a.apportionment if a else None,
                        weight=round(rec.weight, 6),
                    )

                # 6. forecast + gang-execute one interval
                run_tasks, batches, completed = engine.forecast(
                    tasks, self.interval, plan
                )
                errors: dict = {}
                if run_tasks:
                    errors = engine.execute(
                        run_tasks, batches, self.interval, plan, topo,
                        failure_policy="drop", health=self.health,
                        faults=self.faults, interval_index=interval_index,
                        on_task_start=self._make_on_start(jobs),
                        on_task_done=self._make_on_done(jobs),
                        guardian=guardian,
                    )
                    if guardian is not None:
                        for t in run_tasks:
                            if t.name not in errors:
                                guardian.note_success(t.name)
                    if jnl is not None:
                        # Work ran; its task_progress records are buffered
                        # but NOT yet durable — the canonical lost-progress
                        # kill window.
                        jnl.barrier("mid-interval", interval=interval_index)
                else:
                    # every start is beyond this interval: resolve() slides
                    # work forward next cycle; don't spin
                    time.sleep(min(self.poll_s, self.interval))

                # 7. estimate feedback (EWMA fold + profile-cache write-back)
                for name, (old, new) in sorted(
                    fold_realized_feedback(run_tasks).items()
                ):
                    metrics.event("estimate_update", task=name,
                                  profiled_s=round(old, 6),
                                  updated_s=round(new, 6))

                from saturn_tpu.resilience.faults import PreemptedError

                preempted = {n: e for n, e in errors.items()
                             if isinstance(e, PreemptedError)}
                failed = {n: e for n, e in errors.items()
                          if n not in preempted}

                # 8. preemptions requeue THROUGH THE QUEUE — the fleet's
                #    fault, no retry consumed; re-admission is warm (the
                #    strategies survive on the task object).
                for name, err in sorted(preempted.items()):
                    rec = jobs.pop(name)
                    self._release(rec.task, compiled=False)
                    engine.rollback_forecast(rec.task, batches.get(name, 0))
                    metrics.event("task_preempted", task=name,
                                  error=repr(err))
                    self.queue.requeue(rec)
                completed = [t for t in completed if t.name not in preempted]

                # 8b. health faults (sentinel / watchdog): the guardian's own
                #     ledger, NOT charged to the job's max_retries — rollback,
                #     journal the transition (quarantine/detach records are
                #     already durable before the barrier), then requeue with
                #     backoff or evict past the guardian's budget.
                health_errs: Dict[str, BaseException] = {}
                if guardian is not None:
                    health_errs = {n: e for n, e in failed.items()
                                   if guardian.owns(e)}
                    failed = {n: e for n, e in failed.items()
                              if n not in health_errs}
                group_of = (plan.coschedule_group_of()
                            if health_errs else {})
                for name, err in sorted(health_errs.items()):
                    rec = jobs.pop(name)
                    self._release(rec.task, compiled=False)
                    engine.rollback_forecast(rec.task, batches.get(name, 0))
                    decision = guardian.on_fault(
                        rec.task, err, interval_index,
                        in_group=name in group_of,
                    )
                    if jnl is not None:
                        jnl.barrier("post-rollback", task=name,
                                    interval=interval_index)
                    if decision.action == "retry":
                        metrics.event(
                            "task_health_retry", task=name,
                            cause=decision.cause, attempt=decision.attempt,
                            cooldown_intervals=decision.cooldown,
                        )
                        self.queue.requeue(rec)
                    else:
                        self._release(rec.task, compiled=True)
                        self.queue.mark(rec, JobState.FAILED,
                                        error=repr(err))
                        metrics.event("task_failed", task=name,
                                      error=repr(err))
                        metrics.event("job_failed", job=rec.job_id,
                                      task=name, error=repr(err))
                completed = [t for t in completed
                             if t.name not in health_errs]

                # 9. real failures: retry within the job's budget, else FAIL
                for name, err in sorted(failed.items()):
                    rec = jobs[name]
                    rec.attempts += 1
                    self._release(rec.task, compiled=False)
                    if rec.attempts <= rec.request.max_retries:
                        engine.rollback_forecast(
                            rec.task, batches.get(name, 0)
                        )
                        metrics.event("task_retry", task=name,
                                      attempt=rec.attempts, error=repr(err))
                    else:
                        jobs.pop(name)
                        self._release(rec.task, compiled=True)
                        self.queue.mark(rec, JobState.FAILED,
                                        error=repr(err))
                        metrics.event("task_failed", task=name,
                                      error=repr(err))
                        metrics.event("job_failed", job=rec.job_id,
                                      task=name, error=repr(err))
                completed = [t for t in completed if t.name not in failed]

                # 10. retire completions
                for t in completed:
                    rec = jobs.pop(t.name)
                    self._release(rec.task, compiled=True)
                    self.queue.mark(rec, JobState.DONE)
                    metrics.event("task_completed", task=t.name)
                    metrics.event(
                        "job_completed", job=rec.job_id, task=t.name,
                        wait_s=round(
                            (rec.started_at or rec.finished_at)
                            - rec.submitted_at, 6,
                        ),
                        attempts=rec.attempts, requeues=rec.requeues,
                    )

                metrics.event("queue_depth", depth=self.queue.depth(),
                              live=self.queue.live(), active=len(jobs))
                if jnl is not None:
                    # Interval-end group commit: one fsync makes this
                    # interval's realized iterations, lifecycle edges and
                    # checkpoint publications durable together.
                    jnl.commit()
                    jnl.barrier("post-checkpoint", interval=interval_index)
                # Interval boundary for the buffered metrics writer: the
                # JSONL tail CLI follows this file live, so each interval's
                # events must land when its journal records do.
                metrics.flush()
                interval_index += 1

        # Clean shutdown only — a simulated kill unwinds past this (a real
        # SIGKILL would never run it, and recovery must not depend on it).
        if jnl is not None:
            jnl.close()
        logger.info("service loop exited (%d jobs seen)",
                    len(self.queue.jobs()))

    # --------------------------------------------------------------- helpers
    def _drain_arrivals(self, jobs: Dict[str, JobRecord], topo,
                        interval_index: int, guardian) -> List[JobRecord]:
        """One admission pass over the queue (service loop step 2). Also
        called a second time after a defrag wave so a just-unblocked gang
        admits in the same interval instead of the next."""
        newly_admitted: List[JobRecord] = []
        # Reconcile the DEFER pool against terminal exits first: admission
        # pops an entry only on a later ADMIT/REJECT, so a deferred job
        # that leaves the queue terminally without a verdict (e.g. the
        # queue's immediate cancel-evict) would otherwise inflate
        # n_deferred, the backlog views, and defrag blocked_ids forever.
        for job_id in list(self.admission.deferred):
            try:
                if self.queue.get(job_id).state in TERMINAL_STATES:
                    self.admission.deferred.pop(job_id, None)
            except KeyError:
                self.admission.deferred.pop(job_id, None)
        self.admission.begin_pass()
        for rec in self.queue.drain():
            if rec.cancel_requested:
                # Leaving the queue terminally WITHOUT an admission verdict:
                # drop its DEFER-pool entry here (admission only pops on a
                # later ADMIT/REJECT, which will never come), or it inflates
                # n_deferred / backlog views / defrag blocked_ids forever.
                self.admission.deferred.pop(rec.job_id, None)
                self.queue.mark(rec, JobState.EVICTED, error="cancelled")
                metrics.event("job_evicted", job=rec.job_id,
                              task=rec.name, reason="cancelled")
                continue
            if guardian is not None and guardian.benched(
                rec.name, interval_index
            ):
                # Health backoff: still cooling down after a fault —
                # defer re-admission until its resume interval. A grow
                # event short-circuits the bench (grow.note_grow), so
                # parked work passes straight through here.
                self.queue.requeue(rec)
                continue
            dec = self.admission.admit(rec, topo)
            if dec.action == ADMIT:
                jobs[rec.name] = rec
                newly_admitted.append(rec)
                self._prewarm_admitted(rec, topo)
            elif dec.action == DEFER:
                self.queue.requeue(rec)
            else:  # REJECT
                self.queue.mark(rec, JobState.FAILED, error=dec.reason)
        return newly_admitted

    def _maybe_defrag_wave(self, jobs: Dict[str, JobRecord], topo,
                           plan, interval_index: int) -> Optional[str]:
        """Plan + execute one defrag wave over the occupancy-blocked DEFER
        backlog. Returns the wave id, or None when nothing was blocked or
        no compaction helps. Every move is two-phase journaled (see
        ``GrowCoordinator.execute_wave``)."""
        import os as _os

        from saturn_tpu.service.admission import REVISIT_DEFRAG

        blocked_ids = sorted(
            job_id for job_id, e in self.admission.deferred.items()
            if e.get("revisit_on") == REVISIT_DEFRAG
        )
        if not blocked_ids or plan is None:
            return None
        blocked_tasks = []
        for job_id in blocked_ids:
            try:
                blocked_tasks.append(self.queue.get(job_id).task)
            except KeyError:
                continue
        if not blocked_tasks:
            return None
        live_tasks = [r.task for r in jobs.values()]
        wave = self.grow.plan_wave(blocked_tasks, live_tasks, topo, plan)
        if wave.empty:
            return None

        jnl = self.journal

        def publish(task) -> bool:
            # The victim's checkpoint is current at every interval boundary
            # (finalization = checkpoint write + live-state republish);
            # re-journal the publication durably AFTER the move's intent so
            # a kill before migration_done resumes instead of rolling back.
            path = self._last_ckpt.get(task.name)
            if path is None or not _os.path.exists(path):
                return False  # nothing durable to resume from: roll back
            if jnl is not None:
                jnl.log("ckpt_published", task=task.name, path=path,
                        wave_republish=True)
            return True

        return self.grow.execute_wave(
            wave, {t.name: t for t in live_tasks}, interval_index,
            publish_fn=publish,
        )

    def _weight(self, rec: JobRecord) -> float:
        slack = None
        if rec.deadline_at is not None:
            slack = rec.deadline_at - time.monotonic()
        feas = rec.task.feasible_strategies()
        est = min((s.runtime for s in feas.values()), default=0.0)
        rec.weight = compute_weight(rec.request.priority, slack, est)
        return rec.weight

    def _make_on_start(self, jobs: Dict[str, JobRecord]):
        def on_start(name: str) -> None:
            rec = jobs.get(name)
            if rec is not None and rec.state is JobState.SCHEDULED:
                self.queue.mark(rec, JobState.RUNNING)

        return on_start

    def _make_on_done(self, jobs: Dict[str, JobRecord]):
        """Engine per-task completion hook → buffered ``task_progress``
        records (durable at the interval-end group commit). This is the
        exactly-once ledger: recovery subtracts these from the budget, so a
        batch is journaled only after its iterations really ran. With a
        tenant ledger wired, each realized batch also burns the owning
        tenant's chip-seconds (same buffered-append durability contract)."""
        jnl = self.journal
        tenancy = self.tenancy
        if jnl is None and tenancy is None:
            return None
        recs = {name: rec for name, rec in jobs.items()}

        def on_done(name: str, batches: int) -> None:
            if batches <= 0:
                return
            rec = recs.get(name)
            if jnl is not None:
                jnl.append("task_progress", task=name,
                           job=rec.job_id if rec is not None else None,
                           batches=int(batches))
            if tenancy is not None and rec is not None:
                chip_s = _estimate_chip_seconds(rec.task, batches)
                if chip_s > 0.0:
                    tenancy.charge(rec.tenant, chip_s, job=rec.job_id)

        return on_done

    def _prewarm_admitted(self, rec: JobRecord, topo) -> None:
        """Compile-ahead: the moment admission picks this job's strategy
        set, hand its compilables to the background pool so the first
        dispatch finds a warm executable instead of blocking on XLA.
        Duck-typed: a task exposing ``compile_ahead(topology)`` yields
        ``(key, thunk)`` pairs; tasks without the hook cost nothing."""
        pool = self.compile_ahead
        if pool is None:
            return
        hook = getattr(rec.task, "compile_ahead", None)
        if hook is None:
            return
        try:
            pairs = hook(topo) or ()
        except Exception as e:
            logger.debug("compile-ahead hook failed for %s: %r",
                         rec.job_id, e)
            return
        for key, thunk in pairs:
            pool.prewarm(key, thunk, job=rec.job_id, tenant=rec.tenant)

    def _evict(self, jobs: Dict[str, JobRecord], rec: JobRecord,
               reason: str) -> None:
        jobs.pop(rec.name, None)
        self._release(rec.task, compiled=True)
        # Terminal exit: make sure no stale DEFER-pool entry survives the
        # job (normally a no-op — ADMIT already popped it).
        self.admission.deferred.pop(rec.job_id, None)
        self.queue.mark(rec, JobState.EVICTED, error=reason)
        metrics.event("job_evicted", job=rec.job_id, task=rec.name,
                      reason=reason)

    @staticmethod
    def _release(task, compiled: bool) -> None:
        release = getattr(task, "release_live_state", None)
        if release is not None:
            release()
        if compiled:
            release_c = getattr(task, "release_compiled", None)
            if release_c is not None:
                release_c()

    def _shed_pressure(self, jobs: Dict[str, JobRecord], topo,
                       plan: Optional[milp.Plan]) -> None:
        shed, proj, limit = project_pressure_shed(
            jobs, topo, plan, self.pressure_policy, tenancy=self.tenancy
        )
        if shed:
            # Signal wire-level backpressure: the gateway shrinks its
            # admission window while this timestamp is fresh.
            self.last_pressure_shed = time.monotonic()
        for rec in shed:
            logger.warning(
                "admission pressure: evicting %s (projection %.2fs > "
                "slack %.2fs)", rec.job_id, proj, limit,
            )
            if self.tenancy is not None:
                self.tenancy.note_shed(rec.tenant)
            self._evict(jobs, rec, "admission-pressure")


def project_pressure_shed(jobs: Dict[str, JobRecord], topo,
                          plan: Optional[milp.Plan],
                          pressure_policy: str,
                          tenancy=None):
    """Deadline-protecting load shed. The tightest remaining deadline
    slack bounds the projected (greedy, pessimistic) makespan; when the
    projection overshoots, the configured replanner eviction policy
    picks the casualties — lowest ``hints['priority']`` first.

    With a :class:`~saturn_tpu.tenancy.model.TenantLedger`, the policy's
    candidate pool is narrowed to jobs owned by *over-fair-share* tenants
    first: a noisy neighbour's burst sheds its own work before a quiet
    tenant loses anything already admitted. Only when no over-share
    tenant is live does the policy consider the whole set.

    Module-level so simulated loop drivers (the twin campaign runner) run
    the *identical* shedding decision the service does; returns
    ``(records_to_evict, projected_makespan, slack_limit)`` and leaves the
    eviction side effects to the caller.
    """
    with_deadline = [r for r in jobs.values()
                     if r.deadline_at is not None]
    if not with_deadline or len(jobs) <= 1:
        return [], 0.0, 0.0
    limit = min(r.deadline_at for r in with_deadline) - time.monotonic()
    limit = max(limit, 1e-3)
    tasks = [r.task for r in jobs.values()]
    # Pessimistic greedy projection; the frontier variant keeps this
    # O(N * capacity) once the live set outgrows backfill scheduling.
    if len(tasks) > 300:
        proj = anytime.fast_greedy_plan(tasks, topo).makespan
    else:
        proj = milp.greedy_plan(tasks, topo).makespan
    if proj <= limit:
        return [], proj, limit
    from saturn_tpu.resilience.replan import ReplanContext, get_policy

    ctx = ReplanContext(
        topology=topo, previous_plan=plan, previous_makespan=limit,
        change_kind="admission-pressure", degrade_factor=1.0,
    )
    candidates = tasks
    if tenancy is not None:
        live: Dict[str, int] = {}
        for r in jobs.values():
            live[r.tenant] = live.get(r.tenant, 0) + 1
        over = tenancy.over_share_tenants(live)
        over_tasks = [r.task for r in jobs.values() if r.tenant in over]
        if over_tasks and len(over_tasks) < len(tasks):
            candidates = over_tasks
    _keep, shed = get_policy(pressure_policy)(candidates, ctx)
    by_name = {r.name: r for r in jobs.values()}
    return (
        [by_name[t.name] for t in shed if t.name in by_name],
        proj,
        limit,
    )
