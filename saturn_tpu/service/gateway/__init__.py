"""Fault-tolerant network gateway: JSONL-over-TCP transport for the service.

- :mod:`protocol` — frame schema, typed wire errors, exception mapping
- :mod:`server` — :class:`GatewayServer`: threaded front door with
  idempotent submission, per-request deadlines, backpressure windows, and
  graceful drain
- :mod:`client` — :class:`GatewayClient`: retrying client with the
  ``ServiceClient`` surface

Chaos-tested by ``resilience/netchaos.py`` (wire faults) plus the crash
harness (gateway kills); see ``tests/test_gateway.py``.
"""

from saturn_tpu.service.gateway.client import GatewayClient
from saturn_tpu.service.gateway.protocol import (
    ERROR_CODES,
    GatewayError,
    RETRIABLE_CODES,
    classify_exception,
)
from saturn_tpu.service.gateway.server import GatewayServer

__all__ = [
    "ERROR_CODES",
    "GatewayClient",
    "GatewayError",
    "GatewayServer",
    "RETRIABLE_CODES",
    "classify_exception",
]
