"""Wire protocol for the JSONL-over-TCP gateway: frames + typed errors.

One frame per line, UTF-8 JSON, newline-terminated — the same
line-per-record discipline as the metrics stream and the write-ahead
journal, so every transport artifact in the system tails with the same
tools. Requests carry::

    {"op": "submit", "rid": "<session>:<n>", "session": "<client id>",
     "deadline_s": 5.0, "dedup_key": "<session>:d<n>",
     "job": {"name": ..., "total_batches": ..., "priority": ...,
             "deadline_s": ..., "max_retries": ..., "spec": {...}}}

and responses::

    {"rid": "<echoed>", "ok": true,  "result": {...}}
    {"rid": "<echoed>", "ok": false, "error": {"code": "GW_RETRY_AFTER",
     "message": ..., "retriable": true, "retry_after_s": 0.5}}

``rid`` is the client's request correlator: a hostile wire may duplicate or
reorder frames (see ``resilience/netchaos.py``), so the client matches
responses by ``rid`` and discards strays instead of trusting arrival order.

Error codes are **typed and closed** (:data:`ERROR_CODES`): every failure
the server can hand a client serializes as a code the client can branch on,
never a raw exception string — :func:`classify_exception` is the single
mapping from in-process exceptions (queue duplicate-name rejection, unknown
job ids) to wire errors, and :class:`GatewayError` round-trips through
``to_wire``/``from_wire`` losslessly.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

#: Hard per-frame byte cap (including the newline). A frame this size is a
#: protocol violation, not a big request — submit specs are small JSON.
MAX_FRAME_BYTES = 256 * 1024

#: Protocol revision, echoed in the hello exchange.
PROTO_VERSION = 1

# --------------------------------------------------------------- error codes
GW_BADFRAME = "GW_BADFRAME"                  # unparseable / oversized frame
GW_BADREQUEST = "GW_BADREQUEST"              # missing/invalid fields, bad op
GW_DUPLICATE_NAME = "GW_DUPLICATE_NAME"      # task name already live (queue)
GW_DEADLINE_EXPIRED = "GW_DEADLINE_EXPIRED"  # request deadline passed pre-admission
GW_RETRY_AFTER = "GW_RETRY_AFTER"            # backpressure: inflight window full
GW_DRAINING = "GW_DRAINING"                  # gateway draining, not accepting
GW_UNKNOWN_JOB = "GW_UNKNOWN_JOB"            # status/wait/cancel on unknown id
GW_INTERNAL = "GW_INTERNAL"                  # unexpected server-side exception
GW_UNAVAILABLE = "GW_UNAVAILABLE"            # client-side: transport exhausted
GW_TENANT_OVER_QUOTA = "GW_TENANT_OVER_QUOTA"  # tenant inflight window full
#                                                (per-tenant backpressure; the
#                                                global window may be fine)
GW_STALE_EPOCH = "GW_STALE_EPOCH"            # replica lost the lease mid-
#                                              request: fenced, nothing was
#                                              admitted — retry (any replica)

ERROR_CODES = frozenset({
    GW_BADFRAME,
    GW_BADREQUEST,
    GW_DUPLICATE_NAME,
    GW_DEADLINE_EXPIRED,
    GW_RETRY_AFTER,
    GW_DRAINING,
    GW_UNKNOWN_JOB,
    GW_INTERNAL,
    GW_UNAVAILABLE,
    GW_TENANT_OVER_QUOTA,
    GW_STALE_EPOCH,
})

#: Codes a client may transparently retry (with backoff / after
#: ``retry_after_s``). Everything else is a terminal verdict for the call.
#: ``GW_TENANT_OVER_QUOTA`` retries like ``GW_RETRY_AFTER`` (it carries the
#: tenant's own ``retry_after_s``); ``GW_STALE_EPOCH`` retries because the
#: fenced replica admitted nothing — the retry lands on (or re-elects) the
#: current leaseholder and the dedup key maps it to one job id.
RETRIABLE_CODES = frozenset({
    GW_RETRY_AFTER, GW_DRAINING, GW_UNAVAILABLE,
    GW_TENANT_OVER_QUOTA, GW_STALE_EPOCH,
})


class GatewayError(Exception):
    """A typed, wire-serializable gateway failure.

    ``retriable`` defaults from the code's class; ``retry_after_s`` is the
    server's backpressure hint (only meaningful with ``GW_RETRY_AFTER``).
    """

    def __init__(self, code: str, message: str = "", *,
                 retriable: Optional[bool] = None,
                 retry_after_s: Optional[float] = None):
        if code not in ERROR_CODES:
            raise ValueError(f"unknown gateway error code {code!r}")
        super().__init__(f"{code}: {message}" if message else code)
        self.code = code
        self.message = message
        self.retriable = (
            retriable if retriable is not None else code in RETRIABLE_CODES
        )
        self.retry_after_s = retry_after_s

    def to_wire(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "code": self.code,
            "message": self.message,
            "retriable": self.retriable,
        }
        if self.retry_after_s is not None:
            out["retry_after_s"] = round(float(self.retry_after_s), 6)
        return out

    @classmethod
    def from_wire(cls, payload: Any) -> "GatewayError":
        if not isinstance(payload, dict):
            return cls(GW_INTERNAL, f"malformed error payload: {payload!r}")
        code = payload.get("code")
        if code not in ERROR_CODES:
            return cls(
                GW_INTERNAL,
                f"unknown error code {code!r}: {payload.get('message', '')}",
            )
        return cls(
            code,
            str(payload.get("message", "")),
            retriable=bool(payload.get("retriable", code in RETRIABLE_CODES)),
            retry_after_s=payload.get("retry_after_s"),
        )


def classify_exception(exc: BaseException) -> GatewayError:
    """Map an in-process service exception to its typed wire error.

    The single choke point for the ServiceClient ↔ service error paths: the
    queue's duplicate-live-name rejection and bad-request ``ValueError``s,
    the registry's unknown-job ``KeyError``, and anything unexpected
    (``GW_INTERNAL``, carrying the exception type so the operator can grep
    the server log) — never a bare ``repr`` the client must string-match.
    """
    if isinstance(exc, GatewayError):
        return exc
    if isinstance(exc, KeyError):
        return GatewayError(GW_UNKNOWN_JOB, str(exc.args[0]) if exc.args
                            else "unknown job id")
    if isinstance(exc, ValueError):
        if "already live" in str(exc):
            return GatewayError(GW_DUPLICATE_NAME, str(exc))
        return GatewayError(GW_BADREQUEST, str(exc))
    return GatewayError(
        GW_INTERNAL, f"{type(exc).__name__}: {exc}"
    )


# ------------------------------------------------------------------- framing
def encode_frame(obj: Dict[str, Any]) -> bytes:
    """One compact JSON object + newline. Refuses frames over the cap —
    better to fail the sender loudly than wedge the peer's readline."""
    data = (json.dumps(obj, sort_keys=True, separators=(",", ":"),
                       default=str) + "\n").encode("utf-8")
    if len(data) > MAX_FRAME_BYTES:
        raise GatewayError(
            GW_BADFRAME,
            f"frame of {len(data)} bytes exceeds the {MAX_FRAME_BYTES}-byte "
            "cap",
        )
    return data


def decode_frame(line: bytes) -> Dict[str, Any]:
    """Parse one received line into a frame dict, or raise ``GW_BADFRAME``.

    A line at (or past) the byte cap without a terminating newline means the
    peer is mid-way through an oversized frame — the connection is
    unrecoverable from here (the rest of the frame would parse as garbage),
    so the caller should respond and close.
    """
    if len(line) > MAX_FRAME_BYTES or (len(line) >= MAX_FRAME_BYTES
                                       and not line.endswith(b"\n")):
        raise GatewayError(
            GW_BADFRAME, f"frame exceeds the {MAX_FRAME_BYTES}-byte cap"
        )
    try:
        obj = json.loads(line.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError, ValueError) as e:
        raise GatewayError(GW_BADFRAME, f"unparseable frame: {e}") from e
    if not isinstance(obj, dict):
        raise GatewayError(
            GW_BADFRAME, f"frame is {type(obj).__name__}, expected object"
        )
    return obj


def ok_response(rid: Any, result: Dict[str, Any]) -> Dict[str, Any]:
    return {"rid": rid, "ok": True, "result": result}


def error_response(rid: Any, err: GatewayError) -> Dict[str, Any]:
    return {"rid": rid, "ok": False, "error": err.to_wire()}
