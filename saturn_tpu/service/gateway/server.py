"""Threaded JSONL-over-TCP gateway in front of :class:`SaturnService`.

One accept thread plus one reader thread per connection, all feeding the
service's existing :class:`~saturn_tpu.service.queue.SubmissionQueue` — the
gateway owns the *wire* concerns the in-process client never had:

- **Idempotent submission.** Every submit may carry a client-supplied
  ``dedup_key``. The key rides the ``job_submitted`` journal record (the
  queue observer writes it in the same durable group commit as the
  submission itself), so a retried submit whose ACK was lost — to a dropped
  connection, a chaos-proxy mid-ACK kill, or a gateway death — returns the
  *original* job id, exactly-once across process incarnations
  (``replay_service_state`` folds the dedup table back; the gateway seeds
  its map from ``SaturnService.recovered_dedup``).
- **Per-request deadlines.** Frames carry ``deadline_s`` (the client's
  remaining budget at send time); expired work is shed *before* admission —
  at dispatch, and again after waiting out the dedup lock — so a backlogged
  gateway never burns profiling/solver time on a request whose client
  already gave up.
- **Bounded inflight windows + explicit backpressure.** A global cap on
  live jobs and a per-session cap on a client's outstanding submissions;
  past either, the submit is refused with ``GW_RETRY_AFTER`` and a
  ``retry_after_s`` hint instead of silently queueing. The window is wired
  to the service's deadline-pressure load shedder: while the shedder has
  recently evicted (``SaturnService.last_pressure_shed``), the effective
  global window shrinks by ``pressure_window_factor`` so the wire stops
  feeding a mesh that is already shedding admitted work.
- **Graceful drain.** ``shutdown()`` (or SIGTERM via
  :meth:`install_sigterm`) stops accepting connections and submissions,
  lets in-flight requests flush their responses, and journals a durable
  ``gateway_drain`` handoff marker with the shed/dedup ledger.
- **Tenant windows.** When the service carries a ``tenancy`` ledger
  (:class:`~saturn_tpu.tenancy.TenantLedger`), submissions are accounted
  to their ``job.tenant`` and a per-tenant inflight window applies on top
  of the global/session ones — refused with ``GW_TENANT_OVER_QUOTA`` and
  the tenant's own ``retry_after_s``. Under admission pressure the window
  shrink becomes *tenant-selective*: only tenants over their weighted
  fair share are squeezed, so a bursty tenant backs off before a quiet
  tenant loses a single slot.
- **Replication.** N gateways can front one service
  (``GatewayServer(service, replica_of=first, replica_id=..., lease=...)``):
  replicas share the dedup table and an epoch-fenced
  :class:`~saturn_tpu.tenancy.ReplicaLease` over the same durability
  journal. Holding the lease is what authorizes recording a new
  admission; a deposed replica's late submit is refused with
  ``GW_STALE_EPOCH`` *before* anything is admitted, so a client retrying
  a lost ACK against the surviving replica gets the original job id —
  exactly-once across failover.

Locks are named into the saturn-tsan graph (``gateway.conns``,
``gateway.dedup``, ``gateway.lease``) with the acquisition order
``gateway.dedup → gateway.conns → …``, ``gateway.dedup → queue.lock →
journal.lock`` and ``gateway.dedup → gateway.lease``; nothing ever
acquires a gateway lock while holding a queue or journal lock.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from saturn_tpu.analysis import concurrency as tsan
from saturn_tpu.analysis.concurrency import sched_point
from saturn_tpu.resilience.crash import SimulatedKill
from saturn_tpu.service.gateway import protocol
from saturn_tpu.service.gateway.protocol import GatewayError
from saturn_tpu.service.queue import TERMINAL_STATES, JobRequest
from saturn_tpu.tenancy.lease import LeaseHeld
from saturn_tpu.utils import metrics

logger = logging.getLogger("saturn_tpu")

_ACCEPT_POLL_S = 0.2


class _Session:
    """Per-client state that survives reconnects (session resume): the set
    of job ids this client submitted, for the per-session inflight window."""

    def __init__(self, sid: str):
        self.sid = sid
        self.jobs: set = set()
        self.connects = 0


class _Conn:
    def __init__(self, cid: int, sock: socket.socket, addr: Any,
                 thread: threading.Thread):
        self.cid = cid
        self.sock = sock
        self.addr = addr
        self.thread = thread


class GatewayServer:
    """TCP front door for one :class:`SaturnService`.

    The service must run with a ``task_provider`` — wire submissions carry
    a JSON job payload, and the provider rebuilds the task object exactly
    as crash recovery does (same payload contract as
    ``build_restore_records``). ``port=0`` binds an ephemeral port; read
    :attr:`address` after :meth:`start`.
    """

    def __init__(
        self,
        service,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        max_inflight: int = 64,
        max_inflight_per_session: int = 16,
        pressure_window_factor: float = 0.5,
        pressure_cooldown_s: Optional[float] = None,
        retry_after_s: Optional[float] = None,
        wait_chunk_cap_s: float = 5.0,
        replica_id: Optional[str] = None,
        lease=None,
        replica_of: Optional["GatewayServer"] = None,
    ):
        self.service = service
        self.host = host
        self.port = port
        self.max_inflight = max_inflight
        self.max_inflight_per_session = max_inflight_per_session
        self.pressure_window_factor = pressure_window_factor
        self.pressure_cooldown_s = (
            pressure_cooldown_s if pressure_cooldown_s is not None
            else 5.0 * getattr(service, "interval", 1.0)
        )
        self.retry_after_s = (
            retry_after_s if retry_after_s is not None
            else getattr(service, "interval", 1.0)
        )
        self.wait_chunk_cap_s = wait_chunk_cap_s

        # gateway.conns guards the connection registry, sessions, drain flag
        # and the shed ledger; gateway.dedup guards the dedup table AND
        # serializes the submit path (check-key → queue.submit → record-key
        # must be atomic so a concurrent retry of the same key can never
        # double-submit). Order: gateway.dedup → gateway.conns, never the
        # reverse.
        self._lock = tsan.rlock("gateway.conns")
        if replica_of is not None:
            # A replica of an existing gateway over the SAME service: the
            # dedup table and its lock are shared objects, so check-then-
            # record stays atomic across replicas, and the lease defaults
            # to the peer's — one epoch sequence for the whole replica set.
            if replica_of.service is not service:
                raise ValueError(
                    "replica_of must front the same SaturnService"
                )
            self._dedup_lock = replica_of._dedup_lock
            self._dedup = replica_of._dedup
            if lease is None:
                lease = replica_of.lease
        else:
            self._dedup_lock = tsan.rlock("gateway.dedup")
            # Exactly-once across restarts: seed the dedup table from the
            # journal replay the service already performed.
            self._dedup = dict(
                getattr(service, "recovered_dedup", None) or {}
            )
        self._conns: Dict[int, _Conn] = {}
        self._sessions: Dict[str, _Session] = {}
        self._sheds: Dict[str, int] = {}
        self._draining = False
        self._next_conn = 0
        self._dedup_hits = 0
        #: This replica's identity in the lease protocol. Defaults to a
        #: stable per-instance name so single-gateway deployments that pass
        #: a lease still fence correctly.
        self.replica_id = replica_id or f"gw-{id(self):x}"
        #: Optional epoch-fenced ReplicaLease shared with peer replicas.
        #: None = single-gateway mode, no fencing (exactly as before).
        self.lease = lease
        if self.lease is not None and self.lease.journal is None:
            self.lease.journal = getattr(service, "journal", None)

        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self.address: Tuple[str, int] = (host, port)
        self.killed = False  # set only by the crash harness's SimulatedKill
        # Set once shutdown() has fully completed (marker journaled). Hosts
        # that drain from a signal handler's thread must wait on this before
        # stopping the service, or the marker races the journal close.
        self._drained = threading.Event()

    # ---------------------------------------------------------------- control
    def start(self) -> "GatewayServer":
        if self._accept_thread is not None:
            raise RuntimeError("gateway already started")
        sock = socket.create_server((self.host, self.port))
        sock.settimeout(_ACCEPT_POLL_S)  # poll-able accept → prompt drain
        self._listener = sock
        self.address = sock.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="gw-accept", daemon=True
        )
        self._accept_thread.start()
        logger.info("gateway listening on %s:%d", *self.address)
        return self

    def install_sigterm(self) -> bool:
        """Register a SIGTERM handler that drains this gateway. Returns False
        when not callable (non-main thread / unsupported platform)."""
        import signal

        def _on_term(signum, frame):
            threading.Thread(
                target=self.shutdown, kwargs={"reason": "SIGTERM"},
                name="gw-sigterm", daemon=True,
            ).start()

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError, AttributeError):
            return False
        return True

    def shutdown(self, timeout: float = 10.0,
                 reason: str = "shutdown") -> bool:
        """Graceful drain: stop accepting, flush inflight responses, journal
        a durable handoff marker. Returns True when every reader thread
        exited inside ``timeout`` (a clean handoff)."""
        sched_point("gateway.drain")
        with self._lock:
            already = self._draining
            self._draining = True
            conns = list(self._conns.values())
        if already:
            # First caller owns the drain; wait for it to finish so every
            # returner sees the marker durably journaled.
            self._drained.wait(timeout)
            return True
        listener = self._listener
        if listener is not None:
            try:
                listener.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout)
        # Half-close every connection's read side: no new requests arrive,
        # the request a reader is mid-way through still writes its response
        # (the write side stays open until the reader exits).
        for c in conns:
            try:
                c.sock.shutdown(socket.SHUT_RD)
            except OSError:
                pass
        deadline = time.monotonic() + timeout
        clean = True
        for c in conns:
            c.thread.join(max(0.0, deadline - time.monotonic()))
            if c.thread.is_alive():
                clean = False
        with self._lock:
            sheds = dict(self._sheds)
            sessions = len(self._sessions)
        with self._dedup_lock:
            dedup_entries = len(self._dedup)
            dedup_hits = self._dedup_hits
        if self.lease is not None:
            # Clean handoff: declare this replica dead and drop the lease so
            # a peer takes over without waiting out the ttl. (The crash
            # path — _die — deliberately does neither: a SIGKILLed replica
            # can't, and the peer must win by ttl expiry.)
            self.lease.mark_dead(self.replica_id)
            self.lease.release(self.replica_id)
        jnl = self.service.journal
        if jnl is not None:
            # The durable clean-handoff marker: the analysis CLI and the
            # next incarnation's operator can tell a drained gateway from a
            # killed one.
            jnl.log(
                "gateway_drain", reason=reason, clean=clean,
                sessions=sessions, dedup_entries=dedup_entries,
                dedup_hits=dedup_hits, sheds=sheds,
                replica=self.replica_id,
            )
        metrics.event("gateway_drain", reason=reason, clean=clean,
                      sessions=sessions, sheds=sheds)
        if not clean:
            logger.warning(
                "gateway drain (%s): %d connection(s) still flushing past "
                "%.1fs", reason, sum(c.thread.is_alive() for c in conns),
                timeout,
            )
        self._drained.set()
        return clean

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        """Block until a drain (e.g. the SIGTERM handler's) has fully
        completed — marker journaled, readers joined. A host process must
        call this before stopping the service: the handler drains on a
        daemon thread, and exiting early kills it mid-handoff."""
        return self._drained.wait(timeout)

    # ----------------------------------------------------------- accept loop
    def _accept_loop(self) -> None:
        sched_point("gateway.accept")
        listener = self._listener
        while True:
            with self._lock:
                if self._draining:
                    break
            try:
                sock, addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed by shutdown
            self._register(sock, addr)

    def _register(self, sock: socket.socket, addr: Any) -> None:
        with self._lock:
            if self._draining:
                try:
                    sock.close()
                except OSError:
                    pass
                return
            cid = self._next_conn
            self._next_conn += 1
            thread = threading.Thread(
                target=self._serve, args=(cid, sock),
                name=f"gw-conn-{cid}", daemon=True,
            )
            self._conns[cid] = _Conn(cid, sock, addr, thread)
            thread.start()

    def _unregister(self, cid: int) -> None:
        with self._lock:
            self._conns.pop(cid, None)

    # ---------------------------------------------------------- reader thread
    def _serve(self, cid: int, sock: socket.socket) -> None:
        reader = sock.makefile("rb")
        session: Optional[str] = None
        try:
            while True:
                try:
                    line = reader.readline(protocol.MAX_FRAME_BYTES + 1)
                except OSError:
                    break
                if not line:
                    break  # EOF: client hung up (or drain half-closed us)
                arrival = time.monotonic()
                rid: Any = None
                try:
                    frame = protocol.decode_frame(line)
                    rid = frame.get("rid")
                    session = frame.get("session") or session
                    result = self._dispatch(frame, session, arrival)
                    resp = protocol.ok_response(rid, result)
                except GatewayError as e:
                    resp = protocol.error_response(rid, e)
                    if e.code == protocol.GW_BADFRAME:
                        self._send(sock, resp)
                        break  # stream integrity is gone; drop the conn
                except SimulatedKill as e:
                    # The crash harness 'SIGKILL'ed us mid-request — a real
                    # kill takes the whole gateway, so no response (the ACK
                    # dies on the floor), no drain marker, every socket cut.
                    self._die(e)
                    return
                except Exception as e:
                    logger.exception(
                        "gateway: unexpected error serving conn %d", cid
                    )
                    resp = protocol.error_response(
                        rid, protocol.classify_exception(e)
                    )
                if not self._send(sock, resp):
                    break
        finally:
            try:
                reader.close()
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
            self._unregister(cid)

    def _die(self, exc: BaseException) -> None:
        """Simulated whole-gateway death: cut everything, journal nothing.
        Recovery is the next incarnation's problem — that's the point."""
        with self._lock:
            self.killed = True
            self._draining = True   # accept loop exits at its next poll
            conns = list(self._conns.values())
        listener = self._listener
        if listener is not None:
            try:
                listener.close()
            except OSError:
                pass
        for c in conns:
            try:
                c.sock.close()
            except OSError:
                pass
        logger.warning("gateway killed by crash harness: %s", exc)

    @staticmethod
    def _send(sock: socket.socket, resp: Dict[str, Any]) -> bool:
        try:
            sock.sendall(protocol.encode_frame(resp))
        except (OSError, GatewayError):
            return False
        return True

    # ------------------------------------------------------------- dispatch
    def _dispatch(self, frame: Dict[str, Any], session: Optional[str],
                  arrival: float) -> Dict[str, Any]:
        op = frame.get("op")
        if op == "submit":
            return self._op_submit(frame, session, arrival)
        if op == "status":
            return self._op_status(frame)
        if op == "wait":
            return self._op_wait(frame)
        if op == "cancel":
            return self._op_cancel(frame)
        if op == "hello":
            return self._op_hello(frame, session)
        if op == "ping":
            with self._lock:
                draining = self._draining
            return {"pong": True, "draining": draining}
        raise GatewayError(protocol.GW_BADREQUEST, f"unknown op {op!r}")

    def _op_hello(self, frame: Dict[str, Any],
                  session: Optional[str]) -> Dict[str, Any]:
        if not session:
            raise GatewayError(protocol.GW_BADREQUEST,
                               "hello needs a session id")
        with self._lock:
            sess = self._sessions.get(session)
            resumed = sess is not None
            if sess is None:
                sess = self._sessions[session] = _Session(session)
            sess.connects += 1
            live = sum(
                1 for jid in sess.jobs if self._live_state(jid)
            )
        return {"proto": protocol.PROTO_VERSION, "resumed": resumed,
                "live_jobs": live}

    def _op_submit(self, frame: Dict[str, Any], session: Optional[str],
                   arrival: float) -> Dict[str, Any]:
        sched_point("gateway.submit")
        with self._lock:
            if self._draining:
                raise GatewayError(
                    protocol.GW_DRAINING,
                    "gateway is draining; retry against the next incarnation",
                )
        self._check_deadline(frame, arrival, session, "submit")
        job = frame.get("job")
        if not isinstance(job, dict) or not job.get("name"):
            raise GatewayError(protocol.GW_BADREQUEST,
                               "submit needs a job object with a name")
        tenant = job.get("tenant")
        if tenant is not None and not isinstance(tenant, str):
            raise GatewayError(protocol.GW_BADREQUEST,
                               f"job.tenant must be a string, got {tenant!r}")
        key = frame.get("dedup_key")
        if key is not None and self.lease is not None:
            # Serving an idempotent retry needs no lease — the dedup table
            # is shared across replicas and the answer is already durable —
            # so check it BEFORE the lease gate: a client failing over a
            # lost ACK to a non-holder replica gets the original job id
            # instead of bouncing back to the leaseholder.
            with self._dedup_lock:
                jid = self._dedup.get(key)
                if jid is not None:
                    return self._serve_dedup_hit(key, jid, session)
        epoch = self._ensure_lease(session)
        sched_point("gateway.dedup")
        with self._dedup_lock:
            if key is not None:
                jid = self._dedup.get(key)
                if jid is not None:
                    # Idempotent retry: the original admission stands; the
                    # lost-ACK window (connection drop, mid-ACK kill,
                    # gateway restart, replica failover) collapses to a
                    # lookup.
                    return self._serve_dedup_hit(key, jid, session)
            # Shed expired work before admission: time spent waiting out the
            # dedup lock (the gateway's admission queue) counts against the
            # request's budget.
            self._check_deadline(frame, arrival, session, "submit")
            self._check_window(session, tenant)
            # The fence, at the commit point: a replica deposed between its
            # lease check and here (late ACK after failover) must not admit.
            sched_point("gateway.lease")
            if self.lease is not None \
                    and not self.lease.check(self.replica_id, epoch):
                self._shed("stale_epoch", session, "submit", tenant=tenant)
                raise GatewayError(
                    protocol.GW_STALE_EPOCH,
                    f"replica {self.replica_id} holds stale lease epoch "
                    f"{epoch} (current: {self.lease.epoch}) — nothing "
                    "admitted; retry against the current leaseholder",
                )
            task = self._build_task(job)
            req = JobRequest(
                task=task,
                priority=float(job.get("priority", 0.0)),
                deadline_s=job.get("deadline_s"),
                max_retries=int(job.get("max_retries", 1)),
                spec=job.get("spec"),
                dedup_key=key,
                tenant=tenant,
            )
            try:
                rec = self.service.queue.submit(req)
            except (ValueError, RuntimeError) as e:
                raise protocol.classify_exception(e) from e
            # submit() returning IS the durable ack on a durable service:
            # the job_submitted record (dedup key included) is fsync'd.
            if key is not None:
                self._dedup[key] = rec.job_id
            self._note_session_job(session, rec.job_id)
        return {"job_id": rec.job_id, "duplicate": False}

    def _serve_dedup_hit(self, key: str, jid: str,
                         session: Optional[str]) -> Dict[str, Any]:
        """Answer a retried submit from the dedup table (caller holds the
        dedup lock — it's an rlock, so both call sites are safe)."""
        self._dedup_hits += 1
        self._note_session_job(session, jid)
        jnl = self.service.journal
        if jnl is not None:
            jnl.append("gateway_dedup_hit", key=key, job=jid,
                       session=session, replica=self.replica_id)
        metrics.event("gateway_dedup_hit", key=key, job=jid,
                      session=session)
        return {"job_id": jid, "duplicate": True}

    def _ensure_lease(self, session: Optional[str]) -> Optional[int]:
        """Hold (or take) the replica lease before touching admission state.

        Returns the epoch to present at the commit-point fence, or None in
        single-gateway mode. A live peer holding the lease turns into a
        retriable refusal — the client's endpoint rotation finds the
        leaseholder.
        """
        if self.lease is None:
            return None
        try:
            return self.lease.ensure(self.replica_id)
        except LeaseHeld as e:
            self._shed("lease_held", session, "submit")
            raise GatewayError(
                protocol.GW_RETRY_AFTER,
                f"replica {self.replica_id} is not the leaseholder "
                f"({e.holder} is) — retry against it",
                retry_after_s=e.retry_after_s,
            ) from e

    def _op_status(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        jid = self._job_id(frame)
        try:
            rec = self.service.queue.get(jid)
        except KeyError as e:
            raise protocol.classify_exception(e) from e
        return rec.snapshot()

    def _op_wait(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        jid = self._job_id(frame)
        chunk = min(float(frame.get("timeout_s") or self.wait_chunk_cap_s),
                    self.wait_chunk_cap_s)
        try:
            rec = self.service.queue.wait(jid, timeout=max(chunk, 0.0))
        except KeyError as e:
            raise protocol.classify_exception(e) from e
        except TimeoutError:
            snap = self.service.queue.get(jid).snapshot()
            return dict(snap, terminal=False)
        return dict(rec.snapshot(), terminal=True)

    def _op_cancel(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        jid = self._job_id(frame)
        try:
            cancelled = self.service.queue.cancel(jid)
        except KeyError as e:
            raise protocol.classify_exception(e) from e
        return {"cancelled": cancelled}

    # -------------------------------------------------------------- helpers
    @staticmethod
    def _job_id(frame: Dict[str, Any]) -> str:
        jid = frame.get("job")
        if not isinstance(jid, str) or not jid:
            raise GatewayError(protocol.GW_BADREQUEST,
                               "request needs a job id")
        return jid

    def _live_state(self, jid: str) -> bool:
        try:
            rec = self.service.queue.get(jid)
        except KeyError:
            return False
        return rec.state not in TERMINAL_STATES

    def _session(self, sid: Optional[str]) -> Optional[_Session]:
        if sid is None:
            return None
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                sess = self._sessions[sid] = _Session(sid)
            return sess

    def _note_session_job(self, sid: Optional[str], jid: str) -> None:
        if sid is None:
            return
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                sess = self._sessions[sid] = _Session(sid)
            sess.jobs.add(jid)

    def _check_deadline(self, frame: Dict[str, Any], arrival: float,
                        session: Optional[str], op: str) -> None:
        deadline_s = frame.get("deadline_s")
        if deadline_s is None:
            return
        expired = time.monotonic() - arrival >= float(deadline_s)
        if expired or float(deadline_s) <= 0:
            self._shed("deadline_expired", session, op)
            raise GatewayError(
                protocol.GW_DEADLINE_EXPIRED,
                f"request budget of {deadline_s}s elapsed before admission",
            )

    def _pressure_active(self) -> bool:
        last = getattr(self.service, "last_pressure_shed", None)
        return (last is not None
                and time.monotonic() - last < self.pressure_cooldown_s)

    def _check_window(self, session: Optional[str],
                      tenant: Optional[str] = None) -> None:
        tenancy = getattr(self.service, "tenancy", None)
        window = self.max_inflight
        pressured = self._pressure_active()
        if pressured and tenancy is None:
            # The deadline-pressure shedder is evicting admitted work:
            # stop feeding it from the wire until the cooldown passes.
            # (With a tenancy ledger the shrink is tenant-selective below —
            # a quiet tenant keeps its full window.)
            window = max(1, int(window * self.pressure_window_factor))
        live = self.service.queue.live()
        if live >= window:
            self._shed("retry_after", session, "submit", tenant=tenant)
            raise GatewayError(
                protocol.GW_RETRY_AFTER,
                f"{live} live job(s) >= window {window}"
                + (" (pressure-shrunk)" if pressured else ""),
                retry_after_s=self.retry_after_s,
            )
        if tenancy is not None:
            self._check_tenant_window(session, tenant, tenancy, pressured)
        if session is not None:
            with self._lock:
                sess = self._sessions.get(session)
                jobs = list(sess.jobs) if sess is not None else []
            sess_live = sum(1 for jid in jobs if self._live_state(jid))
            if sess_live >= self.max_inflight_per_session:
                self._shed("retry_after_session", session, "submit",
                           tenant=tenant)
                raise GatewayError(
                    protocol.GW_RETRY_AFTER,
                    f"session {session} has {sess_live} live job(s) >= "
                    f"per-session window {self.max_inflight_per_session}",
                    retry_after_s=self.retry_after_s,
                )

    def _check_tenant_window(self, session: Optional[str],
                             tenant: Optional[str], tenancy,
                             pressured: bool) -> None:
        """Per-tenant inflight window, pressure-shrunk only for tenants over
        their weighted fair share — the tenant-aware half of backpressure."""
        quota = tenancy.quota(tenant)
        window = quota.max_inflight
        squeezed = False
        if pressured:
            counts = self.service.queue.live_by_tenant()
            if tenancy.over_fair_share(tenant, counts):
                base = window if window is not None else self.max_inflight
                window = max(1, int(base * self.pressure_window_factor))
                squeezed = True
        if window is None:
            return
        tenant_live = self.service.queue.live_tenant(tenant)
        if tenant_live >= window:
            tenancy.note_shed(tenant)
            self._shed("tenant_over_quota", session, "submit", tenant=tenant)
            raise GatewayError(
                protocol.GW_TENANT_OVER_QUOTA,
                f"tenant {tenancy.resolve(tenant)!r} has {tenant_live} live "
                f"job(s) >= its window {window}"
                + (" (pressure-shrunk: over fair share)" if squeezed else ""),
                retry_after_s=(
                    quota.retry_after_s if quota.retry_after_s is not None
                    else self.retry_after_s
                ),
            )

    def _shed(self, reason: str, session: Optional[str], op: str,
              tenant: Optional[str] = None) -> None:
        with self._lock:
            self._sheds[reason] = self._sheds.get(reason, 0) + 1
        jnl = self.service.journal
        if jnl is not None:
            jnl.append("gateway_shed", reason=reason, session=session, op=op,
                       tenant=tenant, replica=self.replica_id)
        metrics.event("gateway_shed", reason=reason, session=session, op=op,
                      tenant=tenant)

    def _build_task(self, job: Dict[str, Any]) -> Any:
        provider = self.service.task_provider
        if provider is None:
            raise GatewayError(
                protocol.GW_BADREQUEST,
                "wire submissions need SaturnService(task_provider=...) to "
                "rebuild task objects from job specs",
            )
        name = job["name"]
        total = int(job.get("total_batches") or 0)
        # Same payload contract as crash recovery's build_restore_records:
        # one provider serves both paths.
        task = provider({
            "job_id": None,
            "task": name,
            "total_batches": total,
            "remaining_batches": total,
            "priority": float(job.get("priority", 0.0)),
            "deadline_s": job.get("deadline_s"),
            "max_retries": int(job.get("max_retries", 1)),
            "spec": job.get("spec"),
            "tenant": job.get("tenant"),
        })
        if getattr(task, "name", None) != name:
            raise GatewayError(
                protocol.GW_INTERNAL,
                f"task_provider returned {getattr(task, 'name', None)!r} "
                f"for submitted name {name!r}",
            )
        return task

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        """Point-in-time gateway counters (operator/test visibility)."""
        with self._lock:
            out: Dict[str, Any] = {
                "connections": len(self._conns),
                "sessions": len(self._sessions),
                "sheds": dict(self._sheds),
                "draining": self._draining,
            }
        with self._dedup_lock:
            out["dedup_entries"] = len(self._dedup)
            out["dedup_hits"] = self._dedup_hits
        out["replica_id"] = self.replica_id
        if self.lease is not None:
            out["lease"] = self.lease.snapshot()
        return out
