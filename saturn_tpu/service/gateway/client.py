"""Retrying gateway client with the :class:`ServiceClient` surface.

``GatewayClient`` speaks the JSONL frame protocol to a
:class:`~saturn_tpu.service.gateway.server.GatewayServer` and hides the
hostile wire from the caller:

- **Timeouts + capped exponential backoff with deterministic jitter.**
  Every transport failure or retriable server verdict (``GW_RETRY_AFTER``,
  ``GW_DRAINING``, ``GW_UNAVAILABLE``) sleeps ``min(cap, base·2^attempt)``
  plus a jitter drawn from a seeded ``random.Random`` — two clients built
  with the same seed replay the same retry schedule, so chaos campaigns are
  reproducible run-to-run.
- **Reconnect with session resume.** The client owns a stable ``session``
  id; after a reconnect it re-sends ``hello`` and the gateway re-associates
  the session's live jobs (the per-session inflight window survives the
  TCP connection dying).
- **Idempotent submits.** Each ``submit`` mints one ``dedup_key`` *before*
  the first attempt and reuses it across every retry — if the first
  attempt's ACK died on the wire (or the gateway died mid-ACK), the retry
  lands on the journaled dedup entry and returns the original job id.
- **rid correlation.** Responses are matched by echoed ``rid``; stray
  frames (a chaos proxy duplicating or reordering lines) are discarded,
  never mistaken for the answer to the current request.

The surface mirrors ``ServiceClient`` (submit/status/wait/cancel) so
in-process callers swap to the wire transparently; ``submit`` additionally
accepts plain keyword job fields for callers with no task object in hand.
"""

from __future__ import annotations

import random
import socket
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from saturn_tpu.service.gateway import protocol
from saturn_tpu.service.gateway.protocol import GatewayError

_TERMINAL_STATES = ("DONE", "FAILED", "EVICTED")


class GatewayClient:
    """submit / status / wait / cancel against a gateway over TCP."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        session: Optional[str] = None,
        seed: int = 0,
        timeout_s: float = 10.0,
        max_attempts: int = 8,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        endpoints: Optional[Sequence[Tuple[str, int]]] = None,
    ):
        self.host = host
        self.port = port
        #: Replica endpoints, tried in rotation: ``(host, port)`` is always
        #: first, extra ``endpoints`` follow. A transport failure or a
        #: retriable refusal (GW_RETRY_AFTER from a non-leaseholder,
        #: GW_STALE_EPOCH, GW_DRAINING) rotates to the next replica before
        #: the retry — same frame, same dedup_key, so landing on a
        #: different replica still maps to the original job id.
        self.endpoints: List[Tuple[str, int]] = [(host, port)]
        for ep in endpoints or ():
            pair = (ep[0], int(ep[1]))
            if pair not in self.endpoints:
                self.endpoints.append(pair)
        self._ep_idx = 0
        self.session = session or f"gwc-{seed}-{id(self) & 0xFFFF:04x}"
        self.timeout_s = timeout_s
        self.max_attempts = max_attempts
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._rng = random.Random(seed)  # deterministic jitter + dedup keys
        self._rid = 0
        self._dedup_seq = 0
        self._sock: Optional[socket.socket] = None
        self._reader = None
        self.reconnects = 0
        self.retries = 0

    # ------------------------------------------------------------- transport
    def _rotate_endpoint(self) -> None:
        """Point at the next replica (no-op with a single endpoint)."""
        if len(self.endpoints) > 1:
            self._ep_idx = (self._ep_idx + 1) % len(self.endpoints)

    def _connect(self) -> None:
        self.close()
        # Try every endpoint once, starting from the current rotation
        # position: a dead replica costs one connect attempt, not the call.
        last: Optional[BaseException] = None
        sock = None
        for i in range(len(self.endpoints)):
            host, port = self.endpoints[
                (self._ep_idx + i) % len(self.endpoints)
            ]
            try:
                sock = socket.create_connection(
                    (host, port), timeout=self.timeout_s
                )
                self._ep_idx = (self._ep_idx + i) % len(self.endpoints)
                break
            except OSError as e:
                last = e
        if sock is None:
            raise ConnectionError(
                f"no gateway replica reachable across "
                f"{len(self.endpoints)} endpoint(s): {last}"
            )
        self._sock = sock
        self._reader = sock.makefile("rb")
        # Session resume: re-associate this client's live jobs with the
        # (possibly restarted) gateway before any real request runs.
        rid = self._next_rid()
        self._write({"op": "hello", "rid": rid, "session": self.session})
        self._read_response(rid)
        self.reconnects += 1

    def close(self) -> None:
        if self._reader is not None:
            try:
                self._reader.close()
            except OSError:
                pass
            self._reader = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def __enter__(self) -> "GatewayClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _next_rid(self) -> str:
        self._rid += 1
        return f"{self.session}:r{self._rid}"

    def _write(self, frame: Dict[str, Any]) -> None:
        self._sock.sendall(protocol.encode_frame(frame))

    def _read_response(self, rid: str) -> Dict[str, Any]:
        """Read frames until the one answering ``rid`` arrives.

        A hostile wire may duplicate or reorder frames; anything whose rid
        is not ours is a stray (an old duplicate, a reordered earlier
        response) and is dropped on the floor — correctness never depends
        on arrival order.
        """
        deadline = time.monotonic() + self.timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout(f"no response to {rid}")
            self._sock.settimeout(remaining)
            line = self._reader.readline(protocol.MAX_FRAME_BYTES + 1)
            if not line:
                raise ConnectionError("gateway closed the connection")
            try:
                frame = protocol.decode_frame(line)
            except GatewayError:
                continue  # torn/garbled stray — keep scanning for ours
            if frame.get("rid") != rid:
                continue
            if frame.get("ok"):
                result = frame.get("result")
                return result if isinstance(result, dict) else {}
            raise GatewayError.from_wire(frame.get("error"))

    def _backoff(self, attempt: int, hint: Optional[float]) -> float:
        base = min(self.backoff_cap_s,
                   self.backoff_base_s * (2.0 ** attempt))
        if hint is not None:
            base = max(base, float(hint))
        # Deterministic jitter: same seed → same schedule, but two clients
        # with different seeds desynchronize instead of thundering together.
        return base * (0.5 + self._rng.random())

    def _call(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """One request with reconnect + retry. The frame is identical on
        every attempt (same dedup_key, fresh rid), so retries are safe."""
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                if self._sock is None:
                    self._connect()
                rid = self._next_rid()
                self._write(dict(frame, rid=rid, session=self.session))
                return self._read_response(rid)
            except GatewayError as e:
                if not e.retriable:
                    raise
                last = e
                hint = e.retry_after_s
                if len(self.endpoints) > 1:
                    # A retriable refusal from this replica (draining, not
                    # the leaseholder, fenced mid-failover) — try a peer.
                    self.close()
                    self._rotate_endpoint()
            except (OSError, ConnectionError) as e:
                # Transport died mid-request: drop the connection; the next
                # attempt reconnects (rotating to a peer replica when one is
                # configured) and resumes the session.
                self.close()
                self._rotate_endpoint()
                last = e
                hint = None
            self.retries += 1
            time.sleep(self._backoff(attempt, hint))
        raise GatewayError(
            protocol.GW_UNAVAILABLE,
            f"gateway unreachable after {self.max_attempts} attempts: "
            f"{type(last).__name__}: {last}",
        )

    # -------------------------------------------------------------- surface
    def submit(self, task=None, priority: float = 0.0,
               deadline_s: Optional[float] = None,
               max_retries: int = 1,
               spec: Optional[dict] = None,
               *,
               name: Optional[str] = None,
               total_batches: Optional[int] = None,
               request_deadline_s: Optional[float] = None,
               dedup_key: Optional[str] = None,
               tenant: Optional[str] = None) -> str:
        """Enqueue a job; returns the job id (the original id on a retry).

        Accepts either a task object (its ``name``/``total_batches`` cross
        the wire; the server's ``task_provider`` rebuilds the object) or
        explicit ``name=``/``total_batches=`` keywords. ``deadline_s`` is
        the *job's* completion deadline (the pressure shedder's input);
        ``request_deadline_s`` bounds only this submission's time-in-gateway
        before admission. ``tenant`` names the billing/fairness principal
        (quotas, fair-share weighting, tenant-aware shedding); omitted, the
        job runs under the default tenant.
        """
        if task is not None:
            name = getattr(task, "name", None)
            if total_batches is None:
                total_batches = getattr(task, "total_batches", None)
        if not name:
            raise GatewayError(protocol.GW_BADREQUEST,
                               "submit needs a task or a name=")
        if dedup_key is None:
            # Unique per logical submit, even across two client instances
            # resuming the same session: the counter alone would collide
            # (both start at d1), so a seeded-random component disambiguates
            # — deterministic per (seed, submit ordinal), never shared.
            self._dedup_seq += 1
            dedup_key = (f"{self.session}:d{self._dedup_seq}"
                         f"-{self._rng.randrange(1 << 30):08x}")
        frame: Dict[str, Any] = {
            "op": "submit",
            "dedup_key": dedup_key,
            "job": {
                "name": name,
                "total_batches": int(total_batches or 0),
                "priority": priority,
                "deadline_s": deadline_s,
                "max_retries": max_retries,
                "spec": spec,
                "tenant": tenant,
            },
        }
        if request_deadline_s is not None:
            frame["deadline_s"] = request_deadline_s
        return str(self._call(frame)["job_id"])

    def status(self, job_id: str) -> dict:
        """Point-in-time snapshot of the job's lifecycle record."""
        return self._call({"op": "status", "job": job_id})

    def wait(self, job_id: str, timeout: Optional[float] = None) -> dict:
        """Block until the job is DONE/FAILED/EVICTED; raises
        ``TimeoutError`` otherwise. Long waits are chunked into bounded
        server-side waits so a single TCP stall never wedges the caller
        past its transport timeout."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        while True:
            remaining = (
                deadline - time.monotonic() if deadline is not None else None
            )
            if remaining is not None and remaining <= 0:
                raise TimeoutError(
                    f"job {job_id} not terminal after {timeout}s"
                )
            # Ask the server to hold for well under our transport timeout —
            # a chunk that races _read_response's deadline would turn every
            # quiet wait into a spurious reconnect.
            chunk = max(0.1, self.timeout_s * 0.5)
            if remaining is not None:
                chunk = min(chunk, remaining)
            snap = self._call(
                {"op": "wait", "job": job_id, "timeout_s": chunk}
            )
            if snap.get("terminal") or snap.get("state") in _TERMINAL_STATES:
                snap.pop("terminal", None)
                return snap

    def cancel(self, job_id: str) -> bool:
        """Request eviction; False if the job already reached a terminal
        state."""
        return bool(self._call({"op": "cancel", "job": job_id})["cancelled"])

    def ping(self) -> dict:
        return self._call({"op": "ping"})
