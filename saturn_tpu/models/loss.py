"""Pretraining losses (reference: ``GPTJ.py:491-499`` shifted cross-entropy)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def pretraining_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Next-token cross-entropy: logits[:, :-1] predict tokens[:, 1:].

    Mirrors the reference's shift-and-flatten CE (``GPTJ.py:491-499``) where
    input and label are the same token batch (``dataloaders.py:22-24``).
    """
    shifted_logits = logits[:, :-1, :]
    shifted_labels = tokens[:, 1:]
    ce = optax.softmax_cross_entropy_with_integer_labels(
        shifted_logits, shifted_labels
    )
    return ce.mean()


# Executors may compute this exact objective via a model's fused head+loss
# (``ModelSpec.fused_loss_fn`` → ops/ce.py) instead of materializing logits.
# The marker is an objective TAG matched against ``ModelSpec.
# fused_loss_objective`` — the fused path only engages when the model's
# fused function implements exactly this loss (a custom loss_fn carries no
# tag and always gets the logits path; a mismatched pairing, e.g. a BERT
# spec driven with pretraining_loss, falls back too).
pretraining_loss.supports_fused_head = "causal-lm"
