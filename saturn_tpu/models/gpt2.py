"""GPT-2 family in flax.linen, built TPU-first.

Parity target: the reference's hand-rolled GPT-J/GPT-2 zoo
(``examples/wikitext103/models/GPTJ.py:25-526``). The reference flattened the
model into an ``nn.Sequential`` so GPipe/OffloadModel could partition layers
(``GPTJ.py:502-526``). The TPU-native analog of that structural property is a
**scanned layer stack**: all transformer blocks are one ``nn.scan`` with a
leading layer axis on every block param. That single axis is what makes every
parallelism technique a *sharding annotation*:

- pipeline: shard the layer axis over a ``stage`` mesh axis,
- FSDP: shard the widest weight axis over ``data``,
- tensor parallel: shard qkv/mlp matrices over ``model``,
- offload: host-offload the stacked params wholesale.

Design choices for the MXU: bf16 activations/compute, fp32 params and softmax
accumulation; weights kept as large fused matmuls (single qkv projection,
fused MLP) so XLA tiles them onto the systolic array.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from saturn_tpu.core.modelspec import ModelSpec


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50304  # padded to a multiple of 128 for MXU tiling
    seq_len: int = 512       # reference trains at context 512 (GPTJ.py:507)
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: Optional[int] = None  # default 4*d_model
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False  # rematerialize blocks (activation checkpointing)
    # GPT-J structure (reference ``GPTJ.py:44-79`` rotary helpers,
    # ``GPTJ.py:392-424`` block): rotary position embeddings on the first
    # ``rotary_dim`` dims of q/k (no learned positions), and the attention +
    # MLP branches applied in parallel off one LayerNorm.
    rotary: bool = False
    rotary_dim: Optional[int] = None  # default: full head_dim
    parallel_residual: bool = False
    # Mixture-of-experts: replace the dense MLP with a Switch-routed expert
    # MLP (ops/moe.py). Aux load-balance loss is sown and surfaced via
    # ``ModelSpec.apply_with_aux_fn``.
    moe: bool = False
    n_experts: int = 8
    capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2
    # Sequence-parallel mode: name of the mesh axis the sequence is sharded
    # over. When set, the model must run inside shard_map — attention becomes
    # ring attention (ops/ring.py) or Ulysses all-to-all attention
    # (ops/ulysses.py) per ``seq_mode``, and positions are offset by the
    # shard index. None = dense single-program attention.
    seq_axis: Optional[str] = None
    seq_axis_size: int = 1
    seq_mode: str = "ring"  # "ring" | "ulysses"
    # Double-buffer the ring's k/v neighbor hop: ship block s+1 while block
    # s is still being folded (ops/ring.py overlap schedule; bit-identical
    # output, only the hop's program order moves). Ring mode only.
    seq_overlap: bool = False
    # Single-program attention implementation: "dense" (XLA einsums), "flash"
    # (fused Pallas kernel, ops/flash.py), or "auto" (flash wherever the
    # kernel can lower — measured on the v5e chip: 1.01x at seq 512, 1.42x at
    # 1024, 1.97x at 2048, and dense OOMs first at long seq; BASELINE.md
    # attention table). Ignored when seq_axis is set (sequence-parallel
    # attention has its own kernels).
    attention: str = "auto"
    # False = bidirectional (encoder / BERT-class) attention. Sequence-
    # parallel attention paths assume causal, so seq techniques are only
    # feasible for causal configs.
    causal: bool = True
    # Llama-class structure knobs (beyond the reference's GPT-2/GPT-J zoo):
    # RMSNorm instead of LayerNorm, SwiGLU instead of GELU, and
    # grouped-query attention (n_kv_heads < n_heads). The flash kernel
    # takes grouped k/v natively (ops/flash.py — the (B, H, T, D) k/v
    # expansion never materializes); dense/ring/ulysses see k/v repeated
    # to n_heads activation-side. n_kv_heads=None keeps the fused 3D qkv
    # projection and exact param-shape compatibility with every earlier
    # preset.
    norm: str = "layernorm"          # "layernorm" | "rmsnorm"
    mlp_act: str = "gelu"            # "gelu" | "swiglu"
    n_kv_heads: Optional[int] = None
    # lax.scan unroll factor for the layer stack. The round-3 profiler trace
    # showed the scan's dynamic-update-slice activation stashing dragging
    # the MLP matmul fusions to ~0.4-0.5 efficiency; unrolling lets XLA
    # address the stash statically. 1 = plain scan (smallest compile);
    # measure before changing the default (benchmarks/profile_step.py).
    scan_unroll: int = 1
    name: str = "gpt2-small"

    def __post_init__(self) -> None:
        if self.seq_mode not in ("ring", "ulysses"):
            raise ValueError(
                f"seq_mode must be 'ring' or 'ulysses', got {self.seq_mode!r}"
            )
        if self.attention not in ("auto", "dense", "flash"):
            raise ValueError(
                f"attention must be 'auto', 'dense' or 'flash', "
                f"got {self.attention!r}"
            )
        if self.rotary:
            rd = self.rotary_dim if self.rotary_dim is not None else self.head_dim
            if rd % 2 != 0 or rd > self.head_dim:
                raise ValueError(
                    f"rotary_dim must be even and <= head_dim "
                    f"({self.head_dim}), got {rd}"
                )
        if self.norm not in ("layernorm", "rmsnorm"):
            raise ValueError(f"norm must be 'layernorm' or 'rmsnorm', "
                             f"got {self.norm!r}")
        if self.mlp_act not in ("gelu", "swiglu"):
            raise ValueError(f"mlp_act must be 'gelu' or 'swiglu', "
                             f"got {self.mlp_act!r}")
        if self.n_kv_heads is not None and (
            self.n_kv_heads < 1 or self.n_heads % self.n_kv_heads != 0
        ):
            raise ValueError(
                f"n_kv_heads must divide n_heads ({self.n_heads}), "
                f"got {self.n_kv_heads}"
            )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        return self.d_ff if self.d_ff is not None else 4 * self.d_model

    def example_inputs(self, batch_size: int = 1):
        return jnp.zeros((batch_size, self.seq_len), dtype=jnp.int32)


# Size presets matching the public GPT-2 family plus a GPT-J-class config
# (reference example workload is GPT-J-6B, ``GPTJ.py:504-507``) and a tiny
# config for CPU-mesh tests.
PRESETS: Dict[str, Dict[str, Any]] = {
    "test-tiny": dict(d_model=64, n_layers=2, n_heads=4, vocab_size=256, seq_len=64),
    "gpt2-small": dict(d_model=768, n_layers=12, n_heads=12),
    "gpt2-medium": dict(d_model=1024, n_layers=24, n_heads=16),
    "gpt2-large": dict(d_model=1280, n_layers=36, n_heads=20),
    "gpt2-xl": dict(d_model=1600, n_layers=48, n_heads=25),
    # GPT-J-6B: rotary on the first 64 head dims + parallel attn/MLP residual
    # (reference ``GPTJ.py:82-268,392-424``; config ``GPTJ.py:504-507``).
    "gptj-6b": dict(
        d_model=4096, n_layers=28, n_heads=16, d_ff=16384,
        rotary=True, rotary_dim=64, parallel_residual=True,
    ),
    # GPT-J-class ~1.3B config (GPT-neo-1.3B-shaped): the single-chip
    # billion-parameter capability row — too big for plain residency with
    # Adam on a 16 GiB chip, the case the offload executor exists for
    # (reference ``Spilled.py:23-28``).
    "gptj-1b3": dict(
        d_model=2048, n_layers=24, n_heads=16, d_ff=8192,
        rotary=True, rotary_dim=64, parallel_residual=True,
    ),
    "gptj-test-tiny": dict(
        d_model=64, n_layers=2, n_heads=4, vocab_size=256, seq_len=64,
        rotary=True, rotary_dim=8, parallel_residual=True,
    ),
    # Llama-class family (beyond the reference zoo): RMSNorm + SwiGLU +
    # full-head rotary + grouped-query attention. Shapes follow the public
    # TinyLlama-1.1B and Llama-3-8B configs; vocab stays this framework's
    # 50304 (tied embedding head, native tokenizer world).
    "llama-1b": dict(
        d_model=2048, n_layers=22, n_heads=32, n_kv_heads=4, d_ff=5632,
        rotary=True, norm="rmsnorm", mlp_act="swiglu",
    ),
    "llama-8b": dict(
        d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336,
        rotary=True, norm="rmsnorm", mlp_act="swiglu",
    ),
    "llama-test-tiny": dict(
        d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
        vocab_size=256, seq_len=64, rotary=True, norm="rmsnorm",
        mlp_act="swiglu",
    ),
    # Switch-style MoE family (extension beyond the reference; SURVEY.md §2.3
    # lists EP as absent there).
    "moe-test-tiny": dict(
        d_model=64, n_layers=2, n_heads=4, vocab_size=256, seq_len=64,
        moe=True, n_experts=4, d_ff=128,
    ),
    "gpt2-small-moe8": dict(d_model=768, n_layers=12, n_heads=12, moe=True,
                            n_experts=8),
}


def rotary_sin_cos(positions: jax.Array, rotary_dim: int):
    """(sin, cos) tables, each (T, rotary_dim//2), fp32.

    Reference computed fixed sinusoids and rotated every-other dim
    (``GPTJ.py:44-79``); we use the equivalent half-split rotation, which XLA
    fuses into the surrounding matmuls without the interleaving gathers.
    """
    inv_freq = 1.0 / (
        10000.0 ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim)
    )
    angles = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.sin(angles), jnp.cos(angles)


def apply_rotary(t: jax.Array, sin: jax.Array, cos: jax.Array, rotary_dim: int):
    """Rotate the first ``rotary_dim`` dims of ``t`` (..., T, D) by position."""
    sin, cos = sin.astype(t.dtype), cos.astype(t.dtype)
    t_rot, t_pass = t[..., :rotary_dim], t[..., rotary_dim:]
    half = rotary_dim // 2
    t1, t2 = t_rot[..., :half], t_rot[..., half:]
    rotated = jnp.concatenate([t1 * cos - t2 * sin, t2 * cos + t1 * sin], axis=-1)
    return jnp.concatenate([rotated, t_pass], axis=-1)


def config_for(name: str, **overrides) -> GPT2Config:
    if name not in PRESETS:
        raise KeyError(f"unknown model preset {name!r}; options: {list(PRESETS)}")
    kw = dict(PRESETS[name])
    kw.update(overrides)
    return GPT2Config(name=name, **kw)


def resolve_attention(cfg: GPT2Config) -> GPT2Config:
    """Resolve attention='auto' to a concrete implementation for the current
    backend: flash wherever the Pallas kernel can lower (measured ≥ dense at
    every seq on the chip), dense otherwise (CPU tests, indivisible seq)."""
    if cfg.attention != "auto":
        return cfg
    from saturn_tpu.ops.flash import flash_supported

    return replace(cfg, attention="flash" if flash_supported(cfg) else "dense")


def _norm_cls(cfg: GPT2Config):
    """The ONE place the cfg.norm choice maps to a flax module class —
    Block norms, the model's ln_f, and the pipeline head must stay in
    sync."""
    return nn.RMSNorm if cfg.norm == "rmsnorm" else nn.LayerNorm


class Block(nn.Module):
    """Pre-LN transformer block, scan-compatible signature.

    Two residual wirings (parity with ``GPTJ.py:392-424``): sequential GPT-2
    (ln_1 → attn, ln_2 → mlp) or, with ``parallel_residual=True``, GPT-J's
    parallel form (one ln, attn and mlp added together). ``rotary=True``
    rotates the first ``rotary_dim`` q/k dims by position."""

    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, _unused):
        cfg = self.cfg
        dt, pdt = cfg.dtype, cfg.param_dtype
        B, T, D = x.shape

        def make_norm(name):
            return _norm_cls(cfg)(dtype=dt, param_dtype=pdt, name=name)

        # ---- attention ----
        h = make_norm("ln_1")(x)
        if cfg.n_kv_heads is None:
            qkv = nn.Dense(3 * D, dtype=dt, param_dtype=pdt, name="qkv")(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            kv_heads = cfg.n_heads
        else:
            # Grouped-query attention: k/v carry n_kv_heads; one fused
            # projection sized D + 2 * kv_dim.
            kv_heads = cfg.n_kv_heads
            kv_dim = kv_heads * cfg.head_dim
            qkv = nn.Dense(D + 2 * kv_dim, dtype=dt, param_dtype=pdt,
                           name="qkv")(h)
            q = qkv[..., :D]
            k = qkv[..., D:D + kv_dim]
            v = qkv[..., D + kv_dim:]

        def heads(t, n):
            return t.reshape(B, T, n, cfg.head_dim).transpose(0, 2, 1, 3)

        q = heads(q, cfg.n_heads)
        k, v = heads(k, kv_heads), heads(v, kv_heads)
        if cfg.rotary:
            rd = cfg.rotary_dim or cfg.head_dim
            if cfg.seq_axis is not None:
                # Global positions for a sequence-sharded chunk.
                offset = jax.lax.axis_index(cfg.seq_axis) * T
            else:
                offset = 0
            sin, cos = rotary_sin_cos(jnp.arange(T) + offset, rd)
            q = apply_rotary(q, sin, cos, rd)
            k = apply_rotary(k, sin, cos, rd)
        if kv_heads != cfg.n_heads and not (
            cfg.seq_axis is None and self._attention_impl() == "flash"
        ):
            # GQA on the non-flash paths: repeat k/v head groups up to
            # n_heads so dense/ring/ulysses see matched head counts. The
            # params stay at kv_heads — the repeat is activation-only. The
            # flash kernel handles grouped k/v natively (ops/flash.py), so
            # the expanded activations never exist there.
            rep = cfg.n_heads // kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        if cfg.seq_axis is not None:
            if cfg.seq_mode == "ulysses":
                from saturn_tpu.ops.ulysses import ulysses_attention

                attn = ulysses_attention(
                    q, k, v, axis_name=cfg.seq_axis, axis_size=cfg.seq_axis_size
                )
            else:
                from saturn_tpu.ops.ring import ring_attention

                attn = ring_attention(
                    q, k, v, axis_name=cfg.seq_axis,
                    axis_size=cfg.seq_axis_size, overlap=cfg.seq_overlap,
                )
        elif self._attention_impl() == "flash":
            from saturn_tpu.ops.flash import flash_attention

            attn = flash_attention(q, k, v, causal=cfg.causal)
        else:
            # fp32 softmax accumulation for stability; matmuls stay bf16-in.
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
            scores = scores / math.sqrt(cfg.head_dim)
            if cfg.causal:
                mask = jnp.tril(jnp.ones((T, T), dtype=bool))
                scores = jnp.where(mask[None, None], scores, jnp.float32(-1e30))
            probs = jax.nn.softmax(scores, axis=-1).astype(dt)
            attn = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, T, D)
        attn = nn.Dense(D, dtype=dt, param_dtype=pdt, name="attn_out")(attn)

        # ---- mlp (dense or Switch-routed experts) ----
        def mlp(inp):
            if cfg.moe:
                return self._moe_mlp(inp)
            if cfg.mlp_act == "swiglu":
                # Separate gate/up projections (NOT one fused 2F Dense): the
                # TP column rule shards each kernel's output dim, so
                # gate_i/up_i stay on the same model shard and silu(gate)*up
                # is local — a fused contiguous split would put all gate
                # columns on shard 0 and force a full-activation reshard
                # per layer.
                gate = nn.Dense(cfg.ff_dim, dtype=dt, param_dtype=pdt,
                                name="mlp_gate")(inp)
                up = nn.Dense(cfg.ff_dim, dtype=dt, param_dtype=pdt,
                              name="mlp_in")(inp)
                m = nn.silu(gate) * up
            else:
                m = nn.Dense(cfg.ff_dim, dtype=dt, param_dtype=pdt,
                             name="mlp_in")(inp)
                m = nn.gelu(m, approximate=True)
            return nn.Dense(D, dtype=dt, param_dtype=pdt, name="mlp_out")(m)

        if cfg.parallel_residual:
            # GPT-J wiring: attn and MLP both read ln_1(x), one residual add
            # (reference ``GPTJ.py:392-424``).
            x = x + attn + mlp(h)
        else:
            x = x + attn
            h2 = make_norm("ln_2")(x)
            x = x + mlp(h2)
        return x, None

    def _attention_impl(self) -> str:
        """'auto' resolution for configs built without ``build_gpt2`` — one
        rule, shared with the factory path (:func:`resolve_attention`)."""
        return resolve_attention(self.cfg).attention

    def _moe_mlp(self, inp):
        """Expert MLP with explicit (E, ...) weight tables — the leading
        expert axis is what the EP executor shards over the ``expert`` mesh
        axis (dim 1 once the layer scan adds its leading axis)."""
        from saturn_tpu.ops.moe import switch_moe

        cfg = self.cfg
        D, E, F = cfg.d_model, cfg.n_experts, cfg.ff_dim
        pdt = cfg.param_dtype
        init = nn.initializers.normal(0.02)
        router_w = self.param("router", init, (D, E), pdt)
        we_in = self.param("we_in", init, (E, D, F), pdt)
        be_in = self.param("be_in", nn.initializers.zeros, (E, F), pdt)
        we_out = self.param("we_out", init, (E, F, D), pdt)
        be_out = self.param("be_out", nn.initializers.zeros, (E, D), pdt)
        y, aux = switch_moe(
            inp,
            router_w.astype(cfg.dtype),
            we_in.astype(cfg.dtype),
            be_in.astype(cfg.dtype),
            we_out.astype(cfg.dtype),
            be_out.astype(cfg.dtype),
            capacity_factor=cfg.capacity_factor,
        )
        self.sow("aux_loss", "moe_load_balance", aux)
        return y


class GPT2(nn.Module):
    """Decoder-only LM with a scanned block stack under param key 'blocks'."""

    cfg: GPT2Config

    @nn.compact
    def __call__(self, tokens, return_hidden: bool = False):
        cfg = self.cfg
        B, T = tokens.shape
        wte = self.param(
            "wte",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.d_model),
            cfg.param_dtype,
        )
        if cfg.rotary:
            # GPT-J: positions enter through rotary q/k rotation in each
            # block; there is no learned position table (``GPTJ.py:271-338``).
            x = wte[tokens].astype(cfg.dtype)
        else:
            wpe = self.param(
                "wpe",
                nn.initializers.normal(0.01),
                (cfg.seq_len, cfg.d_model),
                cfg.param_dtype,
            )
            if cfg.seq_axis is not None:
                # Local chunk of a sequence-sharded batch: positions offset by
                # the shard index (T here is the per-shard chunk length).
                offset = jax.lax.axis_index(cfg.seq_axis) * T
                pos = jax.lax.dynamic_slice_in_dim(wpe, offset, T, axis=0)
            else:
                pos = wpe[:T]
            x = wte[tokens].astype(cfg.dtype) + pos.astype(cfg.dtype)

        block_cls = Block
        if cfg.remat:
            block_cls = nn.remat(
                Block, prevent_cse=False, policy=jax.checkpoint_policies.nothing_saveable
            )
        stack = nn.scan(
            block_cls,
            variable_axes={"params": 0, "aux_loss": 0},
            split_rngs={"params": True},
            length=cfg.n_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
            unroll=cfg.scan_unroll,
        )
        x, _ = stack(cfg, name="blocks")(x, None)

        x = _norm_cls(cfg)(dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                           name="ln_f")(x)
        if return_hidden:
            # final hidden states for the fused head+loss path (ops/ce.py);
            # the caller owns the tied-head matmul
            return x
        # Tied output head (reference ties via lm_head over flattened weights,
        # GPTJ.py:340-390); fp32 logits for a stable loss.
        logits = jnp.einsum("btd,vd->btv", x, wte.astype(cfg.dtype))
        return logits.astype(jnp.float32)


def build_gpt2(
    name: str = "gpt2-small", pretrained: Any = None, **overrides
) -> ModelSpec:
    """Model factory suitable for ``Task(get_model=...)``.

    Returns a ModelSpec whose params tree is
    ``{'wte', 'blocks': {...leading layer axis...}, 'ln_f'}`` plus ``'wpe'``
    for non-rotary configs (rotary presets have no learned position table).

    ``pretrained``: a local torch/npz state-dict path or an already-loaded
    mapping in HF GPT-2/GPT-J naming — ``init_fn`` then returns the mapped
    weights instead of a random init, which makes every technique a
    *fine-tuning* executor (the reference's canonical workflow,
    ``examples/wikitext103/models/GPTJ.py:502-526``). Shape-validated
    against the preset up front; forwarded by ``Task.get_model`` kwargs like
    any other override.
    """
    cfg = resolve_attention(config_for(name, **overrides))
    module = GPT2(cfg)

    if pretrained is None:
        def init_fn(rng):
            return module.init(rng, cfg.example_inputs())["params"]
    else:
        from saturn_tpu.models import ingest

        if isinstance(pretrained, str):
            # memoized: search builds one spec per candidate config and must
            # not re-read a multi-GB checkpoint each time
            mapped, unused = ingest.cached_params_from_path(pretrained, cfg)
        else:
            mapped, unused = ingest.params_from_state_dict(
                dict(pretrained), cfg
            )
        if unused:
            import logging

            logging.getLogger("saturn_tpu").info(
                "pretrained ingest: %d unused tensors (%s...)",
                len(unused), ", ".join(unused[:4]))
        ingest.validate_against(
            mapped, jax.eval_shape(
                lambda: module.init(jax.random.PRNGKey(0),
                                    cfg.example_inputs())["params"]
            )
        )

        def init_fn(rng):
            del rng  # deterministic: weights come from the state dict
            return jax.tree_util.tree_map(
                lambda x: jnp.asarray(x, dtype=cfg.param_dtype), mapped
            )

    def apply_fn(params, tokens):
        return module.apply({"params": params}, tokens)

    # Pipeline decomposition: embed / one-block / head as pure functions so
    # the pipeline executor can stage any model exposing these (the analog of
    # the reference's requirement that models be nn.Sequential-flattenable,
    # ``GPTJ.py:502-526``).
    def pipeline_embed(other_params, tokens):
        T = tokens.shape[-1]
        x = other_params["wte"][tokens].astype(cfg.dtype)
        if not cfg.rotary:
            x = x + other_params["wpe"][:T].astype(cfg.dtype)
        return x

    def pipeline_block(layer_params, x):
        y, _ = Block(cfg).apply({"params": layer_params}, x, None)
        return y

    def pipeline_head(other_params, x):
        ln = _norm_cls(cfg)(dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        xn = ln.apply({"params": other_params["ln_f"]}, x)
        logits = jnp.einsum("btd,vd->btv", xn, other_params["wte"].astype(cfg.dtype))
        return logits.astype(jnp.float32)

    def hidden_fn(params, tokens):
        return module.apply({"params": params}, tokens, return_hidden=True)

    fused_loss_fn = fused_loss_parts_fn = None
    if cfg.causal and not cfg.moe and cfg.seq_axis is None:
        # Fused head+loss (ops/ce.py): hidden states + the tied wte go
        # straight into the Pallas CE kernel — no (B,T,V) logits tensor.
        # Identical objective to pretraining_loss∘apply_fn (next-token CE,
        # mean over B*(T-1) real targets); the op itself falls back to a
        # dense computation off-TPU, so this is always safe to call.
        def _fused(params, tokens, reduction):
            from saturn_tpu.ops.ce import fused_linear_cross_entropy

            x = hidden_fn(params, tokens)
            labels = jnp.pad(
                tokens[:, 1:].astype(jnp.int32), ((0, 0), (0, 1)),
                constant_values=-1,
            )
            return fused_linear_cross_entropy(
                x, params["wte"], labels, reduction=reduction
            )

        def fused_loss_fn(params, tokens):
            return _fused(params, tokens, "mean")

        def fused_loss_parts_fn(params, tokens):
            # (loss_sum, valid_count) for sharded callers (the dp shard_map
            # wrapper psums both parts before dividing)
            return _fused(params, tokens, "sum_count")

    apply_with_aux_fn = None
    if cfg.moe:

        def apply_with_aux_fn(params, tokens):
            logits, mut = module.apply(
                {"params": params}, tokens, mutable=["aux_loss"]
            )
            aux_leaves = jax.tree.leaves(mut.get("aux_loss", {}))
            aux = sum((jnp.sum(a) for a in aux_leaves), jnp.float32(0.0))
            return logits, aux * cfg.moe_aux_weight

    hints = {
        "block_param_key": "blocks",  # where the scanned layer stack lives
        "n_layers": cfg.n_layers,
        "moe": {"n_experts": cfg.n_experts} if cfg.moe else None,
        "embed_param_keys": ("wte",) if cfg.rotary else ("wte", "wpe"),
        # factory accepts seq_axis/seq_axis_size; the sharded attention +
        # boundary-label loss assume causal next-token training.
        "seq_parallel": cfg.causal,
        "pipeline": {
            "embed": pipeline_embed,
            "block": pipeline_block,
            "head": pipeline_head,
            "act_shape": lambda batch, seqlen: (batch, seqlen, cfg.d_model),
            "act_dtype": cfg.dtype,
        },
    }
    return ModelSpec(
        init_fn=init_fn,
        apply_fn=apply_fn,
        config=cfg,
        hints=hints,
        apply_with_aux_fn=apply_with_aux_fn,
        fused_loss_fn=fused_loss_fn,
        fused_loss_parts_fn=fused_loss_parts_fn,
        fused_loss_objective="causal-lm" if fused_loss_fn else None,
        hidden_fn=hidden_fn,
    )


def build_gptj(name: str = "gptj-6b", **overrides) -> ModelSpec:
    """GPT-J factory (rotary + parallel residual; reference ``GPTJ.py:271-390``)."""
    return build_gpt2(name, **overrides)


def build_llama(name: str = "llama-1b", **overrides) -> ModelSpec:
    """Llama-class factory (RMSNorm + SwiGLU + GQA + rotary) — a family the
    reference zoo never had; every technique works on it because the stack
    is the same scanned-block ModelSpec contract."""
    return build_gpt2(name, **overrides)
