"""BERT-class encoder family: bidirectional transformer + masked-LM objective.

Second model family alongside GPT-2/GPT-J (the target workload class is a
"GPT-2/BERT-class sweep", BASELINE.md). Reuses the scanned GPT-2 stack
(``models/gpt2.py``) with ``causal=False`` — parallelism techniques see the
identical param-tree structure, so dp/fsdp/tp/pp/offload all work unchanged;
sequence-parallel techniques correctly report infeasible (their
boundary-label loss assumes causal next-token training).

Masking is *static-positional* (every ``MASK_STRIDE``-th token): the mask
derives from position alone, so the jitted train step needs no RNG plumbing
or dynamic shapes, and the loss and forward agree on exactly which positions
are masked. This trades BERT's random 15% masking for determinism; the
compute/communication profile — what the profiler and solver care about —
is identical.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import optax

from saturn_tpu.core.modelspec import ModelSpec
from saturn_tpu.models import gpt2

MASK_STRIDE = 7   # ~14% of positions masked, close to BERT's 15%
MASK_OFFSET = 3

BERT_PRESETS: Dict[str, Dict[str, Any]] = {
    "bert-test-tiny": dict(
        d_model=64, n_layers=2, n_heads=4, vocab_size=256, seq_len=64,
    ),
    "bert-base": dict(d_model=768, n_layers=12, n_heads=12),
    "bert-large": dict(d_model=1024, n_layers=24, n_heads=16),
}

# Encoder presets live in the shared preset table so config_for/build_gpt2
# machinery (validation, overrides) applies unchanged.
for _name, _kw in BERT_PRESETS.items():
    gpt2.PRESETS.setdefault(_name, dict(_kw, causal=False))


def _mask(T: int):
    return (jnp.arange(T) % MASK_STRIDE) == MASK_OFFSET


def mlm_loss(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Mean cross-entropy at the masked positions vs the ORIGINAL tokens.

    Pairs with :func:`build_bert`, whose forward replaces the same positions
    with the [MASK] id — ``tokens`` is the unmasked batch the dataloader
    serves, exactly like the causal ``pretraining_loss`` contract.
    """
    B, T = tokens.shape
    m = _mask(T)[None, :].astype(jnp.float32)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, tokens)
    return (ce * m).sum() / (m.sum() * B)


# Fused-head tag (see models/loss.py): the MLM objective is ignore-index CE
# over the masked positions — exactly what ops/ce.py computes when unmasked
# positions carry label -1.
mlm_loss.supports_fused_head = "mlm"


def build_bert(name: str = "bert-base", **overrides) -> ModelSpec:
    """Encoder ModelSpec for ``Task(get_model=...)``; train with :func:`mlm_loss`.

    The top vocab id serves as [MASK]. That id must never occur in the data —
    otherwise unmasked occurrences are indistinguishable from [MASK] and
    masked positions whose label is the top id leak. The data pipeline
    enforces this: pair BERT tasks with ``make_lm_dataset(...,
    reserved_ids=1)``, which keeps ids in ``[0, vocab_size - 1)`` on every
    path (synthetic generation, word vocab cap, byte-tokenizer validation).
    The [MASK] substitution is applied inside every forward entry point —
    including the pipeline-stage ``embed`` hint, so pp/offload-streaming
    train the same objective as dp/fsdp/tp.
    """
    if name not in BERT_PRESETS:
        raise KeyError(f"unknown BERT preset {name!r}; options: {list(BERT_PRESETS)}")
    spec = gpt2.build_gpt2(name, **overrides)
    cfg = spec.config
    mask_id = cfg.vocab_size - 1

    def mask_tokens(tokens):
        return jnp.where(_mask(tokens.shape[-1])[None, :], mask_id, tokens)

    inner_apply = spec.apply_fn

    def apply_fn(params, tokens):
        return inner_apply(params, mask_tokens(tokens))

    hints = dict(spec.hints)
    if "pipeline" in hints:
        pipe = dict(hints["pipeline"])
        inner_embed = pipe["embed"]
        pipe["embed"] = lambda other, tokens: inner_embed(other, mask_tokens(tokens))
        hints["pipeline"] = pipe

    fused_loss_fn = fused_loss_parts_fn = None
    if spec.hidden_fn is not None:
        # Fused head+loss for MLM (ops/ce.py): hidden states of the MASKED
        # input against the original tokens, unmasked positions ignored via
        # label -1 — the same mean-over-masked objective as mlm_loss.
        def _fused(params, tokens, reduction):
            from saturn_tpu.ops.ce import fused_linear_cross_entropy

            x = spec.hidden_fn(params, mask_tokens(tokens))
            labels = jnp.where(
                _mask(tokens.shape[-1])[None, :],
                tokens.astype(jnp.int32), -1,
            )
            return fused_linear_cross_entropy(
                x, params["wte"], labels, reduction=reduction
            )

        def fused_loss_fn(params, tokens):
            return _fused(params, tokens, "mean")

        def fused_loss_parts_fn(params, tokens):
            return _fused(params, tokens, "sum_count")

    return ModelSpec(
        init_fn=spec.init_fn,
        apply_fn=apply_fn,
        config=cfg,
        hints=hints,
        apply_with_aux_fn=None,
        fused_loss_fn=fused_loss_fn,
        fused_loss_parts_fn=fused_loss_parts_fn,
        fused_loss_objective="mlm" if fused_loss_fn else None,
        hidden_fn=spec.hidden_fn,
    )
