"""Pretrained-weight ingestion: HF/torch state dicts → saturn_tpu param trees.

The reference's canonical workload is *fine-tuning* pretrained weights: its
``get_model`` downloads HF GPT-J-6B, flattens the module tree into an
``nn.Sequential``, and caches the result
(``/root/reference/examples/wikitext103/models/GPTJ.py:502-526``). This module
is the TPU-native analog: map a torch-format state dict (HF ``GPT2LMHeadModel``
or ``GPTJForCausalLM`` naming) onto the scanned-stack flax tree that
``models/gpt2.py`` trains — so a user can point a Task at downloaded weights
and fine-tune under any technique the solver picks.

Layout notes (the whole reason this mapper exists):

- **HF GPT-2 uses Conv1D** — weights are stored ``(in, out)``, which IS the
  flax ``Dense`` kernel layout: no transposes. Its ``c_attn`` is the same
  fused q|k|v projection as our ``qkv``.
- **HF GPT-J uses nn.Linear** — weights are ``(out, in)``: every matrix is
  transposed, and the separate ``q/k/v_proj`` are fused into one ``qkv``
  kernel. GPT-J's attention has no biases; ours do (zeros preserve the math).
- **Per-layer tensors are stacked** along a leading layer axis, because the
  block stack is one ``nn.scan`` (the property every executor shards).
- **Vocab padding**: HF GPT-2's 50257 rows are zero-padded up to the
  preset's lane-aligned ``vocab_size`` (50304). Padded rows are real vocab
  entries the data pipeline never emits; zero embeddings contribute constant
  logit 0, the standard padding treatment.
- **Tied head**: our LM head is the tied ``wte`` (GPT-2's own convention).
  GPT-J ships an untied ``lm_head``; by default its ``wte`` is loaded and the
  ``lm_head`` tensors are reported in the returned ``unused`` list — pass
  ``tie_from_lm_head=True`` to load the head matrix into ``wte`` instead
  (better next-token fidelity, slightly worse embedding fidelity).
- **Rotary convention**: HF GPT-J rotates interleaved (every-two) lanes;
  ``models/gpt2.py`` rotates split halves. Equivalent up to a fixed lane
  permutation learned away within a few fine-tuning steps; exact-logit parity
  would need a per-head lane shuffle of q/k, documented here rather than
  silently applied.

No network access anywhere: callers hand a local path (torch ``.pt``/``.bin``
or ``.npz``) or an already-loaded mapping. Tests exercise the full round trip
against synthetically written torch-format state dicts
(``tests/test_ingest.py``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from saturn_tpu.analysis import concurrency as _tsan

__all__ = [
    "load_torch_state_dict",
    "params_from_state_dict",
    "gpt2_params_from_state_dict",
    "gptj_params_from_state_dict",
]


_cache_key: Optional[tuple] = None
_cache_val: Optional[tuple] = None
# Guards the size-1 cache above: parallel trial sweeps build ModelSpecs
# from worker threads, and an unsynchronized lookup/load/store interleave
# can both double-load a multi-GB checkpoint and publish a half-written
# (key, val) pair (key from one thread, val from another).
_cache_lock = _tsan.lock("ingest.params_cache")


def cached_params_from_path(path: str, cfg: Any, **kw):
    """Load + map ``path`` once per (file, preset shape) — strategy search
    builds one ModelSpec per candidate config (``spmd_base._build_uncached``),
    and re-reading a multi-GB checkpoint per config would dominate the sweep.
    Size-1 cache: a 6B mapped tree is ~24 GB of host RAM; never hold two.

    Thread-safe: lookup, load, and store all happen under
    ``ingest.params_cache`` — concurrent callers with the same key share
    one load, and a (key, val) pair is only ever published whole. The
    multi-GB torch load stays under the lock deliberately: two concurrent
    loads would blow host RAM, which is worse than serializing them.
    """
    global _cache_key, _cache_val
    import os

    key = (
        os.path.abspath(path), os.path.getmtime(path), cfg.n_layers,
        cfg.d_model, cfg.vocab_size, cfg.seq_len, cfg.rotary,
        tuple(sorted(kw.items())),
    )
    with _cache_lock:
        if _cache_key == key and _cache_val is not None:
            return _cache_val
        mapped, unused = params_from_state_dict(load_torch_state_dict(path),
                                                cfg, **kw)
        _cache_key, _cache_val = key, (mapped, unused)
        return _cache_val


def load_torch_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Load a state dict from disk into plain numpy arrays.

    Accepts torch-format files (``.pt``/``.bin``, loaded with
    ``weights_only=True`` so untrusted pickles cannot execute code) and
    ``.npz`` archives. Torch is an optional dependency of exactly this
    loader — the rest of the framework never imports it.
    """
    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: np.asarray(z[k]) for k in z.files}
    import torch  # local import: only the ingestion path needs torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(sd, "state_dict"):  # a saved module instead of a state dict
        sd = sd.state_dict()
    return {k: v.detach().cpu().numpy() for k, v in sd.items()}


def _strip_prefix(sd: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Drop the HF ``transformer.`` wrapper prefix if present."""
    if any(k.startswith("transformer.") for k in sd):
        out = {}
        for k, v in sd.items():
            out[k.removeprefix("transformer.")] = v
        return out
    return dict(sd)


def _stack(sd, fmt: str, n_layers: int, transpose: bool = False) -> np.ndarray:
    tensors = []
    for i in range(n_layers):
        t = np.asarray(sd.pop(fmt.format(i)))
        tensors.append(t.T if transpose else t)
    return np.stack(tensors)


def _pad_vocab(wte: np.ndarray, vocab_size: int, name: str) -> np.ndarray:
    v, d = wte.shape
    if v > vocab_size:
        raise ValueError(
            f"{name} has {v} rows but the model preset only has "
            f"vocab_size={vocab_size}; pick a preset with vocab_size >= {v}"
        )
    if v < vocab_size:
        wte = np.pad(wte, ((0, vocab_size - v), (0, 0)))
    return wte


def gpt2_params_from_state_dict(
    sd: Dict[str, np.ndarray], cfg: Any
) -> Tuple[Dict[str, Any], List[str]]:
    """HF ``GPT2LMHeadModel`` state dict → saturn_tpu param tree.

    Returns ``(params, unused_keys)``. Conv1D layout means zero transposes;
    see module docstring for the vocab-pad and position-slice rules.
    """
    sd = _strip_prefix(sd)
    L = cfg.n_layers
    wpe = np.asarray(sd.pop("wpe.weight"))
    if wpe.shape[0] < cfg.seq_len:
        raise ValueError(
            f"pretrained wpe covers {wpe.shape[0]} positions < seq_len "
            f"{cfg.seq_len}"
        )
    params: Dict[str, Any] = {
        "wte": _pad_vocab(np.asarray(sd.pop("wte.weight")), cfg.vocab_size,
                          "wte.weight"),
        "wpe": wpe[: cfg.seq_len],
        "ln_f": {"scale": np.asarray(sd.pop("ln_f.weight")),
                 "bias": np.asarray(sd.pop("ln_f.bias"))},
        "blocks": {
            "ln_1": {"scale": _stack(sd, "h.{}.ln_1.weight", L),
                     "bias": _stack(sd, "h.{}.ln_1.bias", L)},
            "ln_2": {"scale": _stack(sd, "h.{}.ln_2.weight", L),
                     "bias": _stack(sd, "h.{}.ln_2.bias", L)},
            "qkv": {"kernel": _stack(sd, "h.{}.attn.c_attn.weight", L),
                    "bias": _stack(sd, "h.{}.attn.c_attn.bias", L)},
            "attn_out": {"kernel": _stack(sd, "h.{}.attn.c_proj.weight", L),
                         "bias": _stack(sd, "h.{}.attn.c_proj.bias", L)},
            "mlp_in": {"kernel": _stack(sd, "h.{}.mlp.c_fc.weight", L),
                       "bias": _stack(sd, "h.{}.mlp.c_fc.bias", L)},
            "mlp_out": {"kernel": _stack(sd, "h.{}.mlp.c_proj.weight", L),
                        "bias": _stack(sd, "h.{}.mlp.c_proj.bias", L)},
        },
    }
    return params, sorted(sd)


def gptj_params_from_state_dict(
    sd: Dict[str, np.ndarray], cfg: Any, tie_from_lm_head: bool = False
) -> Tuple[Dict[str, Any], List[str]]:
    """HF ``GPTJForCausalLM`` state dict → saturn_tpu param tree.

    Linear layout: every matrix transposes; q/k/v fuse into ``qkv``; the
    bias-free attention projections get zero biases. ``tie_from_lm_head``
    loads the untied head matrix into the tied ``wte`` slot (see module
    docstring).
    """
    sd = _strip_prefix(sd)
    L, D = cfg.n_layers, cfg.d_model
    qkv_k = np.concatenate(
        [
            _stack(sd, "h.{}.attn.q_proj.weight", L, transpose=True),
            _stack(sd, "h.{}.attn.k_proj.weight", L, transpose=True),
            _stack(sd, "h.{}.attn.v_proj.weight", L, transpose=True),
        ],
        axis=2,
    )
    wte_key = "lm_head.weight" if tie_from_lm_head else "wte.weight"
    wte = np.asarray(sd.pop(wte_key))
    sd.pop("wte.weight" if tie_from_lm_head else "lm_head.weight", None)
    sd.pop("lm_head.bias", None)  # tied head has no bias slot
    # HF GPT-J registers rotary caches as buffers in some versions
    for k in [k for k in sd if k.endswith(("attn.bias", "attn.masked_bias",
                                           "embed_positions.weight"))]:
        sd.pop(k)
    params: Dict[str, Any] = {
        "wte": _pad_vocab(wte, cfg.vocab_size, wte_key),
        "ln_f": {"scale": np.asarray(sd.pop("ln_f.weight")),
                 "bias": np.asarray(sd.pop("ln_f.bias"))},
        "blocks": {
            "ln_1": {"scale": _stack(sd, "h.{}.ln_1.weight", L),
                     "bias": _stack(sd, "h.{}.ln_1.bias", L)},
            "qkv": {"kernel": qkv_k,
                    "bias": np.zeros((L, 3 * D), dtype=qkv_k.dtype)},
            "attn_out": {
                "kernel": _stack(sd, "h.{}.attn.out_proj.weight", L,
                                 transpose=True),
                "bias": np.zeros((L, D), dtype=qkv_k.dtype),
            },
            "mlp_in": {"kernel": _stack(sd, "h.{}.mlp.fc_in.weight", L,
                                        transpose=True),
                       "bias": _stack(sd, "h.{}.mlp.fc_in.bias", L)},
            "mlp_out": {"kernel": _stack(sd, "h.{}.mlp.fc_out.weight", L,
                                         transpose=True),
                        "bias": _stack(sd, "h.{}.mlp.fc_out.bias", L)},
        },
    }
    return params, sorted(sd)


def params_from_state_dict(
    sd: Dict[str, np.ndarray], cfg: Any, **kw
) -> Tuple[Dict[str, Any], List[str]]:
    """Dispatch on the state dict's key signature (GPT-2 vs GPT-J naming)."""
    keys = set(_strip_prefix(sd))
    if any(".attn.c_attn." in k for k in keys):
        if kw:
            raise TypeError(f"GPT-2 mapping takes no options, got {kw}")
        return gpt2_params_from_state_dict(sd, cfg)
    if any(".attn.q_proj." in k for k in keys):
        return gptj_params_from_state_dict(sd, cfg, **kw)
    raise ValueError(
        "unrecognized state-dict family: expected HF GPT-2 (attn.c_attn) or "
        "GPT-J (attn.q_proj) key naming; got keys like "
        + ", ".join(sorted(keys)[:5])
    )


def validate_against(params: Dict[str, Any], template: Any) -> None:
    """Shape-check a mapped tree against the model's own init structure,
    naming every mismatched path (a wrong preset fails loudly here, not as
    an XLA shape error three layers deep)."""
    import jax

    def flat(tree):
        return {
            jax.tree_util.keystr(k): v
            for k, v in jax.tree_util.tree_flatten_with_path(tree)[0]
        }

    flat_p, flat_t = flat(params), flat(template)
    problems = []
    for k in sorted(set(flat_p) | set(flat_t)):
        if k not in flat_p:
            problems.append(f"missing {k}")
        elif k not in flat_t:
            problems.append(f"unexpected {k}")
        elif tuple(flat_p[k].shape) != tuple(flat_t[k].shape):
            problems.append(
                f"{k}: got {tuple(flat_p[k].shape)}, "
                f"model wants {tuple(flat_t[k].shape)}"
            )
    if problems:
        raise ValueError(
            "pretrained state dict does not match this model preset:\n  "
            + "\n  ".join(problems)
        )
