"""Built-in parallelism technique library.

The reference shipped its techniques as example UDPs outside the core
(``examples/wikitext103/executors/``) and CONTRIBUTING.md invited a "default
library" contribution (SURVEY.md §1). Here the default library is real:
import-and-register via ``saturn_tpu.library.register_default_library()``.
"""

from __future__ import annotations

from saturn_tpu.parallel.dp import DataParallel
from saturn_tpu.parallel.fsdp import FSDP
from saturn_tpu.parallel.tp import TensorParallel

BUILTIN_TECHNIQUES = {
    "dp": DataParallel,
    "fsdp": FSDP,
    "tp": TensorParallel,
}

try:  # executors with extra requirements register themselves if importable
    from saturn_tpu.parallel.pp import Pipeline

    BUILTIN_TECHNIQUES["pp"] = Pipeline
except ImportError:  # pragma: no cover
    pass

try:
    from saturn_tpu.parallel.offload import HostOffload

    BUILTIN_TECHNIQUES["offload"] = HostOffload
except ImportError:  # pragma: no cover
    pass

try:
    from saturn_tpu.parallel.ring import RingSequenceParallel

    BUILTIN_TECHNIQUES["ring"] = RingSequenceParallel
except ImportError:  # pragma: no cover
    pass

try:
    from saturn_tpu.parallel.ep import ExpertParallel

    BUILTIN_TECHNIQUES["ep"] = ExpertParallel
except ImportError:  # pragma: no cover
    pass

try:
    from saturn_tpu.parallel.ulysses import UlyssesSequenceParallel

    BUILTIN_TECHNIQUES["ulysses"] = UlyssesSequenceParallel
except ImportError:  # pragma: no cover
    pass

# Fused multi-model stacking is NOT a registered technique — it wraps a
# member technique's program across N compatible jobs (the solver prices it
# per GROUP, not per task) — but its public surface rides along here.
try:
    from saturn_tpu.parallel import fused  # noqa: F401
except ImportError:  # pragma: no cover
    fused = None  # type: ignore[assignment]
