"""FSDP executor: GSPMD fully-sharded params over the ``data`` axis.

Replaces the reference's torch-FSDP UDP (``FSDP.py:57-245``). Where torch FSDP
wraps modules and manually all-gathers flat params, here every param's largest
dim is sharded over ``data`` (ZeRO-3) and XLA emits the all-gather before use
and reduce-scatter on grads. The autotune grid mirrors the reference's
{activation checkpointing} × {CPU offload} search (``FSDP.py:72-78``): remat
toggles block rematerialization, offload moves persistent state to host
memory ('pinned_host') where the platform supports it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from saturn_tpu.parallel import sharding as shr
from saturn_tpu.parallel.spmd_base import SPMDTechnique
from saturn_tpu.core.strategy import Techniques


def host_offload_supported() -> bool:
    import jax

    try:
        dev = jax.devices()[0]
        if dev.platform != "tpu":
            # The CPU backend advertises pinned_host but its SPMD
            # partitioner rejects device-placement annotations (RET_CHECK
            # "Side-effect HLO must have sharding"); restrict real
            # offloading to TPU, where XLA host offload is production-grade.
            return False
        kinds = {m.kind for m in dev.addressable_memories()}
        return "pinned_host" in kinds
    except Exception:
        return False


class FSDP(SPMDTechnique):
    name = "fsdp"
    technique = Techniques.FSDP

    def mesh_spec(self, n_devices, task, config) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
        return ("data",), (n_devices,)

    def param_rules(self, task, config):
        return shr.fsdp_rules(axis="data")

    def param_memory_kind(self, config) -> Optional[str]:
        return "pinned_host" if config.get("offload") else None

    def candidate_configs(self, task, n_devices) -> List[Dict[str, Any]]:
        grid: List[Dict[str, Any]] = [
            {"remat": False, "offload": False},
            {"remat": True, "offload": False},
        ]
        if host_offload_supported():
            grid += [
                {"remat": True, "offload": True},
                {"remat": False, "offload": True},
            ]
        return self._with_attention_variants(task, grid)
