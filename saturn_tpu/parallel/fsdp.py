"""FSDP executor: GSPMD fully-sharded params over the ``data`` axis.

Replaces the reference's torch-FSDP UDP (``FSDP.py:57-245``). Where torch FSDP
wraps modules and manually all-gathers flat params, here every param's largest
dim is sharded over ``data`` (ZeRO-3) and XLA emits the all-gather before use
and reduce-scatter on grads. The autotune grid mirrors the reference's
{activation checkpointing} × {CPU offload} search (``FSDP.py:72-78``): remat
toggles block rematerialization, offload moves persistent state to host
memory ('pinned_host') where the platform supports it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from saturn_tpu.ops.collective_matmul import (
    zero3_block_rules,
    zero3_loss_and_grads,
)
from saturn_tpu.ops.pipeline import pipeline_hints
from saturn_tpu.parallel import sharding as shr
from saturn_tpu.parallel.spmd_base import SPMDTechnique
from saturn_tpu.core.strategy import Techniques


def host_offload_supported() -> bool:
    import jax

    try:
        dev = jax.devices()[0]
        if dev.platform != "tpu":
            # The CPU backend advertises pinned_host but its SPMD
            # partitioner rejects device-placement annotations (RET_CHECK
            # "Side-effect HLO must have sharding"); restrict real
            # offloading to TPU, where XLA host offload is production-grade.
            return False
        kinds = {m.kind for m in dev.addressable_memories()}
        return "pinned_host" in kinds
    except Exception:
        return False


class FSDP(SPMDTechnique):
    name = "fsdp"
    technique = Techniques.FSDP

    def mesh_spec(self, n_devices, task, config) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
        return ("data",), (n_devices,)

    def param_rules(self, task, config):
        if config.get("overlap"):
            # Must match the zero3 program's in_specs leaf-for-leaf, or the
            # outer jit reshards at every shard_map boundary.
            spec = task.get_model()
            return zero3_block_rules(
                block_key=spec.hints.get("block_param_key", "blocks"),
                axis="data",
            )
        return shr.fsdp_rules(axis="data")

    def param_memory_kind(self, config) -> Optional[str]:
        return "pinned_host" if config.get("offload") else None

    def candidate_configs(self, task, n_devices) -> List[Dict[str, Any]]:
        grid: List[Dict[str, Any]] = [
            {"remat": False, "offload": False},
            {"remat": True, "offload": False},
        ]
        if host_offload_supported():
            grid += [
                {"remat": True, "offload": True},
                {"remat": False, "offload": True},
            ]
        if self._overlap_ok(task, n_devices):
            # ZeRO-3 prefetch (ops/collective_matmul.py): layer k+1's shard
            # gather rides under layer k's compute. Own grid points — the
            # trial runner times overlapped vs serial and realized cost
            # picks; bit-identical grads either way.
            grid += [
                {"remat": False, "offload": False, "overlap": True},
                {"remat": True, "offload": False, "overlap": True},
            ]
        return self._with_attention_variants(task, grid)

    def _overlap_ok(self, task, n_devices: int) -> bool:
        """The explicit zero3 program needs the model's pipeline
        decomposition (scanned stack) and an evenly sharded batch."""
        try:
            spec = task.get_model()
            ds = task.get_dataset()
        except Exception:
            return False
        if "pipeline" not in spec.hints or self._aux_incompatible(spec):
            return False
        return ds.batch_size % n_devices == 0

    def make_step_fns(self, spec, task, config, mesh, ds):
        if not config.get("overlap"):
            return super().make_step_fns(spec, task, config, mesh, ds)
        self._require_no_aux(spec)  # shard_map loss path would drop aux loss
        hints = pipeline_hints(spec)
        bkey = spec.hints.get("block_param_key", "blocks")

        def loss_and_grads(params, batch):
            return zero3_loss_and_grads(
                params, batch,
                mesh=mesh,
                embed_fn=hints["embed"],
                block_fn=hints["block"],
                head_fn=hints["head"],
                loss_fn=task.loss_fn,
                block_key=bkey,
                shard_axis="data",
                batch_axes=("data",),
                prefetch=True,
                remat=bool(config.get("remat", False)),
            )

        return self.step_fns_from_loss_and_grads(
            spec.init_fn, task, loss_and_grads
        )
