"""Expert-parallel executor: shard the expert axis over the ICI mesh.

Capability extension beyond the reference (SURVEY.md §2.3: "EP (expert) ...
absent"), delivered exactly the way the reference delivers every parallelism
— as a technique class behind the plugin interface (``Technique.py:24``).

Mesh is 2-D ``(data, expert)``. The MoE weight tables carry an explicit
expert axis ((layers, experts, ...) after the layer scan — ``models/gpt2.py``
``_moe_mlp``), which is sharded over ``expert``; dense trunk params follow
ZeRO-style sharding over ``data``. With the (experts, capacity, d_model)
dispatch intermediate sharded on its expert dim, XLA lowers the
dispatch/combine einsums of ``ops/moe.py`` to all-to-alls over ICI — the
GSPMD equivalent of hand-written MoE a2a kernels.

The train step adds the model's sown load-balance aux loss via
``ModelSpec.apply_with_aux_fn``.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

from jax.sharding import PartitionSpec as P

from saturn_tpu.parallel import sharding as shr
from saturn_tpu.parallel.spmd_base import SPMDTechnique
from saturn_tpu.core.strategy import Techniques

_EXPERT_PARAM = re.compile(r"(^|/)(we_in|we_out|be_in|be_out)$")


def expert_rules(axis: str, n_experts: int):
    """Shard the expert dim of MoE tables; router stays replicated.

    The expert dim is positional, not size-matched: dim 1 under the layer
    scan ((n_layers, E, ...), ``models/gpt2.py`` ``_moe_mlp``), dim 0 for an
    unscanned table. Size-matching would shard the scan dim whenever
    n_layers == n_experts.
    """

    def rules(path: str, shape: Tuple[int, ...], mesh_axes) -> P:
        n_shard = mesh_axes[axis]
        spec = [None] * len(shape)
        if _EXPERT_PARAM.search(path):
            dim = 1 if len(shape) >= 2 and shape[1] == n_experts else 0
            if shape[dim] == n_experts and n_experts % n_shard == 0:
                spec[dim] = axis
        return P(*spec)

    return rules


class ExpertParallel(SPMDTechnique):
    name = "ep"
    technique = Techniques.EXPERT

    def mesh_spec(self, n_devices, task, config) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
        ep = config.get("ep", min(n_devices, 2))
        if n_devices % ep != 0:
            raise ValueError(f"{n_devices} devices not divisible by ep={ep}")
        return ("data", "expert"), (n_devices // ep, ep)

    def _n_experts(self, task) -> int:
        moe = task.get_model().hints.get("moe")
        return moe["n_experts"] if moe else 0

    def param_rules(self, task, config):
        rules = [expert_rules("expert", self._n_experts(task))]
        if config.get("zero"):
            rules.append(shr.fsdp_rules("data"))
        return shr.compose_rules(*rules)

    def candidate_configs(self, task, n_devices) -> List[Dict[str, Any]]:
        E = self._n_experts(task)
        if not E:
            return []  # dense model: EP infeasible, search returns (None, None)
        # No custom train step: the aux load-balance loss is added by the
        # shared scaffold (step_fns_from_forward prefers apply_with_aux_fn),
        # so EP's objective matches dp/fsdp/tp exactly.
        grid: List[Dict[str, Any]] = []
        ep = 2
        while ep <= n_devices and E % ep == 0:
            if n_devices % ep == 0:
                grid.append({"ep": ep, "remat": False, "zero": False})
                grid.append({"ep": ep, "remat": True, "zero": True})
            ep <<= 1
        return grid
