"""Sequence-parallel executor: Ulysses all-to-all over a ('data', 'seq') mesh.

Sibling of :class:`RingSequenceParallel` — same mesh, same boundary-label
loss, same plugin contract — but attention reshards with two all-to-alls to
head-sharded full-sequence form (``ops/ulysses.py``) instead of rotating k/v
around the ring. Requires ``n_heads % sp == 0``. The trial runner profiles
both and the MILP picks whichever is faster for each task's shape.
"""

from __future__ import annotations

from typing import Any, Dict, List

from saturn_tpu.parallel.ring import RingSequenceParallel
from saturn_tpu.core.strategy import Techniques


class UlyssesSequenceParallel(RingSequenceParallel):
    name = "ulysses"
    technique = Techniques.ULYSSES

    def candidate_configs(self, task, n_devices) -> List[Dict[str, Any]]:
        grid = super().candidate_configs(task, n_devices)
        spec = task.get_model()
        n_heads = getattr(spec.config, "n_heads", 1)
        return [c for c in grid if n_heads % c["sp"] == 0]

    def _model_overrides(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = super()._model_overrides(config)
        out["seq_mode"] = "ulysses"
        return out
