"""HostOffload executor: params/opt-state spilled to host memory, streamed in.

Replaces the reference's fairscale-OffloadModel UDP ("Spilled",
``examples/wikitext103/executors/Spilled.py:23-152``): layers lived in CPU RAM
and were streamed through the GPU one slice at a time with activation
checkpointing forced on (``Spilled.py:47,124-125``). The TPU-native analog
(SURVEY.md §2.2) keeps the persistent train state in **pinned host memory**
(``memory_kind='pinned_host'``) and streams it over PCIe into HBM inside the
jitted step:

- ``stream=True``: the scanned layer stack is fetched **one layer per scan
  iteration** (``jax.device_put(..., Space.Device)`` inside ``lax.scan``), with
  ``jax.checkpoint`` around the body so the backward pass re-fetches and
  recomputes — exactly OffloadModel's slice streaming + forced activation
  checkpointing, but expressed to XLA so transfers overlap compute.
- ``stream=False``: the whole param tree is staged to device once per step
  (cheaper when HBM fits params but not params+opt-state).
- ``zero=True`` (multi-device): the host-resident copy itself is sharded over
  the ``data`` axis — host-RAM ZeRO on top of offload.

Where the reference probed OOM with try/except + ``torch.cuda.empty_cache()``
(``Spilled.py:68-87``), feasibility here is decided by XLA's compile-time
memory analysis (``SPMDTechnique._fits_memory``). The reference's
``num_slices`` autotune over layer-count divisors (``Spilled.py:91-96``)
collapses to the stream/bulk choice: scan-streaming fetches at the finest
(per-layer) granularity and lets XLA pipeline the transfers, so intermediate
slice counts have no advantage.

Real pinned-host placement is TPU-only (see
``fsdp.host_offload_supported``); on CPU test meshes the same code paths run
with default memory, so the streaming math stays covered everywhere.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
from jax.sharding import PartitionSpec as P

from saturn_tpu.ops.pipeline import pipeline_hints
from saturn_tpu.parallel import sharding as shr
from saturn_tpu.parallel.fsdp import host_offload_supported
from saturn_tpu.parallel.spmd_base import SPMDTechnique
from saturn_tpu.core.strategy import Techniques


def _device_space():
    """The device-memory destination for ``jax.device_put``, if any.

    ``jax.memory.Space`` came and went across 0.4.x; on versions without it
    the memory-kind transfer spells ``TransferToMemoryKind("device")``. When
    neither exists, return None — callers skip the transfer, which is exactly
    right wherever ``host_offload_supported()`` is also False (the tree
    already lives in device memory).
    """
    mem = getattr(jax, "memory", None)
    if mem is not None:
        return mem.Space.Device
    try:
        from jax.sharding import TransferToMemoryKind
    except ImportError:
        try:
            from jax._src.sharding_impls import TransferToMemoryKind
        except ImportError:
            return None
    return TransferToMemoryKind("device")


_DEVICE_SPACE = _device_space()
_REAL_OFFLOAD: Optional[bool] = None


def _to_device(tree):
    # Identity wherever real host offload is off (CPU meshes, missing memory
    # -space API): the tree already lives in device memory, and the CPU SPMD
    # partitioner rejects the placement annotation outright (RET_CHECK
    # "Side-effect HLO must have sharding").
    global _REAL_OFFLOAD
    if _REAL_OFFLOAD is None:
        _REAL_OFFLOAD = _DEVICE_SPACE is not None and host_offload_supported()
    if not _REAL_OFFLOAD:
        return tree
    return jax.device_put(tree, _DEVICE_SPACE)


class HostOffload(SPMDTechnique):
    name = "offload"
    technique = Techniques.OFFLOAD

    def mesh_spec(self, n_devices, task, config) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
        return ("data",), (n_devices,)

    def batch_spec(self, config) -> P:
        return P("data")

    def param_rules(self, task, config):
        # Params replicated across the data axis (the reference's Spilled was
        # single-device, ``Spilled.py:27-28``; we generalize to data-parallel
        # replicas, each streaming its own copy). 'zero' shards the host
        # copy itself over data — host-RAM ZeRO.
        if config.get("zero"):
            return shr.fsdp_rules(axis="data")
        return shr.replicated_rules

    def param_memory_kind(self, config) -> Optional[str]:
        return "pinned_host" if host_offload_supported() else None

    def candidate_configs(self, task, n_devices) -> List[Dict[str, Any]]:
        spec = task.get_model()
        grid: List[Dict[str, Any]] = []
        # Streaming replaces the forward pass, which would drop an aux loss;
        # non-streaming configs below use spec.apply_fn and keep it.
        if "pipeline" in spec.hints and not self._aux_incompatible(spec):
            # finest streaming first: lowest peak HBM, the configuration the
            # technique exists for (reference tried num_slices ascending,
            # ``Spilled.py:91-96``)
            grid.append({"stream": True, "remat": True})
            if n_devices >= 2:
                grid.append({"stream": True, "remat": True, "zero": True})
        grid.append({"stream": False, "remat": True})
        grid.append({"stream": False, "remat": False})
        return grid

    def _model_overrides(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = super()._model_overrides(config)
        if config.get("stream"):
            # streaming does its own jax.checkpoint around the scan body;
            # the model itself must not double-remat.
            out["remat"] = False
        return out

    def make_step_fns(self, spec, task, config, mesh, ds):
        host = self.param_memory_kind(config) == "pinned_host"
        if not config.get("stream"):
            # Bulk mode: the generic pinned-host handling in the base class
            # is exactly this mode — stage the whole tree to device for the
            # forward (one host->HBM prefetch), run the optimizer update as
            # host computation so params+moments never sit in HBM together.
            return super().make_step_fns(spec, task, config, mesh, ds)

        # Streaming mode: per-layer fetch inside a scan over the stacked
        # block params (requires the model's pipeline decomposition hints).
        self._require_no_aux(spec)  # streaming forward would drop an aux loss
        hints = pipeline_hints(spec)
        bkey = spec.hints.get("block_param_key", "blocks")
        embed_fn, block_fn, head_fn = hints["embed"], hints["block"], hints["head"]

        def forward(params, tokens):
            other = {k: v for k, v in params.items() if k != bkey}
            other_dev = _to_device(other)
            x = embed_fn(other_dev, tokens)

            def body(carry, layer_params):
                layer_dev = _to_device(layer_params)
                return block_fn(layer_dev, carry), None

            if config.get("remat", True):
                body = jax.checkpoint(body, prevent_cse=False)
            x, _ = jax.lax.scan(body, x, params[bkey])
            return head_fn(other_dev, x)

        return self.step_fns_from_forward(
            spec, task, forward, update_on_host=host
        )
