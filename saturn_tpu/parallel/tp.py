"""Tensor-parallel executor: Megatron-style sharding over a 2-D (data, model) mesh.

Realizes the reference's declared-but-never-implemented ``MEGATRON`` technique
(``Strategy.py:34``, SURVEY.md §2.3). Column-parallel qkv/mlp-in, row-parallel
attn-out/mlp-out, vocab-sharded embedding; XLA inserts the activation psums
that Megatron's f/g conjugate operators do by hand. The autotune knob is the
(data × model) mesh factorization plus remat, searched best-guess-first.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from jax.sharding import PartitionSpec as P

from saturn_tpu.ops.collective_matmul import (
    zero3_block_rules,
    zero3_loss_and_grads,
)
from saturn_tpu.ops.pipeline import pipeline_hints
from saturn_tpu.parallel import sharding as shr
from saturn_tpu.parallel.spmd_base import SPMDTechnique
from saturn_tpu.core.strategy import Techniques


class TensorParallel(SPMDTechnique):
    name = "tp"
    technique = Techniques.TENSOR
    # wte is vocab-sharded over 'model' (megatron embedding): the fused CE
    # kernel can't consume a vocab shard — keep the GSPMD logits path, which
    # partitions the head matmul + softmax along vocab natively.
    fused_loss_ok = False

    def mesh_spec(self, n_devices, task, config) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
        tp = config.get("tp", min(n_devices, 2))
        return ("data", "model"), (n_devices // tp, tp)

    def param_rules(self, task, config):
        if config.get("overlap"):
            # Weight-gathered lowering: must match the zero3 program's
            # in_specs leaf-for-leaf (blocks sharded over 'model', rest
            # replicated) or the outer jit reshards every step.
            spec = task.get_model()
            return zero3_block_rules(
                block_key=spec.hints.get("block_param_key", "blocks"),
                axis="model",
            )
        # TP rules first; FSDP-over-data fills remaining axes when the grid
        # asks for it (2-D sharding: params split over both model and data).
        if config.get("zero"):
            return shr.compose_rules(
                shr.tensor_parallel_rules("model"), shr.fsdp_rules("data")
            )
        return shr.tensor_parallel_rules("model")

    def batch_spec(self, config) -> P:
        if config.get("overlap"):
            # The weight-gathered lowering replicates compute over 'model'
            # unless the batch shards over it too.
            return P(("data", "model"))
        return P("data")

    def candidate_configs(self, task, n_devices) -> List[Dict[str, Any]]:
        spec = task.get_model()
        n_heads = getattr(spec.config, "n_heads", 1)
        overlap_ok = self._overlap_ok(task, n_devices)
        grid: List[Dict[str, Any]] = []
        tp = 2
        while tp <= n_devices and n_heads % tp == 0:
            grid.append({"tp": tp, "remat": False, "zero": False})
            grid.append({"tp": tp, "remat": True, "zero": True})
            if overlap_ok:
                # Collective-matmul lowering of the same (data, model) mesh
                # (ops/collective_matmul.py): block weights stay sharded
                # over 'model' (memory parity with zero), but instead of
                # GSPMD's activation psums the program gathers each layer's
                # weight shards chunk-by-chunk, layer-ahead, under the
                # previous layer's compute. Profiled as its own grid point.
                grid.append(
                    {"tp": tp, "remat": False, "zero": True, "overlap": True}
                )
                grid.append(
                    {"tp": tp, "remat": True, "zero": True, "overlap": True}
                )
            tp <<= 1
        return self._with_attention_variants(task, grid)

    def _overlap_ok(self, task, n_devices: int) -> bool:
        """The zero3 program needs the model's pipeline decomposition and a
        batch that shards over the full (data, model) mesh."""
        try:
            spec = task.get_model()
            ds = task.get_dataset()
        except Exception:
            return False
        if "pipeline" not in spec.hints or self._aux_incompatible(spec):
            return False
        return ds.batch_size % n_devices == 0

    def make_step_fns(self, spec, task, config, mesh, ds):
        if not config.get("overlap"):
            return super().make_step_fns(spec, task, config, mesh, ds)
        self._require_no_aux(spec)  # shard_map loss path would drop aux loss
        hints = pipeline_hints(spec)
        bkey = spec.hints.get("block_param_key", "blocks")

        def loss_and_grads(params, batch):
            return zero3_loss_and_grads(
                params, batch,
                mesh=mesh,
                embed_fn=hints["embed"],
                block_fn=hints["block"],
                head_fn=hints["head"],
                loss_fn=task.loss_fn,
                block_key=bkey,
                shard_axis="model",
                batch_axes=("data", "model"),
                prefetch=True,
                remat=bool(config.get("remat", False)),
            )

        return self.step_fns_from_loss_and_grads(
            spec.init_fn, task, loss_and_grads
        )
