"""Tensor-parallel executor: Megatron-style sharding over a 2-D (data, model) mesh.

Realizes the reference's declared-but-never-implemented ``MEGATRON`` technique
(``Strategy.py:34``, SURVEY.md §2.3). Column-parallel qkv/mlp-in, row-parallel
attn-out/mlp-out, vocab-sharded embedding; XLA inserts the activation psums
that Megatron's f/g conjugate operators do by hand. The autotune knob is the
(data × model) mesh factorization plus remat, searched best-guess-first.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from saturn_tpu.parallel import sharding as shr
from saturn_tpu.parallel.spmd_base import SPMDTechnique
from saturn_tpu.core.strategy import Techniques


class TensorParallel(SPMDTechnique):
    name = "tp"
    technique = Techniques.TENSOR
    # wte is vocab-sharded over 'model' (megatron embedding): the fused CE
    # kernel can't consume a vocab shard — keep the GSPMD logits path, which
    # partitions the head matmul + softmax along vocab natively.
    fused_loss_ok = False

    def mesh_spec(self, n_devices, task, config) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
        tp = config.get("tp", min(n_devices, 2))
        return ("data", "model"), (n_devices // tp, tp)

    def param_rules(self, task, config):
        # TP rules first; FSDP-over-data fills remaining axes when the grid
        # asks for it (2-D sharding: params split over both model and data).
        if config.get("zero"):
            return shr.compose_rules(
                shr.tensor_parallel_rules("model"), shr.fsdp_rules("data")
            )
        return shr.tensor_parallel_rules("model")

    def candidate_configs(self, task, n_devices) -> List[Dict[str, Any]]:
        spec = task.get_model()
        n_heads = getattr(spec.config, "n_heads", 1)
        grid: List[Dict[str, Any]] = []
        tp = 2
        while tp <= n_devices and n_heads % tp == 0:
            grid.append({"tp": tp, "remat": False, "zero": False})
            grid.append({"tp": tp, "remat": True, "zero": True})
            tp <<= 1
        return self._with_attention_variants(task, grid)
