"""Sequence-parallel executor: ring attention over a ('data', 'seq') mesh.

Long-context capability the reference does not have (SURVEY.md §5) — its only
length levers were activation checkpointing and offload. Delivered as a
library technique through the same two-method plugin contract
(``Technique.py:24``), so the trial runner profiles it and the MILP can pick
it per task like any other technique.

Each device holds a (B/dp, T/sp) token chunk; attention rotates k/v blocks
around the ``seq`` ring (``ops/ring.py``), so the T×T score matrix never
materializes on one chip — activation memory scales 1/sp², enabling context
lengths that are infeasible for every dense technique. The autotune knob is
the (data × seq) mesh factorization plus remat.

Assumes the next-token CE objective (the label for a chunk boundary comes
from the neighbor shard): the technique declares itself infeasible for tasks
with any other loss, which the trial runner handles like every infeasible
(task × technique) pair (``PerformanceEvaluator.py:110``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from jax.sharding import PartitionSpec as P

from saturn_tpu.models.loss import pretraining_loss
from saturn_tpu.ops.ring import ring_loss_and_grads
from saturn_tpu.parallel import sharding as shr
from saturn_tpu.parallel.spmd_base import SPMDTechnique
from saturn_tpu.core.strategy import Techniques


class RingSequenceParallel(SPMDTechnique):
    name = "ring"
    technique = Techniques.RING

    def mesh_spec(self, n_devices, task, config) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
        sp = config.get("sp", 2)  # same default as _model_overrides
        if n_devices % sp != 0:
            raise ValueError(f"{n_devices} devices not divisible by sp={sp}")
        # 'seq' minor: ring neighbors are adjacent devices on the ICI ring.
        return ("data", "seq"), (n_devices // sp, sp)

    def batch_spec(self, config) -> P:
        return P("data", "seq")

    def param_rules(self, task, config):
        return shr.replicated_rules

    def candidate_configs(self, task, n_devices) -> List[Dict[str, Any]]:
        if task.loss_fn is not pretraining_loss:
            return []  # boundary-label exchange assumes next-token CE
        spec = task.get_model()
        if not spec.hints.get("seq_parallel"):
            return []
        if self._aux_incompatible(spec):
            return []  # shard_map loss path would drop the model's aux loss
        ds = task.get_dataset()
        T = ds.context_length  # the dimension actually sharded over 'seq'
        grid: List[Dict[str, Any]] = []
        sp = 2
        while sp <= n_devices and T % sp == 0:
            if ds.batch_size % (n_devices // sp) == 0:
                # overlap = double-buffered k/v hop (ops/ring.py): profiled
                # as its own grid point so realized cost, not faith, picks.
                grid.append({"sp": sp, "remat": False})
                grid.append({"sp": sp, "remat": False, "overlap": True})
                grid.append({"sp": sp, "remat": True})
                grid.append({"sp": sp, "remat": True, "overlap": True})
            sp <<= 1
        return grid

    def _model_overrides(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = super()._model_overrides(config)
        out["seq_axis"] = "seq"
        out["seq_axis_size"] = config.get("sp", 2)
        out["seq_overlap"] = bool(config.get("overlap", False))
        return out

    def make_step_fns(self, spec, task, config, mesh, ds):
        self._require_no_aux(spec)  # shard_map loss path would drop an aux loss
        # init runs OUTSIDE shard_map: use a dense-attention twin (identical
        # param tree — seq parallelism adds no params) for shape/init.
        plain = dict(self._model_overrides(config))
        plain["seq_axis"] = None
        plain["seq_axis_size"] = 1
        spec_plain = task.get_model(**plain)

        def loss_and_grads(params, batch):
            return ring_loss_and_grads(
                params, batch, mesh=mesh, apply_fn=spec.apply_fn
            )

        return self.step_fns_from_loss_and_grads(
            spec_plain.init_fn, task, loss_and_grads
        )
