"""Fused multi-model stacking: N sweep members as ONE compiled SPMD program.

Saturn's headline workload is batches of jobs sharing an architecture and
differing only in hyperparameters (HPO sweeps, model selection). Co-scheduling
(round 6) and bubble-filling (round 15) still pay one Python dispatch, one
data pipeline and one compiled program *per job*. Fusion stacks the members'
params/opt-state along a leading ``model`` axis and vmaps the train step over
it, so N jobs pay those costs once — per-member hyperparameters (LR today;
the vector generalizes) ride along as stacked ``(N,)`` arrays, keeping every
member's trajectory distinct AND bit-identical to its solo run (the
trajectory-equivalence suite in ``tests/test_fused.py`` proves it, the same
way ``tests/test_coschedule.py`` proves interleaving safety).

Layout: the ``model`` axis is vmapped on-device and, when the group runs on a
multi-chip block, sharded across the block via a leading ``PartitionSpec``
prefix (``P("model")`` on every stacked leaf, the batch stack and the hparam
vector) — GSPMD lays it out like any other mesh axis, so each chip advances
``N / n_devices`` members with zero cross-member collectives.

Lifecycle (docs/architecture.md round 21): ``fusion_candidates`` proposes
fusable sets (same :func:`fusion_fingerprint`), the trial runner profiles the
stacked program like any other grid point (``Strategy.fused_per_batch_time``),
the MILP picks fused vs co-scheduled vs solo on measured cost
(``solver/milp.py``), and the engine's fused launcher drives
:func:`run_fused_interval`. The **unfuse path** slices a diverged member's
leaves out of the stack mid-interval (guardian detach, early stop, or a
sentinel fault on its per-member loss column), checkpoints the slice through
the sharded manifest, journals the transition, and hands the member back to
the engine as a solo job — no lost or duplicated steps.
"""

from __future__ import annotations

import hashlib
import json
import logging
import timeit as _timeit
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from saturn_tpu.analysis import concurrency as tsan
from saturn_tpu.core.mesh import make_submesh
from saturn_tpu.ops import stacking
from saturn_tpu.parallel.spmd_base import choose_window
from saturn_tpu.utils import checkpoint as ckpt

log = logging.getLogger("saturn_tpu")

#: Version of the fusion machinery baked into the profile-cache fingerprint
#: and the AOT-cache runtime identity (the ``SCHEDULE_SET_VERSION`` pattern,
#: round 15): bump when the stacked program's semantics change, so stale
#: per-job profiles re-trial instead of silently warm-starting a different
#: dispatch mode.
FUSION_SET_VERSION = 1


def fusion_signature() -> str:
    """Content signature of the fusion machinery for cache identities."""
    return f"fused-stack-v{FUSION_SET_VERSION}"


# ----------------------------------------------------------- fingerprinting
def fusion_fingerprint(task: Any) -> Optional[str]:
    """Compatibility key: two tasks may share a stack iff fingerprints match.

    Captures everything the stacked program's shape depends on — model config,
    abstract param tree, batch shape/dtype, optimizer family, loss objective —
    and *excludes* everything that rides along as a stacked hparam (LR).
    ``None`` means the task cannot fuse at all (callable optimizer, model
    factory failure): callers must treat ``None`` as matching nothing.
    """
    cached = getattr(task, "_fusion_fingerprint", False)
    if cached is not False:
        return cached
    fp = _fingerprint_uncached(task)
    task._fusion_fingerprint = fp
    return fp


def _fingerprint_uncached(task: Any) -> Optional[str]:
    opt = task.hparams.optimizer
    if not isinstance(opt, str):
        return None  # a callable optimizer factory has no comparable identity
    try:
        spec = task.get_model()
        ds = task.get_dataset()
        eb = ds.example_batch()
        shapes = jax.eval_shape(lambda: spec.init_fn(jax.random.PRNGKey(0)))
    except Exception as e:
        log.debug("fusion_fingerprint(%s) failed: %r", getattr(task, "name", "?"), e)
        return None
    cfg = getattr(spec, "config", None)
    try:
        cfg_sig = sorted(
            (k, repr(v)) for k, v in vars(cfg).items()
        ) if cfg is not None and hasattr(cfg, "__dict__") else repr(cfg)
    except TypeError:
        cfg_sig = repr(cfg)
    param_sig = [
        (jax.tree_util.keystr(p), tuple(l.shape), str(l.dtype))
        for p, l in jax.tree_util.tree_flatten_with_path(shapes)[0]
    ]
    loss_tag = getattr(task.loss_fn, "supports_fused_head", None) or getattr(
        task.loss_fn, "__name__", repr(task.loss_fn)
    )
    payload = json.dumps(
        {
            "fusion": fusion_signature(),
            "config": cfg_sig,
            "params": param_sig,
            "batch": [tuple(np.shape(eb)), str(np.asarray(eb).dtype)],
            "optimizer": opt,
            "loss": loss_tag,
        },
        sort_keys=True, default=repr,
    )
    return hashlib.sha1(payload.encode()).hexdigest()


def fusion_candidates(
    task_list: Sequence[Any], min_members: int = 2, max_members: int = 8
) -> List[List[str]]:
    """Fusable sets among ``task_list``: groups of task *names* whose members
    share a :func:`fusion_fingerprint` (same ModelSpec shape, batch/seq,
    optimizer family, loss). The ``coschedule_candidates`` analog for
    stacking — the solver prices each proposed set against its co-scheduled
    and solo alternatives (``solver/milp.py``). Oversized cohorts split into
    chunks of ``max_members``.
    """
    by_fp: Dict[str, List[str]] = {}
    for t in task_list:
        fp = fusion_fingerprint(t)
        if fp is None:
            continue
        # The stacked program runs every member as a whole-model replica on
        # its model-axis shard — one chip, full batch, no data-axis psum. A
        # task whose allowed solo widths include >1 chip would see different
        # floating-point arithmetic (split batch + cross-chip grad reduce)
        # depending on whether the scheduler happened to fuse it, breaking
        # trajectory bit-identity under rescheduling (tests/test_chaos.py
        # compares faulted campaigns against an uninterrupted reference).
        # Only single-chip tasks are arithmetic-neutral to fuse.
        widths = getattr(t, "chip_range", None) or []
        if any(int(c) != 1 for c in widths):
            continue
        by_fp.setdefault(fp, []).append(t.name)
    groups: List[List[str]] = []
    for names in by_fp.values():
        for i in range(0, len(names), max(int(max_members), 2)):
            chunk = names[i : i + max(int(max_members), 2)]
            if len(chunk) >= max(int(min_members), 2):
                groups.append(chunk)
    return groups


# ----------------------------------------------------------- stacked program
def _make_tx(opt_name: str) -> Callable[[Any], Any]:
    """lr -> optax transformation, traceable: constructed INSIDE the vmapped
    step so each member's update closes over its own (traced) LR. Bitwise
    equal to the solo program's concrete-float construction — adamw/adam/sgd
    scale by lr as a plain multiply, so a traced scalar lowers to the same
    HLO the constant did (verified by the trajectory-equivalence tests)."""
    if opt_name == "adamw":
        return optax.adamw
    if opt_name == "adam":
        return optax.adam
    return optax.sgd


def _member_step_fns(
    spec: Any, loss_fn: Any, opt_name: str, fused_loss_ok: bool = True
) -> Tuple[Callable, Callable]:
    """(member_init(lr) -> state, member_step(state, batch, lr) -> (state,
    loss)) for ONE member — the exact solo scaffold
    (``SPMDTechnique.step_fns_from_loss_and_grads``) with the LR lifted from
    a closure constant to a traced argument.

    The loss path mirrors ``step_fns_from_forward``'s single-device decision:
    the member program inside the vmap is a whole-model replica (the model
    axis is the only sharded one), so the fused head+loss (ops/ce.py)
    engages exactly when the member's solo single-device program would use
    it — which is what keeps a fused member's loss trajectory bit-identical
    to its solo run.
    """
    fused = getattr(spec, "fused_loss_fn", None)
    tag = getattr(loss_fn, "supports_fused_head", None)
    use_fused_ce = (
        fused is not None
        and fused_loss_ok
        and spec.apply_with_aux_fn is None
        and tag is not None
        and tag == getattr(spec, "fused_loss_objective", None)
    )
    if use_fused_ce:
        def loss_of(params, batch):
            return fused(params, batch)
    elif spec.apply_with_aux_fn is not None:
        def loss_of(params, batch):
            logits, aux = spec.apply_with_aux_fn(params, batch)
            return loss_fn(logits, batch) + aux
    else:
        def loss_of(params, batch):
            return loss_fn(spec.apply_fn(params, batch), batch)

    tx_of = _make_tx(opt_name)

    def member_init(lr):
        params = spec.init_fn(jax.random.PRNGKey(0))
        return {
            "params": params,
            "opt_state": tx_of(lr).init(params),
            "step": jnp.zeros((), dtype=jnp.int32),
        }

    def member_step(state, batch, lr):
        tx = tx_of(lr)
        loss, grads = jax.value_and_grad(loss_of)(state["params"], batch)
        updates, new_opt = tx.update(grads, state["opt_state"], state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return {
            "params": new_params,
            "opt_state": new_opt,
            "step": state["step"] + 1,
        }, loss

    return member_init, member_step


@dataclass
class FusedProgram:
    """Compiled artifacts for one (fingerprint, config, N, block) stack."""

    n_members: int
    mesh: Any
    member_shapes: Any            # solo-shaped ShapeDtypeStruct tree
    stacked_shapes: Any           # (N, ...) ShapeDtypeStruct tree
    state_shardings: Any          # P("model") prefix on every stacked leaf
    batch_sharding: Any           # (N, B, T) stack
    lr_sharding: Any              # (N,) hparam vector
    member_batch_shape: Tuple[int, ...]
    batch_dtype: Any
    member_init: Any              # lr -> solo-shaped state (python fn)
    _stacked_step: Any            # raw (state, batch, lrs) -> (state, loss)
    _single: Any = None
    _windows: Dict[int, Any] = field(default_factory=dict)

    def _devices(self) -> List[Any]:
        return list(self.mesh.devices.flat)

    def _lr_sds(self):
        return jax.ShapeDtypeStruct((self.n_members,), jnp.float32)

    def single_compiled(self):
        """AOT-compiled one-step stacked program: (state, (N,B,T), (N,)) ->
        (state, (N,) per-member losses). State donated; lrs are not."""
        with _CACHE_LOCK:
            hit = self._single
        if hit is not None:
            return hit
        from saturn_tpu.utils import aot_cache

        batch_sds = jax.ShapeDtypeStruct(
            (self.n_members, *self.member_batch_shape), self.batch_dtype
        )
        jitted = jax.jit(
            self._stacked_step,
            in_shardings=(self.state_shardings, self.batch_sharding,
                          self.lr_sharding),
            out_shardings=(self.state_shardings,
                           NamedSharding(self.mesh, P())),
            donate_argnums=(0,),
        )
        compiled = aot_cache.load_or_compile(
            jitted.lower(self.stacked_shapes, batch_sds, self._lr_sds()),
            self._devices(),
        )
        with _CACHE_LOCK:
            if self._single is None:
                self._single = compiled
            return self._single

    def window_compiled(self, k: int):
        """AOT-compiled fused K-window: ``lax.scan`` of the stacked step over
        a (K, N, B, T) staging stack — one dispatch and one (K, N) loss
        readback amortize over K lockstep batches for all N members. State
        AND the window stack are donated (fresh stack per call)."""
        k = int(k)
        with _CACHE_LOCK:
            hit = self._windows.get(k)
        if hit is not None:
            return hit
        from saturn_tpu.utils import aot_cache

        step = self._stacked_step

        def window_step(state, window, lrs):
            def body(s, b):
                return step(s, b, lrs)

            return jax.lax.scan(body, state, window)

        window_sharding = NamedSharding(
            self.mesh, P(None, *tuple(self.batch_sharding.spec))
        )
        window_sds = jax.ShapeDtypeStruct(
            (k, self.n_members, *self.member_batch_shape), self.batch_dtype
        )
        jitted = jax.jit(
            window_step,
            in_shardings=(self.state_shardings, window_sharding,
                          self.lr_sharding),
            out_shardings=(self.state_shardings,
                           NamedSharding(self.mesh, P())),
            donate_argnums=(0, 1),
        )
        compiled = aot_cache.load_or_compile(
            jitted.lower(self.stacked_shapes, window_sds, self._lr_sds()),
            self._devices(),
        )
        with _CACHE_LOCK:
            return self._windows.setdefault(k, compiled)

    def window_sharding(self):
        return NamedSharding(
            self.mesh, P(None, *tuple(self.batch_sharding.spec))
        )

    def init_member_host(self, lr: float) -> Any:
        """One member's freshly-initialized state as host numpy — identical
        values to the solo program's ``bundle.init()`` (same PRNGKey(0)
        init), so a fused-from-scratch member matches its solo twin from
        step 0."""
        dev = jax.jit(self.member_init)(jnp.float32(lr))
        return jax.tree_util.tree_map(np.asarray, dev)


#: Compiled-program cache: (fingerprint, config, N, block) -> FusedProgram.
#: Keyed on the GROUP's shape identity, not member names — an unfuse from
#: N to N-1 members reuses any previously compiled (N-1)-stack of the same
#: fingerprint, and re-fusing next interval hits the cache outright.
_PROGRAMS: Dict[Any, FusedProgram] = {}
_CACHE_LOCK = tsan.lock("fused.programs")


def usable_devices(devices: Sequence[Any], n_members: int) -> List[Any]:
    """Largest prefix of ``devices`` the model axis can span: N must divide
    the axis size so every chip carries the same member count. Walks the
    block size down by powers of two; worst case a single device carries the
    whole (vmapped, unsharded) stack."""
    n_dev = max(len(devices), 1)
    while n_dev > 1 and int(n_members) % n_dev != 0:
        n_dev //= 2
    return list(devices[:n_dev])


def build_fused_program(
    members: Sequence[Any],
    devices: Sequence[Any],
    inner: Optional[Any] = None,
    config: Optional[Dict[str, Any]] = None,
) -> FusedProgram:
    """Build (or fetch from cache) the stacked program for ``members``.

    ``inner`` is the wrapped SPMD technique (defaults to member 0's selected
    strategy executor); its ``fused_loss_ok`` and model-override policy apply
    to the member program exactly as they would solo. All members must share
    a :func:`fusion_fingerprint` — enforced here, because a mismatched member
    would otherwise surface as an XLA shape error inside vmap.
    """
    if not members:
        raise ValueError("build_fused_program: empty member list")
    rep = members[0]
    if inner is None and rep.selected_strategy is not None:
        inner = rep.selected_strategy.executor
    if config is None:
        sel = rep.selected_strategy
        config = dict(sel.params or {}) if sel is not None else {}
    fp = fusion_fingerprint(rep)
    if fp is None:
        raise ValueError(
            f"task {rep.name!r} is not fusable (no fusion fingerprint)"
        )
    for m in members[1:]:
        if fusion_fingerprint(m) != fp:
            raise ValueError(
                f"fused member {m.name!r} has a different fusion fingerprint "
                f"than {rep.name!r} — the group is not stack-compatible"
            )
    devs = usable_devices(devices, len(members))
    key = (
        fp,
        tuple(sorted(config.items())),
        len(members),
        tuple(getattr(d, "id", i) for i, d in enumerate(devs)),
    )
    with _CACHE_LOCK:
        hit = _PROGRAMS.get(key)
    if hit is not None:
        return hit
    prog = _build_program_uncached(rep, members, devs, inner, config)
    with _CACHE_LOCK:
        return _PROGRAMS.setdefault(key, prog)


def _build_program_uncached(
    rep: Any, members: Sequence[Any], devs: List[Any],
    inner: Optional[Any], config: Dict[str, Any],
) -> FusedProgram:
    n = len(members)
    overrides = inner._model_overrides(config) if inner is not None else {}
    spec = rep.get_model(**overrides)
    fused_loss_ok = bool(getattr(inner, "fused_loss_ok", True))
    member_init, member_step = _member_step_fns(
        spec, rep.loss_fn, rep.hparams.optimizer, fused_loss_ok
    )
    mesh = make_submesh(devs, ("model",), (len(devs),))
    member_shapes = jax.eval_shape(
        member_init, jax.ShapeDtypeStruct((), jnp.float32)
    )
    stacked_shapes = stacking.stacked_shapes(member_shapes, n)
    state_shardings = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P("model")), stacked_shapes
    )
    ds = rep.get_dataset()
    eb = np.asarray(ds.example_batch())

    def stacked_step(state, batch, lrs):
        return jax.vmap(member_step)(state, batch, lrs)

    return FusedProgram(
        n_members=n,
        mesh=mesh,
        member_shapes=member_shapes,
        stacked_shapes=stacked_shapes,
        state_shardings=state_shardings,
        batch_sharding=NamedSharding(mesh, P("model")),
        lr_sharding=NamedSharding(mesh, P("model")),
        member_batch_shape=tuple(eb.shape),
        batch_dtype=eb.dtype,
        member_init=member_init,
        _stacked_step=stacked_step,
    )


# --------------------------------------------------------- interval execution
@dataclass
class MemberResult:
    """One member's outcome for a fused interval."""

    name: str
    steps: int = 0                      # batches retired IN the stack
    final_loss: Optional[float] = None
    fault: Optional[BaseException] = None   # sentinel fault (state discarded)
    detached_at: Optional[int] = None   # unfuse point (interval-relative)


@dataclass
class FusedIntervalReport:
    """What :func:`run_fused_interval` hands back to the engine's launcher."""

    n_steps: int
    window: int
    members: Dict[str, MemberResult]
    detached: List[Tuple[Any, int]]     # (task, steps retired at unfuse)
    per_step_s: float = 0.0             # steady-state lockstep seconds
    samples_per_sec: float = 0.0        # aggregate across the stack
    elapsed_s: float = 0.0


def _fused_live_key(fp: str, config: Dict[str, Any], devs: Sequence[Any]):
    return (
        "fused", fp, tuple(sorted(config.items())),
        tuple(getattr(d, "id", i) for i, d in enumerate(devs)),
    )


def _resume_member_host(m: Any, prog: FusedProgram, live_key: Any) -> Any:
    """Member state as a host tree: live cache, checkpoint, or fresh init —
    the same resume ladder as ``SPMDTechnique.interval_dispatches``, with the
    data cursor re-derived from the trained-step count on a ckpt restore."""
    live = getattr(m, "_live_state", None)
    if live is not None and live[0] == live_key:
        m._live_state = None
        return live[1]
    m._live_state = None
    if m.has_ckpt():
        state = ckpt.restore(m.ckpt_path, prog.member_shapes)
        m.current_batch = m.cursor_for_step(int(np.asarray(state["step"])))
        return state
    return prog.init_member_host(m.hparams.lr)


def _member_host_slices(state: Any, indices: Sequence[int]) -> List[Any]:
    """Device->host member slices (the per-member checkpoint view)."""
    return [
        jax.tree_util.tree_map(
            np.asarray, stacking.member_slice(state, i)
        )
        for i in indices
    ]


def run_fused_interval(
    members: Sequence[Any],
    devices: Sequence[Any],
    tid: int = 0,
    batch_counts: Optional[Sequence[int]] = None,
    inner: Optional[Any] = None,
    config: Optional[Dict[str, Any]] = None,
    window_size: Optional[int] = None,
    detach_requested: Optional[Callable[[Any], bool]] = None,
) -> FusedIntervalReport:
    """One engine interval for a fused group: lockstep batches for all
    members through one compiled program.

    The lockstep budget is ``min`` over the members' interval budgets — the
    engine re-forecasts the shortfall next interval, exactly as it does for
    any under-retired job. Dispatch shape mirrors the solo path: ``n // K``
    fused windows (scanned (K, N, B, T) stacks) plus an ``n % K`` per-step
    tail, batches staged one unit ahead by the prefetcher.

    ``detach_requested`` is polled at every unit boundary (defaults to the
    member's ``_fused_detach`` flag, which the guardian's detach/quarantine
    path and early stopping set): a detaching member is **unfused** —
    state sliced out of the stack, checkpointed through the sharded
    manifest (crash barrier ``"fused.unfuse"`` fires first, so the chaos
    harness can kill inside the transition), journaled as a
    ``fused_unfuse`` metrics event — and returned in ``report.detached``
    for the engine to resume solo. Survivors continue on a rebuilt
    (cache-hit) N-1 stack.

    Sentinel faults are per member: each member's (n,) loss column is folded
    exactly as its solo interval would fold it; a faulted member's state is
    discarded (no checkpoint, no live-state publish — its last durable
    checkpoint is the rollback target) while healthy members commit.
    """
    if not members:
        raise ValueError("run_fused_interval: empty group")
    detach_requested = detach_requested or (
        lambda t: bool(getattr(t, "_fused_detach", False))
    )
    cur: List[Any] = list(members)
    if inner is None and cur[0].selected_strategy is not None:
        inner = cur[0].selected_strategy.executor
    if config is None:
        sel = cur[0].selected_strategy
        config = dict(sel.params or {}) if sel is not None else {}

    budgets = [
        int(b) for b in (
            batch_counts if batch_counts is not None
            else [m.total_batches for m in cur]
        )
    ]
    n = max(min(budgets), 0) if budgets else 0
    report = FusedIntervalReport(
        n_steps=n, window=1,
        members={m.name: MemberResult(name=m.name) for m in cur},
        detached=[],
    )
    if n <= 0:
        return report

    fp = fusion_fingerprint(cur[0])
    prog = build_fused_program(cur, devices, inner=inner, config=config)
    live_key = _fused_live_key(fp, config, prog._devices())

    host_states = [_resume_member_host(m, prog, live_key) for m in cur]
    starts = {m.name: m.current_batch for m in cur}

    from saturn_tpu.core import distributed as _dist

    state = _dist.put_tree_global(
        stacking.stack_trees(host_states), prog.state_shardings
    )
    del host_states

    # -------- window plan (identical unit algebra to the solo path)
    fused_ok = inner._fused_ok(config) if inner is not None else True
    k = choose_window(n) if window_size is None else int(window_size)
    k = max(1, min(k, n))
    if k > 1 and not fused_ok:
        k = 1
    n_windows = n // k if k > 1 else 0
    units: List[Tuple[bool, int]] = [(True, w * k) for w in range(n_windows)]
    units += [(False, j) for j in range(n_windows * k, n)]
    report.window = k
    first_unit_batches = k if (units and units[0][0]) else 1

    # Per-segment loss buffers: (member names at that segment, device
    # (steps, N_seg) matrices). Membership only changes at unfuse points.
    segments: List[Tuple[List[str], List[Any]]] = []
    seg_losses: List[Any] = []

    def close_segment() -> None:
        if seg_losses:
            segments.append(([m.name for m in cur], list(seg_losses)))
            seg_losses.clear()

    from saturn_tpu.data.prefetch import DevicePrefetcher

    batch_size = int(prog.member_batch_shape[0]) if prog.member_batch_shape else 1
    n_members0 = len(cur)
    names0 = [m.name for m in cur]
    t_all0 = _timeit.default_timer()
    t_steady = t_all0
    steps_done = 0
    u = 0
    while u < len(units):
        # ---- unfuse check at the unit boundary
        leaving = [m for m in cur if detach_requested(m)]
        if leaving and len(cur) - len(leaving) >= 1:
            close_segment()
            for m in leaving:
                idx = cur.index(m)
                member_host = _member_host_slices(state, [idx])[0]
                # Crash barrier FIRST: a kill here leaves nothing durable
                # from this interval, so replay re-runs it bit-identically
                # and unfuses at the same boundary — exactly once.
                ckpt._barrier(
                    "fused.unfuse", task=m.name, step=steps_done, tid=tid
                )
                ckpt.save(m.ckpt_path, member_host)
                from saturn_tpu.utils import metrics as _metrics

                _metrics.event(
                    "fused_unfuse", task=m.name, group=names0,
                    step=steps_done, n_remaining=len(cur) - 1,
                )
                log.info(
                    "fused group: unfused member %s at interval step %d "
                    "(%d member(s) remain)", m.name, steps_done, len(cur) - 1,
                )
                report.members[m.name].steps = steps_done
                report.members[m.name].detached_at = steps_done
                report.detached.append((m, steps_done))
                survivors = [j for j in range(len(cur)) if j != idx]
                host_survivors = _member_host_slices(state, survivors)
                cur.pop(idx)
                prog = build_fused_program(
                    cur, devices, inner=inner, config=config
                )
                state = _dist.put_tree_global(
                    stacking.stack_trees(host_survivors), prog.state_shardings
                )
        elif leaving:
            log.warning(
                "fused group: detach requested for every member — "
                "finishing the interval fused (nothing to unfuse into)"
            )

        # ---- run until the next boundary event (or interval end)
        n_cur = len(cur)
        lrs_dev = _dist.put_global(
            np.asarray([m.hparams.lr for m in cur], dtype=np.float32),
            prog.lr_sharding,
        )
        seg_u0 = u
        member_names = [m.name for m in cur]

        def stage(j: int, _u0=seg_u0, _members=list(cur),
                  _names=list(member_names), _prog=prog):
            fused_u, off = units[_u0 + j]
            if fused_u:
                host = np.stack([
                    stacking.stack_member_batches(
                        [m.batch_at(starts[m.name] + off + i) for m in _members],
                        member_names=_names,
                        expect=_prog.member_batch_shape,
                    )
                    for i in range(k)
                ])
                return _dist.put_global(host, _prog.window_sharding())
            host = stacking.stack_member_batches(
                [m.batch_at(starts[m.name] + off) for m in _members],
                member_names=_names, expect=_prog.member_batch_shape,
            )
            return _dist.put_global(host, _prog.batch_sharding)

        single_fn = (
            prog.single_compiled()
            if any(not f for f, _ in units[seg_u0:]) else None
        )
        fused_fn = (
            prog.window_compiled(k)
            if any(f for f, _ in units[seg_u0:]) else None
        )
        expect = (
            (k, n_cur, *prog.member_batch_shape),
            (n_cur, *prog.member_batch_shape),
        )
        prefetch = DevicePrefetcher(
            len(units) - seg_u0, stage, depth=2,
            expect_shapes=expect, member_names=member_names,
        )
        try:
            while u < len(units):
                if u > seg_u0 and any(detach_requested(m) for m in cur):
                    break  # handle the unfuse at the outer boundary
                try:
                    dev_batch = next(prefetch)
                except StopIteration:
                    break
                if units[u][0]:
                    state, loss = fused_fn(state, dev_batch, lrs_dev)  # (K, N)
                    seg_losses.append(jnp.reshape(loss, (k, n_cur)))
                    steps_done += k
                else:
                    state, loss = single_fn(state, dev_batch, lrs_dev)  # (N,)
                    seg_losses.append(jnp.reshape(loss, (1, n_cur)))
                    steps_done += 1
                if u == seg_u0 == 0 and len(units) > 1:
                    # Warmup fence: keep executable load + first staging out
                    # of the steady-state window (realized feedback).
                    jax.block_until_ready(loss)  # lint: sanctioned-host-sync
                    t_steady = _timeit.default_timer()
                u += 1
        finally:
            # SimulatedKill is a BaseException: never leak a staging thread.
            prefetch.close()

    close_segment()

    # -------- finalization: per-member sentinel folds, checkpoints, timing
    t_end = _timeit.default_timer()
    elapsed_all = t_end - t_all0
    from saturn_tpu.health import sentinel as _sentinel
    from saturn_tpu.utils import metrics as _metrics

    scfg = _sentinel.get_config()
    # Per-member loss columns across segments (a detached member's column
    # ends at its unfuse point — its solo continuation owns the rest).
    columns: Dict[str, List[Any]] = {m.name: [] for m in cur}
    for names, mats in segments:
        for mat in mats:
            for i, nm in enumerate(names):
                if nm in columns:
                    columns[nm].append(mat[:, i])

    final_losses: Dict[str, float] = {}
    faulted: set = set()
    for m in cur:
        col = columns.get(m.name) or []
        poison = m.__dict__.pop("_health_poison", None)
        if not col:
            continue
        vec = jnp.concatenate(col)
        if scfg.enabled:
            if poison is not None:
                # Chaos injection corrupts the OBSERVED member column only
                # (train state untouched), exactly like the solo path
                # (spmd_base interval finalization) — without this, faults
                # scheduled onto a fused member were silently dropped and
                # the chaos campaign never saw a rollback.
                ov = _sentinel.poison_overrides(
                    poison, int(vec.shape[0]),
                    lambda j: m.dataset_index(starts[m.name] + j),
                )
                if ov is not None:
                    vec = vec.at[ov[0]].set(ov[1])
            carry = getattr(m, "_sentinel_carry", None)
            if carry is None:
                carry = _sentinel.carry_init()
            rep = np.asarray(
                _dist.host_array(_sentinel.fold(carry, vec, scfg))
            )
            loss_val = float(rep[_sentinel.REP_LAST_LOSS])
            fault = _sentinel.inspect(rep)
            if fault is not None:
                cause, first_off, bad_count = fault
                bad = tuple(sorted({
                    m.dataset_index(starts[m.name] + int(j)) for j in
                    set(np.flatnonzero(
                        ~np.isfinite(np.asarray(_dist.host_array(vec)))
                    )) | {max(int(first_off), 0)}
                }))
                err = _sentinel.NumericFaultError(
                    m.name, first_off // max(k, 1), cause, step=first_off,
                    loss=loss_val, batch_indices=bad, bad_count=bad_count,
                )
                _metrics.event(
                    "task_numeric_fault", task=m.name, cause=cause,
                    window=first_off // max(k, 1), step=int(first_off),
                    bad_count=int(bad_count), batches=list(bad), fused=True,
                )
                log.warning(
                    "fused member %s: sentinel tripped (%s) at interval "
                    "step %d — discarding the member's interval",
                    m.name, cause, first_off,
                )
                report.members[m.name].fault = err
                faulted.add(m.name)
                continue
            m._sentinel_carry = rep[:2].copy()
        else:
            loss_val = float(
                np.asarray(_dist.host_array(vec)).reshape(-1)[-1]
            )
        final_losses[m.name] = loss_val
        report.members[m.name].final_loss = loss_val
        report.members[m.name].steps = n

    # Per-member checkpoint slices through the sharded manifest; a faulted
    # member's state is NOT persisted (its previous checkpoint is the
    # rollback target, exactly like the solo fault path).
    healthy = [i for i, m in enumerate(cur) if m.name not in faulted]
    slices = _member_host_slices(state, healthy)
    for i, host in zip(healthy, slices):
        m = cur[i]
        ckpt.save_async(m.ckpt_path, host)
        m._live_state = (live_key, host)

    per_step = (
        (t_end - t_steady) / max(n - first_unit_batches, 1)
        if len(units) > 1 else elapsed_all / max(n, 1)
    )
    report.per_step_s = per_step
    report.elapsed_s = elapsed_all
    report.samples_per_sec = (
        n * n_members0 * batch_size / max(elapsed_all, 1e-9)
    )
    _metrics.event(
        "fused_interval", members=names0, n_members=n_members0,
        batches=n, window=k,
        per_step_s=per_step,
        samples_per_sec=round(report.samples_per_sec, 2),
        losses={nm: round(v, 6) for nm, v in final_losses.items()},
        detached=[m.name for m, _ in report.detached],
        faulted=sorted(faulted),
    )
    log.info(
        "fused group %s: ran %d lockstep batches (K=%d, %d members, "
        "%.1f samples/s aggregate)",
        names0, n, k, n_members0, report.samples_per_sec,
    )
    return report
