"""Pipeline-parallel executor: GPipe / 1F1B over a ``stage`` mesh axis.

Replaces the reference's torchgpipe UDP (``examples/wikitext103/executors/
Pipeline.py:24-167``). Reference behavior preserved: partition the layer
stack across workers (``balance_by_time``, ``Pipeline.py:94-103`` → here
:func:`balance_stages`, an exact DP over the model's ``layer_costs`` hint
— profiled or FLOP-derived per-layer costs, uniform when absent), and
autotune the microbatch count (``Pipeline.py:139-159`` halving sweep → grid
over {M} multiples of the stage count). The schedule itself lives in
``saturn_tpu.ops.pipeline`` (shard_map + ppermute); unequal stage spans
(uneven costs, or a layer count the stage count doesn't divide) run via
the padded-span schedule there.

A ``data`` axis composes data parallelism with the pipeline: a mesh of
``n`` devices runs ``n/S`` pipeline replicas of ``S`` stages each.

The schedule is a profiled grid dimension, not a default: candidate configs
carry ``schedule: "gpipe" | "1f1b"`` and the trial runner times both, so the
solver picks per task from realized cost rather than the analytic bubble
formula. ``layout: "stage_major"`` additionally lets the stage axis span
slice boundaries (activation hops over DCN, per-stage data all-reduce on
ICI) when no single slice fits the model.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from jax.sharding import PartitionSpec as P

from saturn_tpu.ops.pipeline import (
    PIPELINE_SCHEDULES,
    balance_stages,
    pipeline_hints,
    pipeline_loss_and_grads,
    schedule_bubble_fraction,
    staged_pipeline_loss_and_grads,
)
from saturn_tpu.parallel.spmd_base import SPMDTechnique
from saturn_tpu.core.strategy import Techniques


def _layer_costs(spec, n_layers: int) -> Optional[list]:
    """Per-layer cost vector from the model hints, or None for uniform.
    Validated here so a stale hint fails search loudly, not mid-step."""
    costs = spec.hints.get("layer_costs")
    if costs is None:
        return None
    costs = list(costs)
    if len(costs) != n_layers or min(costs) <= 0:
        raise ValueError(
            f"layer_costs must be {n_layers} positive entries, got {costs!r}"
        )
    return costs


class Pipeline(SPMDTechnique):
    name = "pp"
    technique = Techniques.PIPELINE

    def mesh_spec(self, n_devices, task, config) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
        s = config.get("stages", 2)
        if n_devices % s != 0:
            raise ValueError(f"{n_devices} devices not divisible by {s} stages")
        if config.get("layout") == "stage_major":
            # Cross-slice stage placement: with slice-major device ordering
            # (``core/mesh.py``) the LEADING mesh axis is the one whose
            # collectives cross DCN once the block outgrows a slice. Putting
            # ``stage`` first sends the per-tick ppermute activation hop over
            # DCN (one activation tensor per tick — the cheap collective)
            # while each stage's data-parallel grad all-reduce stays inside
            # its slice. Shardflow's ``crossing_axes`` prices exactly this.
            return ("stage", "data"), (s, n_devices // s)
        return ("data", "stage"), (n_devices // s, s)

    def batch_spec(self, config) -> P:
        return P("data")

    def param_rules(self, task, config):
        spec = task.get_model()
        bkey = spec.hints.get("block_param_key", "blocks")
        s = config.get("stages", 2)

        def rules(path: str, shape: Tuple[int, ...], mesh_axes) -> P:
            # At-rest layout: NamedSharding requires the sharded dim to
            # divide by the axis size, so a stack the stage count doesn't
            # divide stays replicated at rest (param memory = dp's; the
            # padded-span repack inside the step still distributes compute).
            # Cost-uneven stacks whose length DOES divide keep the sharded
            # rest layout — the repack moves only boundary-crossing layers.
            if bkey in path and shape and shape[0] % s == 0:
                return P("stage")
            return P()

        return rules

    def candidate_configs(self, task, n_devices) -> List[Dict[str, Any]]:
        spec = task.get_model()
        n_layers = getattr(spec.config, "n_layers", 1)
        if "pipeline" not in spec.hints:
            return []
        if self._aux_incompatible(spec):
            return []  # staged forward would drop the model's aux loss
        costs = _layer_costs(spec, n_layers)
        batch = task.get_dataset().batch_size
        # Cross-slice stage placement is only worth its DCN hops when the
        # block genuinely spans slices (``search`` stamps ``topology``).
        topo = getattr(self, "topology", None)
        slice_size = getattr(topo, "slice_size", None) if topo is not None else None
        cross_slice = bool(slice_size) and int(slice_size) < int(n_devices)
        grid: List[Dict[str, Any]] = []
        # Every divisor of the device count, not just powers of two: the old
        # ``s <<= 1`` sweep meant a 6-device slice never considered s=3/s=6.
        for s in range(2, min(n_devices, n_layers) + 1):
            if n_devices % s != 0:
                continue
            d = n_devices // s
            if batch % d != 0:
                continue
            per_replica = batch // d
            # Balanced boundaries (reference balance_by_time analog):
            # needed when per-layer costs are uneven OR the stage count
            # doesn't divide the stack (pre-round-4 both cases silently
            # produced no pp candidates).
            spans: Optional[Tuple[int, ...]] = None
            if costs is not None:
                spans = balance_stages(costs, s)
            elif n_layers % s != 0:
                spans = balance_stages([1.0] * n_layers, s)
            # Microbatch sweep, most-microbatches (smallest bubble) first —
            # the analog of the reference's halving search (Pipeline.py:139).
            gpipe_ms = [m for m in (4 * s, 2 * s, s) if per_replica % m == 0]
            if not gpipe_ms:
                # Fallback: the largest stage-count multiple <= 4s dividing
                # the per-replica batch (the old sweep silently emitted no
                # pp candidates here).
                fb = [m for m in range(s, 4 * s + 1, s) if per_replica % m == 0]
                if fb:
                    gpipe_ms = [max(fb)]
            onef_ms = list(gpipe_ms)
            if not onef_ms:
                # 1F1B has no M % S constraint (the staged program runs any
                # M >= 1) — any divisor of the per-replica batch works.
                fb = [m for m in range(2, min(per_replica, 4 * s) + 1)
                      if per_replica % m == 0]
                if fb:
                    onef_ms = [max(fb)]
            layouts: List[Optional[str]] = [None]
            if cross_slice:
                layouts.append("stage_major")
            for layout in layouts:
                for schedule, ms in (("gpipe", gpipe_ms), ("1f1b", onef_ms)):
                    for m in ms:
                        base: Dict[str, Any] = {
                            "stages": s, "microbatches": m,
                            "schedule": schedule,
                        }
                        if spans is not None:
                            base["spans"] = spans
                        if layout is not None:
                            base["layout"] = layout
                        grid.append(dict(base, remat=False))
                        grid.append(dict(base, remat=True))
                        # Double-buffered stage hops (ops/pipeline.py H=2):
                        # next tick's ppermute issued before this tick's
                        # stage compute. Own grid points — realized cost
                        # decides, the bubble model prices H into the prior.
                        grid.append(dict(base, remat=False, overlap=True))
                        grid.append(dict(base, remat=True, overlap=True))
        return grid

    def config_bubble_fraction(self, config) -> float:
        """Analytic pipeline-bubble fraction of a steady-state step: the
        device-idle share a co-scheduled partner's windows could fill. 1F1B
        drains its bubble faster — (S-1)/(M+2(S-1)) vs GPipe's
        (S-1)/(M+S-1) — which makes a 1F1B job a WORSE gap-filler partner;
        the solver's co-location term prices exactly that difference."""
        s = int(config.get("stages", 2))
        m = int(config.get("microbatches", 2 * s))
        return schedule_bubble_fraction(
            str(config.get("schedule", "gpipe")), s, m,
            overlap=bool(config.get("overlap", False)),
        )

    def make_step_fns(self, spec, task, config, mesh, ds):
        self._require_no_aux(spec)  # staged forward would drop an aux loss
        s = config.get("stages", 2)
        m = config.get("microbatches", 2 * s)
        schedule = str(config.get("schedule", "gpipe"))
        if schedule not in PIPELINE_SCHEDULES:
            raise ValueError(
                f"unknown pipeline schedule {schedule!r}; "
                f"choices: {PIPELINE_SCHEDULES}"
            )
        spans = config.get("spans")
        n_layers = getattr(spec.config, "n_layers", 1)
        if spans is None and n_layers % s != 0:
            raise ValueError(
                f"{n_layers} layers not divisible by {s} stages — pass "
                "config['spans'] (candidate_configs computes balanced ones)"
            )
        hints = pipeline_hints(spec)
        bkey = spec.hints.get("block_param_key", "blocks")
        loss_fn = task.loss_fn
        common = dict(
            mesh=mesh,
            block_key=bkey,
            embed_fn=hints["embed"],
            block_fn=hints["block"],
            head_fn=hints["head"],
            loss_fn=loss_fn,
            n_microbatches=m,
            remat=bool(config.get("remat", False)),
            stage_spans=spans,
        )

        overlap = bool(config.get("overlap", False))
        if schedule == "1f1b" or overlap:
            # Explicitly staged program: bounded stash (min(M, 2S-1) vs AD's
            # M live microbatch residuals), backward launched C2 ticks behind
            # forward. Bit-identical summed grads vs the staged GPipe
            # ordering (same body jaxpr, same accumulation order). Overlapped
            # GPipe also routes here — only the staged scan can hoist the
            # stage hop above the tick's compute (H=2 double buffering).
            def loss_and_grads(params, batch):
                return staged_pipeline_loss_and_grads(
                    params, batch, schedule=schedule, overlap=overlap,
                    **common
                )
        else:
            def loss_and_grads(params, batch):
                return pipeline_loss_and_grads(params, batch, **common)

        return self.step_fns_from_loss_and_grads(spec.init_fn, task, loss_and_grads)
