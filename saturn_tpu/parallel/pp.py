"""Pipeline-parallel executor: GPipe over a ``stage`` mesh axis.

Replaces the reference's torchgpipe UDP (``examples/wikitext103/executors/
Pipeline.py:24-167``). Reference behavior preserved: partition the layer
stack across workers (``balance_by_time``, ``Pipeline.py:94-103`` → here
:func:`balance_stages`, an exact DP over the model's ``layer_costs`` hint
— profiled or FLOP-derived per-layer costs, uniform when absent), and
autotune the microbatch count (``Pipeline.py:139-159`` halving sweep → grid
over {M} multiples of the stage count). The schedule itself lives in
``saturn_tpu.ops.pipeline`` (shard_map + ppermute); unequal stage spans
(uneven costs, or a layer count the stage count doesn't divide) run via
the padded-span schedule there.

A ``data`` axis composes data parallelism with the pipeline: a mesh of
``n`` devices runs ``n/S`` pipeline replicas of ``S`` stages each.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from jax.sharding import PartitionSpec as P

from saturn_tpu.ops.pipeline import (
    balance_stages,
    pipeline_hints,
    pipeline_loss_and_grads,
)
from saturn_tpu.parallel.spmd_base import SPMDTechnique
from saturn_tpu.core.strategy import Techniques


def _layer_costs(spec, n_layers: int) -> Optional[list]:
    """Per-layer cost vector from the model hints, or None for uniform.
    Validated here so a stale hint fails search loudly, not mid-step."""
    costs = spec.hints.get("layer_costs")
    if costs is None:
        return None
    costs = list(costs)
    if len(costs) != n_layers or min(costs) <= 0:
        raise ValueError(
            f"layer_costs must be {n_layers} positive entries, got {costs!r}"
        )
    return costs


class Pipeline(SPMDTechnique):
    name = "pp"
    technique = Techniques.PIPELINE

    def mesh_spec(self, n_devices, task, config) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
        s = config.get("stages", 2)
        if n_devices % s != 0:
            raise ValueError(f"{n_devices} devices not divisible by {s} stages")
        return ("data", "stage"), (n_devices // s, s)

    def batch_spec(self, config) -> P:
        return P("data")

    def param_rules(self, task, config):
        spec = task.get_model()
        bkey = spec.hints.get("block_param_key", "blocks")
        s = config.get("stages", 2)

        def rules(path: str, shape: Tuple[int, ...], mesh_axes) -> P:
            # At-rest layout: NamedSharding requires the sharded dim to
            # divide by the axis size, so a stack the stage count doesn't
            # divide stays replicated at rest (param memory = dp's; the
            # padded-span repack inside the step still distributes compute).
            # Cost-uneven stacks whose length DOES divide keep the sharded
            # rest layout — the repack moves only boundary-crossing layers.
            if bkey in path and shape and shape[0] % s == 0:
                return P("stage")
            return P()

        return rules

    def candidate_configs(self, task, n_devices) -> List[Dict[str, Any]]:
        spec = task.get_model()
        n_layers = getattr(spec.config, "n_layers", 1)
        if "pipeline" not in spec.hints:
            return []
        if self._aux_incompatible(spec):
            return []  # staged forward would drop the model's aux loss
        costs = _layer_costs(spec, n_layers)
        batch = task.get_dataset().batch_size
        grid: List[Dict[str, Any]] = []
        s = 2
        while s <= n_devices and s <= n_layers:
            if n_devices % s == 0:
                d = n_devices // s
                # Balanced boundaries (reference balance_by_time analog):
                # needed when per-layer costs are uneven OR the stage count
                # doesn't divide the stack (pre-round-4 both cases silently
                # produced no pp candidates).
                spans: Optional[Tuple[int, ...]] = None
                if costs is not None:
                    spans = balance_stages(costs, s)
                elif n_layers % s != 0:
                    spans = balance_stages([1.0] * n_layers, s)
                # Microbatch sweep, most-microbatches (smallest bubble)
                # first — the analog of the reference's halving search
                # (Pipeline.py:139).
                for m in (4 * s, 2 * s, s):
                    if batch % (d * m) == 0:
                        base: Dict[str, Any] = {"stages": s, "microbatches": m}
                        if spans is not None:
                            base["spans"] = spans
                        grid.append(dict(base, remat=False))
                        grid.append(dict(base, remat=True))
            s <<= 1
        return grid

    def make_step_fns(self, spec, task, config, mesh, ds):
        self._require_no_aux(spec)  # staged forward would drop an aux loss
        s = config.get("stages", 2)
        m = config.get("microbatches", 2 * s)
        spans = config.get("spans")
        n_layers = getattr(spec.config, "n_layers", 1)
        if spans is None and n_layers % s != 0:
            raise ValueError(
                f"{n_layers} layers not divisible by {s} stages — pass "
                "config['spans'] (candidate_configs computes balanced ones)"
            )
        hints = pipeline_hints(spec)
        bkey = spec.hints.get("block_param_key", "blocks")
        loss_fn = task.loss_fn

        def loss_and_grads(params, batch):
            return pipeline_loss_and_grads(
                params,
                batch,
                mesh=mesh,
                block_key=bkey,
                embed_fn=hints["embed"],
                block_fn=hints["block"],
                head_fn=hints["head"],
                loss_fn=loss_fn,
                n_microbatches=m,
                remat=bool(config.get("remat", False)),
                stage_spans=spans,
            )

        return self.step_fns_from_loss_and_grads(spec.init_fn, task, loss_and_grads)
