"""Data-parallel executor: batch-sharded pjit over a 1-D ``data`` mesh.

Replaces the reference's DDP UDP (``examples/wikitext103/executors/DDP.py``):
instead of per-GPU processes + NCCL allreduce, the batch is sharded over the
``data`` axis and XLA emits the gradient psum over ICI. Unlike the reference's
DDP — whose ``search`` returned None and could never be selected
(``DDP.py:72``, SURVEY.md §2 C17) — this one is a first-class citizen.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from saturn_tpu.parallel import sharding as shr
from saturn_tpu.parallel.spmd_base import SPMDTechnique
from saturn_tpu.core.strategy import Techniques


class DataParallel(SPMDTechnique):
    name = "dp"
    technique = Techniques.DP
    # Params replicated + batch sharded over 'data': the fused head+loss
    # runs on multi-chip blocks too, via the shard_map sum/count wrapper
    # (spmd_base.step_fns_from_forward).
    fused_loss_shardable = True

    def mesh_spec(self, n_devices, task, config) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
        return ("data",), (n_devices,)

    def param_rules(self, task, config):
        return shr.replicated_rules

    def candidate_configs(self, task, n_devices) -> List[Dict[str, Any]]:
        # remat off first (faster when it fits), on as fallback — same
        # best-guess-first grid ordering idea as ``FSDP.py:72-78``; crossed
        # with flash attention on TPU so the solver picks from measurement.
        return self._with_attention_variants(
            task, [{"remat": False}, {"remat": True}]
        )
