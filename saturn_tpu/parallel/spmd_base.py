"""SPMDTechnique: shared machinery for sharding-based executors (DP/FSDP/TP).

In the reference, each technique was ~200 lines of process spawning, NCCL
setup, wrapper classes and OOM probing (``FSDP.py``, ``DDP.py``). TPU-native,
a technique reduces to: a mesh shape, a PartitionSpec rule function, and a
small autotune grid. Everything else — building the jitted train step, XLA
memory feasibility, steady-state timing, checkpoint/resume with resharding —
is shared here.

Contract parity (``Technique.py:24-45``): subclasses get ``search`` (autotune
+ profile) and ``execute`` (bounded batches, resume + checkpoint) for free and
override only the three small hooks at the bottom.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import timeit as _timeit
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from saturn_tpu.core.mesh import make_submesh
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.parallel import sharding as shr
from saturn_tpu.utils import checkpoint as ckpt
from saturn_tpu.utils.timing import (
    device_hbm_bytes,
    hbm_bytes_required,
    time_fused_window,
    time_train_step,
)

log = logging.getLogger("saturn_tpu")


def _stage_to_device(tree):
    """Move a (possibly pinned-host) tree into device memory inside jit."""
    return jax.device_put(tree, jax.memory.Space.Device)


# ------------------------------------------------------- fused-window policy
#: Default ceiling on the fused multi-step window K (``lax.scan`` over a
#: stacked window of K batches inside one jitted call). K trades per-step
#: Python dispatch + per-step loss readback against staged-batch memory
#: ((K, B, T) tokens resident at once) and progress granularity — a window
#: is all-or-nothing under preemption, and the interval's batch budget is
#: only exact at window boundaries.
DEFAULT_MAX_WINDOW = 8

_ENV_MAX_WINDOW = "SATURN_TPU_MAX_WINDOW"


def _env_hbm_bytes() -> int:
    """SATURN_TPU_HBM_BYTES (memlens's capacity override) as an int, 0
    when unset/garbage — platforms that report no memory stats fall back
    to it so compile-time rejection works on CPU sweeps too."""
    try:
        return max(int(float(os.environ.get("SATURN_TPU_HBM_BYTES", "0"))), 0)
    except ValueError:
        return 0


def max_window() -> int:
    """Ceiling on the fused window K (env ``SATURN_TPU_MAX_WINDOW``).

    ``<= 1`` disables fused dispatch entirely — every interval runs the
    exact legacy per-step path.
    """
    try:
        k = int(os.environ.get(_ENV_MAX_WINDOW, DEFAULT_MAX_WINDOW))
    except ValueError:
        return DEFAULT_MAX_WINDOW
    return max(1, k)


def choose_window(n_batches: int, cap: Optional[int] = None) -> int:
    """Fused window size K for an interval budget of ``n_batches``.

    The largest window under the cap that the budget can fill at least
    once; 1 (the exact per-step fallback) when the interval is too short to
    amortize a fused program or fused dispatch is disabled. The interval
    then runs ``n // K`` fused windows plus an ``n % K`` per-step tail, so
    every budgeted batch runs and loss trajectories stay bit-identical to
    the 1-step path.
    """
    cap = max_window() if cap is None else int(cap)
    n = int(n_batches)
    if cap <= 1 or n < 2:
        return 1
    return min(cap, n)


def _host_fraction(t_host: float, t_device: float) -> float:
    """Fraction of one steady-state batch spent on host-side staging work.

    ``t_host`` is the measured staging cost per batch (dataset slice +
    ``device_put`` transfer), ``t_device`` the device-only per-batch time.
    The ratio against their sum lands in [0, 1] with the useful pivot at
    0.5: above it the job is stage-bound — its device sits idle
    ``t_host - t_device`` out of every ``t_host`` of wall clock, which is
    the bubble a co-scheduled compute-bound neighbor can fill.
    """
    t_host = max(0.0, float(t_host))
    t_device = max(0.0, float(t_device))
    total = t_host + t_device
    if total <= 0.0:
        return 0.0
    return min(1.0, max(0.0, t_host / total))


def dispatch_signature() -> str:
    """Content signature of the execution dispatch mode, for the profile
    cache key (``utils/profile_cache.fingerprint``): per-step trial profiles
    must not warm-start fused-dispatch sweeps (and vice versa) — the two
    modes have genuinely different per-batch times, which is the point."""
    k = max_window()
    return f"fused-scan-v1:k{k}" if k > 1 else "per-step"


@dataclass
class _Bundle:
    """Everything needed to run one (task, devices, config) combination."""

    mesh: Any
    step: Any                 # jitted train step: (state, batch) -> (state, loss)
    init: Any                 # jitted sharded init: () -> state
    state_shapes: Any         # ShapeDtypeStruct tree (for restore templates)
    state_shardings: Any
    batch_sharding: Any
    lowered: Any              # jit(...).lower(...) result, for memory analysis
    train_step: Any = None    # raw python step fn (fused scan re-traces it)
    batch_sds: Any = None     # ShapeDtypeStruct of one host batch
    retrace_key: Any = None   # stable (task, config, block) dispatch identity
    _compiled: Any = None
    _fused: Dict[int, Any] = field(default_factory=dict)
    _fused_lock: Any = field(default_factory=threading.Lock)

    def _block_devices(self):
        """The concrete devices this bundle's programs are pinned to — part
        of every AOT-cache key (same program, different block = different
        executable)."""
        return list(self.mesh.devices.flat)

    @property
    def compiled(self):
        """The AOT-compiled train step. Compiled exactly once per bundle —
        memory analysis, trial timing and interval execution all share it, so
        a (task, config, block) combination never compiles twice. Routed
        through the persistent executable cache (``utils/aot_cache``): a
        restart or re-admission of a previously-seen program deserializes
        instead of recompiling."""
        if self._compiled is None:
            from saturn_tpu.utils import aot_cache

            self._compiled = aot_cache.load_or_compile(
                self.lowered, self._block_devices()
            )
        return self._compiled

    def stacked_sharding(self):
        """Sharding for a (K, batch, seq) window stack: the window axis is
        unsharded (scan consumes it sequentially), each slice keeps the
        bundle's batch sharding."""
        return NamedSharding(
            self.mesh, P(None, *tuple(self.batch_sharding.spec))
        )

    def has_fused(self, k: int) -> bool:
        with self._fused_lock:
            return int(k) in self._fused

    def fused_compiled(self, k: int):
        """AOT-compiled fused K-step program, compiled once per (bundle, K).

        ``lax.scan`` of the raw train step over a stacked (K, batch, seq)
        window inside one XLA program: one Python dispatch and one loss
        readback amortize over K batches, and XLA pipelines the inter-step
        boundary (no host round-trip between steps). State AND the window
        stack are donated — the caller must stage a fresh stack per call.
        The per-step losses come back as a (K,) vector so the loss
        trajectory is observable exactly as the 1-step path reports it.
        """
        k = int(k)
        with self._fused_lock:
            hit = self._fused.get(k)
        if hit is not None:
            return hit
        train = self.train_step
        if train is None or k < 1:
            raise ValueError(f"bundle cannot build a fused window (k={k})")

        def multi_step(state, window):
            return jax.lax.scan(train, state, window)

        fused = jax.jit(
            multi_step,
            in_shardings=(self.state_shardings, self.stacked_sharding()),
            out_shardings=(self.state_shardings, NamedSharding(self.mesh, P())),
            donate_argnums=(0, 1),
        )
        window_sds = jax.ShapeDtypeStruct(
            (k, *self.batch_sds.shape), self.batch_sds.dtype
        )
        if self.retrace_key is not None:
            # Static retrace-risk check (saturn-lint pass 2a): a novel
            # abstract signature for an already-compiled (bundle, K) key
            # means this compile is an AOT-cache miss the plan didn't
            # budget for — flag it before it burns chip time.
            from saturn_tpu.analysis import jax_lint as _jlint

            diag = _jlint.retrace_registry.note(
                self.retrace_key, k,
                _jlint.abstract_signature((self.state_shapes, window_sds)),
            )
            if diag is not None:
                log.warning("%s", diag.message)
        from saturn_tpu.utils import aot_cache

        compiled = aot_cache.load_or_compile(
            fused.lower(self.state_shapes, window_sds), self._block_devices()
        )
        with self._fused_lock:
            return self._fused.setdefault(k, compiled)


class SPMDTechnique(BaseTechnique):
    """Base for techniques expressible as (mesh shape + sharding rules)."""

    name = "spmd"

    # Per-chip memory never grows with block size under sharding: replicated
    # state is constant per chip, sharded state (params, activations, layer
    # spans, expert tables) shrinks. Lets the trial runner skip all smaller
    # sizes once XLA memory analysis rejects one (``core/technique.py``).
    memory_monotone = True

    # Per-instance ceiling on cached compiled programs. A 16-task ×
    # multi-config × multi-block sweep would otherwise hold every executable
    # for the life of the technique (VERDICT r2 weak #7); LRU keeps the
    # working set (active tasks' current configs) while bounding growth.
    bundle_cache_cap = 32

    # Whether this technique may route standard-loss tasks through the
    # model's fused head+loss (ops/ce.py). Techniques that shard the head
    # weights over the vocab axis must opt out: the Pallas CE kernel has no
    # vocab-partitioning rule, so GSPMD would all-gather the full table and
    # an unsharded (N, V) logits stash per device.
    fused_loss_ok = True
    # Whether the fused loss may run on MULTI-chip blocks via the shard_map
    # wrapper (step_fns_from_forward): only valid for purely batch-sharding
    # techniques — params must be replicated (in_spec P()) and the batch
    # sharded along the mesh. dp opts in; fsdp/tp shard params.
    fused_loss_shardable = False

    # Advertises the optional ``execute(window_size=...)`` kwarg to the
    # engine (``executor/engine.py`` gates the kwarg on this attribute so
    # plugin techniques with the bare BaseTechnique signature keep working).
    supports_windows = True
    # Advertises ``interval_dispatches`` — the resumable per-window generator
    # the engine's co-schedule group launcher interleaves across tasks
    # sharing a device block. Techniques without it fall back to sequential
    # execution on the shared launcher (correct, just unoverlapped).
    supports_coschedule = True
    # Whether fused multi-step dispatch (``lax.scan`` window) is valid for
    # this technique at all. Techniques whose step depends on per-call host
    # interaction can opt out; offloaded (pinned_host) configs are excluded
    # per-config in ``_fused_ok`` regardless.
    fused_dispatch_ok = True

    def __init__(self) -> None:
        # Bundle cache keyed by (task, config, device block): the orchestrator
        # calls execute() every interval (reference kill-and-respawn,
        # ``executor.py:65``); without the cache each interval would pay a
        # full XLA recompile of an identical program. LRU-ordered (see
        # ``bundle_cache_cap``); completed tasks release their entries via
        # ``release_task`` (mirroring ``Task.release_live_state``). The lock
        # covers the compound move_to_end/popitem/del sequences: one technique
        # instance serves concurrent trial threads (``evaluator.py``) and
        # gang-launch threads (``engine.py``).
        from collections import OrderedDict

        self._bundles: "OrderedDict[Any, _Bundle]" = OrderedDict()
        self._bundles_lock = threading.Lock()
        # Static per-step FLOPs (shardflow's dense-dot ledger) per bundle
        # key — the numerator of the task_interval tflops/mfu report.
        # Traced lazily at most once per compiled program; a failed trace
        # caches None so telemetry degrades to omitting the fields instead
        # of re-paying (or re-raising) the trace every interval.
        self._flops_cache: Dict[Any, Optional[float]] = {}
        self._flops_lock = threading.Lock()
        # Why each (task, size) search came back infeasible — consumed (and
        # popped) by the trial runner's monotone pruning. Keyed per grid
        # point because one instance serves concurrent trial threads.
        self._search_reports: Dict[Any, Dict[str, Any]] = {}
        # Host fraction measured for the best (task, size) config — consumed
        # (popped) by the trial runner alongside the per-batch time; feeds
        # the solver's co-location term via ``Strategy.host_fraction``.
        self._host_fracs: Dict[Any, float] = {}
        self._reports_lock = threading.Lock()

    def search_report(self, task_name: str, size: int) -> Optional[Dict[str, Any]]:
        """Pop the infeasibility report for the most recent ``search`` of
        (task, size); None when the search was feasible or never ran."""
        with self._reports_lock:
            return self._search_reports.pop((task_name, size), None)

    def host_fraction_report(self, task_name: str, size: int) -> Optional[float]:
        """Pop the host fraction measured by the most recent feasible
        ``search`` of (task, size); None when no feasible search ran. Same
        pop-once protocol as ``search_report`` — one technique instance
        serves concurrent trial threads."""
        with self._reports_lock:
            return self._host_fracs.pop((task_name, size), None)

    def config_bubble_fraction(self, config: Dict[str, Any]) -> float:
        """Analytic DEVICE-idle fraction of a steady-state step under
        ``config`` — schedule bubbles (pipeline warmup/cooldown) a
        co-scheduled partner's device windows could fill, in [0, 1).

        Unlike ``host_fraction`` this is derived from the config, not
        measured: the bubble is a property of the schedule shape (stage and
        microbatch counts), so every install path — trial, cache hit,
        interpolated fill, elastic re-synthesis — recomputes it exactly.
        Dense sharding techniques have no schedule bubble; the pipeline
        executor overrides this with the GPipe/1F1B bubble formulas.
        """
        return 0.0

    def release_task(self, task_name: str) -> None:
        """Drop every cached compiled program for ``task_name`` — called when
        the task completes or is evicted, so finished sweeps don't pin
        executables (and their device constants) for the technique's life."""
        with self._bundles_lock:
            for key in [k for k in self._bundles if k[0] == task_name]:
                del self._bundles[key]
        with self._flops_lock:
            for key in [k for k in self._flops_cache if k[0] == task_name]:
                del self._flops_cache[key]

    def _step_flops(self, task, devices, config) -> Optional[float]:
        """Shardflow's static dense-FLOP count for one step of this (task,
        config, block) — global across the sub-mesh, per batch. Cached per
        bundle key (same identity as the compiled program it describes)."""
        key = self._bundle_key(task, devices, config)
        with self._flops_lock:
            if key in self._flops_cache:
                return self._flops_cache[key]
        flops: Optional[float]
        try:
            from saturn_tpu.analysis.shardflow.interp import interpret

            traced = self.trace_step(task, devices, config)
            flops = float(interpret(traced).flops) or None
        except Exception:
            log.debug("shardflow flops trace failed for task %s", task.name,
                      exc_info=True)
            flops = None
        with self._flops_lock:
            self._flops_cache[key] = flops
        return flops

    def _bundle_key(self, task, devices, config):
        return (
            task.name,
            tuple(sorted((k, v) for k, v in config.items())),
            tuple(getattr(d, "id", i) for i, d in enumerate(devices)),
        )

    # ----------------------------------------------------------------- hooks
    def mesh_spec(
        self, n_devices: int, task: Any, config: Dict[str, Any]
    ) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
        """(axis_names, axis_sizes) for a sub-mesh of ``n_devices`` chips."""
        raise NotImplementedError

    def param_rules(self, task: Any, config: Dict[str, Any]):
        """Rule fn (path, shape, mesh_axes) -> PartitionSpec for params."""
        raise NotImplementedError

    def batch_spec(self, config: Dict[str, Any]) -> P:
        """PartitionSpec for the (batch, seq) token batch."""
        return P("data")

    def candidate_configs(
        self, task: Any, n_devices: int
    ) -> List[Dict[str, Any]]:
        """Autotune grid, best-guess-first (reference ``FSDP.py:72-78``)."""
        return [{}]

    def param_memory_kind(self, config: Dict[str, Any]) -> Optional[str]:
        """Memory kind for persistent state ('pinned_host' = offload)."""
        return None

    def make_step_fns(
        self, spec: Any, task: Any, config: Dict[str, Any], mesh: Any, ds: Any
    ) -> Tuple[Any, Any]:
        """(init_state, train_step) for this technique.

        The default is the standard data/tensor-sharded step: loss over the
        full global batch, grads, optax update — GSPMD inserts all
        collectives from the shardings alone. Techniques with an explicit
        schedule (pipeline) override this to build a ``shard_map`` step;
        techniques that only change the forward pass (offload streaming)
        override via ``step_fns_from_forward``.

        When the technique pins persistent state to host memory
        (``param_memory_kind == 'pinned_host'`` — fsdp's offload grid, bulk
        offload), TPU compute cannot consume the host-space arrays directly
        (round-5 chip run: ``add`` of f32 and f32<host> is rejected), so the
        forward stages params to device and the optimizer update runs as
        host computation — see ``step_fns_from_loss_and_grads``.
        """
        to_host_update = self.param_memory_kind(config) == "pinned_host"
        forward = spec.apply_fn
        forward_with_aux = None
        if to_host_update:
            def forward(params, batch):
                return spec.apply_fn(_stage_to_device(params), batch)

            if spec.apply_with_aux_fn is not None:
                def forward_with_aux(params, batch):
                    return spec.apply_with_aux_fn(
                        _stage_to_device(params), batch
                    )

        return self.step_fns_from_forward(
            spec, task, forward, forward_with_aux=forward_with_aux,
            mesh=mesh, batch_partition=self.batch_spec(config),
            update_on_host=to_host_update,
        )

    def step_fns_from_forward(
        self, spec: Any, task: Any, forward: Any, forward_with_aux: Any = None,
        mesh: Any = None, batch_partition: Any = None,
        update_on_host: bool = False,
    ) -> Tuple[Any, Any]:
        """Standard loss/grad/optax scaffold around ``forward(params, batch)``.

        Models exposing an auxiliary training loss (``apply_with_aux_fn``,
        e.g. MoE load balancing) get it added here, in the shared scaffold,
        so the objective is identical no matter which technique the solver
        picks for an interval. A technique that wraps the forward pass but
        preserves its semantics (bulk offload staging) passes its own
        ``forward_with_aux`` wrapper; techniques that replace the schedule
        outright (pipeline, ring, offload streaming) must declare aux models
        infeasible instead — ``_aux_incompatible`` is the helper for that.
        """
        loss_fn = task.loss_fn
        if forward_with_aux is None and (
            spec.apply_with_aux_fn is not None and forward is spec.apply_fn
        ):
            forward_with_aux = spec.apply_with_aux_fn

        # Fused head+loss (ops/ce.py): same objective, no (B,T,V) logits.
        # Only when the technique runs the model's own forward and the
        # task's loss is the standard one the fused path implements. A
        # pallas_call has NO GSPMD partitioning rule, so how it engages
        # depends on the block:
        # - single device (mesh absent or size 1): call it directly;
        # - multi-chip blocks of a purely batch-sharding technique
        #   (``fused_loss_shardable``, i.e. dp: params replicated): wrap it
        #   in shard_map — each device runs the kernel on its batch shard
        #   and the (loss_sum, valid_count) parts are psum'd before the
        #   global divide (per-shard means would misweight uneven masks);
        # - everything else (fsdp's vocab-sharded wte, tp) keeps the GSPMD
        #   logits pipeline, which partitions the head matmul + softmax
        #   natively.
        fused = getattr(spec, "fused_loss_fn", None)
        parts = getattr(spec, "fused_loss_parts_fn", None)
        tag = getattr(loss_fn, "supports_fused_head", None)
        single = mesh is None or getattr(mesh, "size", 1) <= 1
        if (
            fused is not None
            and self.fused_loss_ok
            and (single or (self.fused_loss_shardable and parts is not None))
            and forward is spec.apply_fn
            and forward_with_aux is None
            and tag is not None
            and tag == getattr(spec, "fused_loss_objective", None)
        ):
            if single:
                fused_loss = fused
            else:
                try:
                    from jax import shard_map
                except ImportError:  # jax < 0.5 keeps it in experimental
                    from jax.experimental.shard_map import shard_map

                axes = tuple(mesh.axis_names)
                bspec = batch_partition if batch_partition is not None else P(
                    axes[0]
                )

                def _local(p, b):
                    s, c = parts(p, b)
                    s = jax.lax.psum(s, axes)
                    c = jax.lax.psum(c, axes)
                    return s / jax.numpy.maximum(c, 1)

                def fused_loss(params, batch):
                    return shard_map(
                        _local, mesh=mesh, in_specs=(P(), bspec),
                        out_specs=P(),
                    )(params, batch)

            def loss_and_grads(params, batch):
                return jax.value_and_grad(fused_loss)(params, batch)

            return self.step_fns_from_loss_and_grads(
                spec.init_fn, task, loss_and_grads,
                update_on_host=update_on_host,
            )

        def loss_and_grads(params, batch):
            def loss_of(p):
                if forward_with_aux is not None:
                    logits, aux = forward_with_aux(p, batch)
                    return loss_fn(logits, batch) + aux
                return loss_fn(forward(p, batch), batch)

            return jax.value_and_grad(loss_of)(params)

        return self.step_fns_from_loss_and_grads(
            spec.init_fn, task, loss_and_grads, update_on_host=update_on_host
        )

    @staticmethod
    def _aux_incompatible(spec: Any) -> bool:
        """True if the model carries an aux loss this technique's custom
        forward path would silently drop — used by candidate_configs to
        declare the (task × technique) pair infeasible, keeping the training
        objective consistent across interval-boundary technique switches."""
        return spec.apply_with_aux_fn is not None

    def _require_no_aux(self, spec: Any) -> None:
        """Execution-time guard mirroring the candidate_configs check:
        build()/make_step_fns called directly with an aux-loss model on a
        schedule that would drop the aux term must fail loudly, not train a
        silently different objective."""
        if self._aux_incompatible(spec):
            raise ValueError(
                f"{self.name}: model has an auxiliary loss (apply_with_aux_fn) "
                f"that this technique's custom schedule would drop; use a "
                f"dense technique (dp/fsdp/tp/ep) for aux-loss models"
            )

    def step_fns_from_loss_and_grads(
        self, init_params: Any, task: Any, loss_and_grads: Any,
        update_on_host: bool = False,
    ) -> Tuple[Any, Any]:
        """(init_state, train_step) around ``loss_and_grads(params, batch)``.

        The single definition of the train-state layout ({params, opt_state,
        step}) and the optimizer-update tail — every technique (dense,
        offload, pipeline, ring) routes through here so the state contract
        cannot diverge between them.

        ``update_on_host``: run the (elementwise) optax update as XLA host
        computation against the pinned-host state. This is what lets
        billion-param offload fit: params + both adam moments never occupy
        HBM at once — only the grads cross PCIe (ZeRO-Offload's CPU-optimizer
        design, the TPU-native analog of the reference's fairscale spilling,
        ``Spilled.py:23-28``). Staging the update to device instead would
        put 4 copies (params, grads, mu, nu) on chip and OOM the very
        models the technique exists for.
        """
        tx = task.hparams.make_optimizer()

        def init_state():
            params = init_params(jax.random.PRNGKey(0))
            return {
                "params": params,
                "opt_state": tx.init(params),
                "step": jax.numpy.zeros((), dtype=jax.numpy.int32),
            }

        def train_step(state, batch):
            loss, grads = loss_and_grads(state["params"], batch)
            if update_on_host:
                from jax.experimental.compute_on import compute_on

                grads = jax.device_put(grads, jax.memory.Space.Host)
                ctx = compute_on("device_host")
            else:
                ctx = contextlib.nullcontext()
            with ctx:
                updates, new_opt = tx.update(
                    grads, state["opt_state"], state["params"]
                )
                new_params = optax.apply_updates(state["params"], updates)
                new_step = state["step"] + 1
            return {
                "params": new_params,
                "opt_state": new_opt,
                "step": new_step,
            }, loss

        return init_state, train_step

    # -------------------------------------------------------------- building
    def _model_overrides(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = {}
        if "remat" in config:
            out["remat"] = config["remat"]
        if config.get("attention"):
            out["attention"] = config["attention"]
        return out

    def _with_attention_variants(
        self, task: Any, grid: List[Dict[str, Any]]
    ) -> List[Dict[str, Any]]:
        """Cross an autotune grid with explicit {flash, dense} attention when
        the Pallas kernel can lower for this task's model. Both variants are
        pinned explicitly (the model default is 'auto', so an unpinned entry
        would duplicate the flash one on TPU); flash first — it measured
        fastest at every seq on the chip (BASELINE.md) — but the trial runner
        keeps whichever measures faster for THIS task: the
        empirically-selected-config premise of the whole system
        (``PerformanceEvaluator.py:101-115``)."""
        from saturn_tpu.ops.flash import flash_supported

        try:
            cfg = task.get_model().config
        except Exception:
            return grid
        if getattr(cfg, "attention", None) is None or not flash_supported(cfg):
            return grid
        out: List[Dict[str, Any]] = []
        for c in grid:
            out.append(dict(c, attention="flash"))
            out.append(dict(c, attention="dense"))
        return out

    def build(
        self, task: Any, devices: Sequence[Any], config: Dict[str, Any],
        use_cache: bool = True,
    ) -> _Bundle:
        key = self._bundle_key(task, devices, config)
        if use_cache:
            with self._bundles_lock:
                hit = self._bundles.get(key)
                if hit is not None:
                    self._bundles.move_to_end(key)  # LRU touch
                    return hit
        bundle = self._build_uncached(task, devices, config)
        bundle.retrace_key = key
        # Seed the retrace-risk registry with the per-step signature so a
        # later rebuild of the same dispatch key with novel shapes/dtypes
        # (dataset drift, config mutation) is flagged before it recompiles.
        from saturn_tpu.analysis import jax_lint as _jlint

        diag = _jlint.retrace_registry.note(
            key, "per-step",
            _jlint.abstract_signature((bundle.state_shapes, bundle.batch_sds)),
        )
        if diag is not None:
            log.warning("%s", diag.message)
        if use_cache:
            with self._bundles_lock:
                self._bundles[key] = bundle
                while len(self._bundles) > self.bundle_cache_cap:
                    evicted, _ = self._bundles.popitem(last=False)
                    log.info("%s: bundle cache cap %d hit — evicted %s",
                             self.name, self.bundle_cache_cap, evicted[0])
        return bundle

    def _build_uncached(
        self, task: Any, devices: Sequence[Any], config: Dict[str, Any]
    ) -> _Bundle:
        # Persistent XLA compilation cache (opt-in via
        # SATURN_TPU_COMPILE_CACHE_DIR): every compile — trial-time AND the
        # execution engine's bundle builds — lands in one on-disk cache, so a
        # program compiled by a sweep is reused by later intervals and later
        # processes. Idempotent no-op when unconfigured.
        from saturn_tpu.utils import profile_cache as _pcache

        _pcache.maybe_enable_persistent_compile_cache()
        spec = task.get_model(**self._model_overrides(config))
        axis_names, axis_sizes = self.mesh_spec(len(devices), task, config)
        mesh = make_submesh(devices, axis_names, axis_sizes)
        mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape))

        ds = task.get_dataset()
        bspec = self.batch_spec(config)
        data_axis = tuple(bspec)[0] if len(tuple(bspec)) else None
        if data_axis is not None and ds.batch_size % mesh_axes.get(data_axis, 1) != 0:
            raise ValueError(
                f"batch_size {ds.batch_size} not divisible by "
                f"{data_axis}={mesh_axes.get(data_axis)}"
            )

        init_state, train_step = self.make_step_fns(spec, task, config, mesh, ds)
        state_shapes = jax.eval_shape(init_state)
        rules = self.param_rules(task, config)
        mem_kind = self.param_memory_kind(config)

        from saturn_tpu.analysis import jax_lint as _jlint

        def shard_of(path, leaf):
            spec_ = rules(shr._path_str(path), tuple(leaf.shape), mesh_axes)
            # Sharding lint (saturn-lint pass 2d): refuse a spec the mesh
            # cannot satisfy (unknown axis, rank overflow) HERE, on CPU,
            # with the rule's file:line — not as a GSPMD compile failure
            # on the chips. Raises ShardingLintError (a ValueError, so the
            # trial runner treats it like any infeasible configuration).
            _jlint.enforce_pspec(spec_, tuple(leaf.shape), mesh_axes,
                                 path=shr._path_str(path), rules=rules)
            if mem_kind is not None:
                return NamedSharding(mesh, spec_, memory_kind=mem_kind)
            return NamedSharding(mesh, spec_)

        state_shardings = jax.tree_util.tree_map_with_path(shard_of, state_shapes)
        batch_sharding = NamedSharding(mesh, bspec)

        step = jax.jit(
            train_step,
            in_shardings=(state_shardings, batch_sharding),
            out_shardings=(state_shardings, NamedSharding(mesh, P())),
            donate_argnums=(0,),
        )
        init = jax.jit(init_state, out_shardings=state_shardings)

        batch_sds = jax.ShapeDtypeStruct(
            ds.example_batch().shape, ds.example_batch().dtype
        )
        lowered = step.lower(state_shapes, batch_sds)
        return _Bundle(
            mesh=mesh,
            step=step,
            init=init,
            state_shapes=state_shapes,
            state_shardings=state_shardings,
            batch_sharding=batch_sharding,
            lowered=lowered,
            train_step=train_step,
            batch_sds=batch_sds,
        )

    # ------------------------------------------------------------- shardflow
    def trace_step(
        self, task: Any, devices: Sequence[Any], config: Dict[str, Any]
    ) -> Dict[str, Any]:
        """Build hook for saturn-shardflow (``analysis/shardflow/``): trace
        this technique's train step to a closed jaxpr together with its
        sharding intent, **without compiling** — abstract values only, so
        the static analyzer can propagate PartitionSpecs through every
        equation on CPU before any chip time is spent.

        Mirrors ``_build_uncached`` up to (but excluding) ``jit``/``lower``:
        same mesh, same step functions, same rule-derived specs — if the two
        ever diverge the differential test (``tests/test_shardflow_
        differential.py``) catches it against the compiled program.
        """
        spec = task.get_model(**self._model_overrides(config))
        axis_names, axis_sizes = self.mesh_spec(len(devices), task, config)
        mesh = make_submesh(devices, axis_names, axis_sizes)
        mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape))

        ds = task.get_dataset()
        init_state, train_step = self.make_step_fns(spec, task, config, mesh, ds)
        state_shapes = jax.eval_shape(init_state)
        rules = self.param_rules(task, config)
        state_specs = jax.tree_util.tree_map_with_path(
            lambda path, leaf: rules(
                shr._path_str(path), tuple(leaf.shape), mesh_axes
            ),
            state_shapes,
        )
        batch_sds = jax.ShapeDtypeStruct(
            ds.example_batch().shape, ds.example_batch().dtype
        )
        closed = jax.make_jaxpr(train_step)(state_shapes, batch_sds)
        return {
            "jaxpr": closed,
            "state_shapes": state_shapes,
            "state_specs": state_specs,
            "batch_spec": self.batch_spec(config),
            "batch_sds": batch_sds,
            "mesh_axes": mesh_axes,
            "technique": self.name,
            "size": len(devices),
            "config": dict(config),
            # memlens: pinned-host configs keep resident params/opt-state
            # in host memory, so the liveness pass excludes them from HBM
            "param_memory_kind": self.param_memory_kind(config),
        }

    # ------------------------------------------------------------ feasibility
    def _fits_memory(
        self, bundle: _Bundle, devices: Sequence[Any],
        task: Any = None, config: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """XLA compile-time memory check (replaces OOM probes,
        ``Spilled.py:68-87``)."""
        return self._fits_compiled(bundle.compiled, devices,
                                   task=task, config=config, k=1)

    def _fits_compiled(
        self, compiled: Any, devices: Sequence[Any], *,
        task: Any = None, config: Optional[Dict[str, Any]] = None,
        k: int = 1,
    ) -> bool:
        """Memory check against a specific compiled program — the fused
        K-step trial analyzes the window program it will actually time (its
        peak includes the (K, B, T) staged stack the 1-step program never
        holds).

        When the caller knows the (task, config) this program came from,
        every check also emits a ``memlens_calibration`` metrics event —
        static predicted bytes next to the compiled figure — so the
        SAT-M005 drift audit accrues for free on every sweep.
        """
        limit = device_hbm_bytes(devices[0])
        if limit <= 0:
            # platform doesn't report limits (CPU tests); honor the same
            # env capacity memlens reads, so CPU sweeps can model a chip
            limit = _env_hbm_bytes()
        need = hbm_bytes_required(compiled)
        if task is not None and config is not None:
            self._memlens_calibration(task, devices, config, need, k)
        if limit <= 0:
            return True
        ok = need == 0 or need <= 0.92 * limit
        if not ok:
            log.info(
                "%s: config needs %.2f GiB > %.2f GiB HBM — infeasible",
                self.name, need / 2**30, limit / 2**30,
            )
        return ok

    def _memlens_calibration(
        self, task: Any, devices: Sequence[Any], config: Dict[str, Any],
        compiled_bytes: int, k: int,
    ) -> None:
        """Best-effort static-vs-compiled comparison; never raises and
        never changes the feasibility outcome."""
        try:
            from saturn_tpu.analysis.memlens import liveness as _ml_liveness
            from saturn_tpu.analysis.memlens import passes as _ml_passes
            from saturn_tpu.utils import metrics as _metrics

            traced = self.trace_step(task, list(devices), dict(config))
            profile = _ml_liveness.analyze(traced, window=k)
            _metrics.event(
                "memlens_calibration",
                technique=self.name,
                task=getattr(task, "name", "?"),
                size=len(devices),
                k=int(k),
                predicted_bytes=int(profile.peak_bytes),
                compiled_bytes=int(compiled_bytes),
            )
            drift = _ml_passes.audit_point(
                profile.peak_bytes, compiled_bytes, self.name,
                len(devices), k=k,
            )
            if drift is not None:
                log.warning("%s", drift.message)
        except Exception as e:
            log.debug("memlens calibration skipped: %r", e)

    def _fused_ok(self, config: Dict[str, Any]) -> bool:
        """Whether THIS config may run fused windows. Pinned-host configs
        stay per-step: their step interleaves host/device memory-space moves
        (``compute_on``) that a scanned program would fold into one XLA
        program holding all K staged batches plus the host round-trips —
        exactly the residency the offload technique exists to avoid."""
        return bool(self.fused_dispatch_ok) and (
            self.param_memory_kind(config) != "pinned_host"
        )

    # ---------------------------------------------------------------- search
    def search(
        self, task: Any, devices: Sequence[Any], tid: int
    ) -> Tuple[Optional[Dict[str, Any]], Optional[float]]:
        best: Tuple[Optional[Dict[str, Any]], Optional[float]] = (None, None)
        best_hf = 0.0
        n_configs = n_memory = n_error = 0
        for config in self.candidate_configs(task, len(devices)):
            n_configs += 1
            try:
                timed = self._try_config(task, devices, config)
            except Exception as e:  # infeasible configs must not kill the sweep
                log.info("%s trial %s failed: %r", self.name, config, e)
                n_error += 1
                continue
            if timed is None:  # _try_config returns None only on the memory check
                n_memory += 1
                continue
            t, hf = timed
            if best[1] is None or t < best[1]:
                best = (dict(config), t)
                best_hf = hf
        if best[1] is not None:
            with self._reports_lock:
                self._host_fracs[(task.name, len(devices))] = best_hf
        if best[1] is None:
            # Memory is the binding constraint only when EVERY candidate was
            # rejected by XLA memory analysis — a mesh/divisibility error in
            # any config means smaller sizes might still work, so monotone
            # pruning must not engage.
            with self._reports_lock:
                self._search_reports[(task.name, len(devices))] = {
                    "memory_infeasible": n_configs > 0 and n_memory == n_configs,
                    "configs": n_configs,
                    "memory_rejected": n_memory,
                    "errors": n_error,
                }
        return best

    def _profile_window(self, config: Dict[str, Any]) -> int:
        """K the trial should profile: steady-state execute() runs full
        windows of the max size, so that is what the MILP's per-batch times
        must measure — not the per-step program fused dispatch retired."""
        return max_window() if self._fused_ok(config) else 1

    def _try_config(
        self, task: Any, devices: Sequence[Any], config: Dict[str, Any]
    ) -> Optional[Tuple[float, float]]:
        """(seconds/batch, host_fraction) for one config; None = over memory.

        The host fraction — staging cost (dataset slice + ``device_put``)
        relative to staging + device compute for one steady-state batch — is
        what the solver's co-location term consumes: a stage-bound job
        (fraction near 1) leaves the device idle most of the wall clock, so
        a compute-bound neighbor's windows can fill the bubble. The timed
        per-batch number stays device-only (the prefetcher hides staging at
        execute() time); staging is measured separately, outside the timed
        region.
        """
        bundle = self.build(task, devices, config)
        k = self._profile_window(config)
        if k > 1:
            # Profile the fused window program execute() dispatches at
            # steady state. Memory-check the SAME program (its peak holds
            # the (K, B, T) stack); pre-staged, per-call-fresh window stacks
            # keep donation honest and transfer out of the timed region —
            # at execute() time the prefetcher overlaps staging with
            # compute, so a trial that timed staging would overestimate.
            fused = bundle.fused_compiled(k)
            if not self._fits_compiled(fused, devices,
                                       task=task, config=config, k=k):
                return None
            ds = task.get_dataset()
            sharding = bundle.stacked_sharding()

            def stage(j: int):
                host = np.stack(
                    [np.asarray(ds.batch(j * k + i)) for i in range(k)]
                )
                return jax.device_put(host, sharding)

            state = bundle.init()
            t = time_fused_window(
                fused, state, stage, k, n_timed=2, n_warmup=1
            )
            t0 = _timeit.default_timer()
            probe = stage(0)
            jax.block_until_ready(probe)
            t_host = (_timeit.default_timer() - t0) / k
            del probe
            return t, _host_fraction(t_host, t)
        if not self._fits_memory(bundle, devices, task=task, config=config):
            return None
        state = bundle.init()
        t0 = _timeit.default_timer()
        batch = jax.device_put(
            task.get_dataset().batch(0), bundle.batch_sharding
        )
        jax.block_until_ready(batch)
        t_host = _timeit.default_timer() - t0
        t = time_train_step(bundle.compiled, state, batch, n_timed=3, n_warmup=2)
        return t, _host_fraction(t_host, t)

    # --------------------------------------------------------------- execute
    def execute(
        self,
        task: Any,
        devices: Sequence[Any],
        tid: int,
        override_batch_count: Optional[int] = None,
        window_size: Optional[int] = None,
    ) -> None:
        """Run one interval of ``n`` batches as an async step pipeline.

        Dispatch shape: ``n // K`` fused K-step windows (one ``lax.scan``
        program per window, single loss readback at interval end) followed
        by an ``n % K`` per-step tail on the exact legacy 1-step program —
        the same train step scanned vs called, so the loss trajectory is
        bit-identical either way. Batch staging (numpy slice + device_put)
        runs on a prefetch thread one unit ahead of the device, closing the
        host/device bubble of the old step-at-a-time loop.

        ``window_size``: the engine plumbs ``pick_window(n)`` here so K is
        chosen from the interval batch budget; ``None`` chooses locally
        (``choose_window``). K is forced to 1 for configs where fused
        dispatch is invalid (``_fused_ok``) and for n < 2 — short intervals
        never pay a window compile.

        Implemented as a full drain of ``interval_dispatches`` — the solo
        path and the co-scheduled path run the identical per-unit dispatch
        sequence, which is what makes the interleaved trajectory guarantee
        a structural property rather than a test assertion.
        """
        for _ in self.interval_dispatches(
            task, devices, tid,
            override_batch_count=override_batch_count,
            window_size=window_size,
        ):
            pass

    def interval_dispatches(
        self,
        task: Any,
        devices: Sequence[Any],
        tid: int,
        override_batch_count: Optional[int] = None,
        window_size: Optional[int] = None,
        shared: bool = False,
    ):
        """One interval as resumable per-window sub-dispatches (a generator).

        Yield protocol, in order:

        - ``("waiting", u)`` — shared mode only: unit ``u``'s staged batch is
          not ready yet. The caller (the engine's co-schedule group launcher)
          should dispatch another member's windows instead of parking here;
          resuming retries the poll.
        - ``("dispatched", u)`` — unit ``u``'s device program was enqueued
          (dispatch is async; the device may still be running it).
        - ``("drain", n_units)`` — every unit has been dispatched. Resuming
          past this performs the blocking finalization (loss readback,
          realized feedback, checkpoint write, live-state republish) and
          ends the generator.

        ``shared=True`` is co-schedule mode: staging is polled non-blockingly
        (``DevicePrefetcher.try_next``), the first-unit warmup fence is
        skipped, and per-task realized feedback / samples-per-sec are left to
        the caller's group wall-time attribution — the device-side dispatch
        ORDER is exactly the solo path's, so each member's loss/checkpoint
        trajectory is bit-identical to running alone.
        """
        config = dict(task.selected_strategy.params or {})
        bundle = self.build(task, devices, config)
        key = self._bundle_key(task, devices, config)

        live = getattr(task, "_live_state", None)
        if live is not None and live[0] == key:
            # Same technique/config/block as the previous interval: the
            # device-resident state is still authoritative — skip the
            # disk round-trip (the ckpt is only needed when the solver
            # *switches* technique or block between intervals).
            state = live[1]
        elif task.has_ckpt():
            # Resume — map saved shards directly onto THIS technique's
            # shardings (cross-technique resharding; the reference's
            # kill-and-respawn reload, ``FSDP.py:189-191``). restore_sharded
            # assembles each leaf lazily per destination shard from the
            # manifest, so resume never materializes a full replicated host
            # tree (and legacy single-file checkpoints take its compat path).
            from saturn_tpu.core import distributed as _dist

            state = ckpt.restore_sharded(
                task.ckpt_path, bundle.state_shapes, bundle.state_shardings
            )
            # Data cursor is derived from the trained-step count, so resume
            # is restart-safe (the reference replayed the iterator from the
            # in-memory cursor only, ``Task.py:130-140``).
            # cursor_for_step folds the quarantine skip-list into the
            # modulus, so a restore after quarantine replay lands on the
            # surviving sequence.
            step_leaf = state["step"]
            task.current_batch = task.cursor_for_step(
                int(np.asarray(_dist.host_array(step_leaf)))
            )
        else:
            state = bundle.init()

        # The cached buffers get donated into the first step below, so they
        # must not be offered again if this interval crashes mid-run: drop
        # the cache now and re-publish after the end-of-interval checkpoint.
        task._live_state = None

        n = override_batch_count
        if n is None:
            n = task.total_batches
        n = int(n)

        from saturn_tpu.core import distributed as _dist
        from saturn_tpu.data.prefetch import NOT_READY, DevicePrefetcher

        start = task.current_batch

        # -------- window plan: n_windows fused units + per-step tail units
        k = choose_window(n) if window_size is None else int(window_size)
        k = max(1, min(k, max(n, 1)))
        if k > 1 and not self._fused_ok(config):
            k = 1
        n_windows = n // k if k > 1 else 0
        # unit = (is_fused, batch offset within the interval)
        units: List[Tuple[bool, int]] = [(True, w * k) for w in range(n_windows)]
        units += [(False, j) for j in range(n_windows * k, n)]

        # Whether the program the FIRST unit runs had already compiled: if
        # so, even an n==1 interval yields a clean compile-free sample (a
        # task forecast at one batch per interval must not be starved of
        # feedback forever — its wrong trial profile is exactly what the
        # feedback exists to fix).
        first_fused = bool(units) and units[0][0]
        was_warm = (
            bundle.has_fused(k) if first_fused else bundle._compiled is not None
        )
        # AOT-compile every program this interval needs BEFORE the clock
        # starts — compile cost belongs to neither samples/sec nor the
        # realized-feedback window (docs/parity.md, round 10).
        fused_fn = bundle.fused_compiled(k) if n_windows else None
        single_fn = (
            bundle.compiled if any(not f for f, _ in units) else None
        )
        stacked_sharding = bundle.stacked_sharding() if n_windows else None

        def stage(u: int):
            fused_u, off = units[u]
            if fused_u:
                host = np.stack([
                    np.asarray(task.batch_at(start + off + j)) for j in range(k)
                ])
                return _dist.put_global(host, stacked_sharding)
            # put_global == device_put single-process; on a multi-host
            # block each process's devices take their slice locally
            return _dist.put_global(
                task.batch_at(start + off), bundle.batch_sharding
            )

        loss = None
        # Every unit's carried loss stays on-device for the sentinel's
        # interval-end fold (tiny buffers: one scalar / (K,) per unit).
        unit_losses: List[Any] = []
        t_all0 = _timeit.default_timer()
        t_steady = t_all0
        # Batch staging runs one unit ahead on the prefetch thread; the
        # loop body only dispatches device programs.
        prefetch = DevicePrefetcher(len(units), stage, depth=2)
        try:
            u = 0
            while u < len(units):
                if shared:
                    try:
                        dev_batch = prefetch.try_next()
                    except StopIteration:
                        break
                    if dev_batch is NOT_READY:
                        yield ("waiting", u)
                        continue
                else:
                    try:
                        dev_batch = next(prefetch)
                    except StopIteration:
                        break
                if units[u][0]:
                    state, loss = fused_fn(state, dev_batch)  # loss: (K,)
                else:
                    state, loss = single_fn(state, dev_batch)
                unit_losses.append(loss)
                if u == 0 and len(units) > 1 and not shared:
                    # The first unit still pays one-time warmup (executable
                    # load, constant transfer) plus the un-overlapped first
                    # staging. Keep it out of the realized-feedback window:
                    # block on its result and restart the steady-state timer.
                    # (Shared mode skips the fence — blocking here would
                    # stall the group launcher; the group owns timing.)
                    jax.block_until_ready(loss)  # lint: sanctioned-host-sync
                    t_steady = _timeit.default_timer()
                yield ("dispatched", u)
                u += 1
            # All device work for this member is enqueued. The caller may
            # resume other members before paying this member's blocking
            # finalization below.
            yield ("drain", len(units))
        finally:
            # SimulatedKill is a BaseException: a killed interval must not
            # leak a producer thread that keeps slicing batches from a task
            # the harness is rolling back.
            prefetch.close()
        if loss is not None:
            from saturn_tpu.health import sentinel as _sentinel
            from saturn_tpu.utils import metrics as _metrics

            scfg = _sentinel.get_config()
            poison = task.__dict__.pop("_health_poison", None)
            rep = None
            if scfg.enabled:
                import jax.numpy as jnp

                # Sentinel path: fold the interval's full per-step loss
                # vector through one jitted on-device scan and read back the
                # fixed-shape report instead of the bare scalar — STILL one
                # host readback per interval (the reliable queue drain, see
                # utils/timing.py note), and the report's last slot is the
                # same final loss the bare readback returned.
                losses_vec = jnp.concatenate(
                    [jnp.reshape(x, (-1,)) for x in unit_losses]
                )
                if poison is not None:
                    ov = _sentinel.poison_overrides(
                        poison, n, lambda j: task.dataset_index(start + j)
                    )
                    if ov is not None:
                        # Chaos injection corrupts the OBSERVED losses only
                        # (a device-side scatter); train state is untouched,
                        # so post-rollback trajectories stay fault-free.
                        losses_vec = losses_vec.at[ov[0]].set(ov[1])
                carry = getattr(task, "_sentinel_carry", None)
                if carry is None:
                    carry = _sentinel.carry_init()
                rep = np.asarray(
                    _dist.host_array(_sentinel.fold(carry, losses_vec, scfg))
                )
                loss_val = float(rep[_sentinel.REP_LAST_LOSS])
            else:
                # ONE host readback per interval — the reliable queue drain
                # (see utils/timing.py note). A fused window's loss is the
                # (K,) per-step trajectory; its last entry is the interval's
                # final loss, identical to what the 1-step path would report.
                loss_val = float(_dist.host_array(loss).reshape(-1)[-1])
            fault = _sentinel.inspect(rep) if rep is not None else None
            if fault is not None:
                cause, first_off, bad_count = fault
                fused_part = n_windows * k
                if first_off < fused_part:
                    window = first_off // k
                else:
                    window = n_windows + (first_off - fused_part)
                # Fault path (cold): pull the observed vector and blame the
                # exact bad steps. Quarantine resolution must be per batch —
                # blaming the whole K-step window would skip-list healthy
                # data (and with K == epoch length, the entire dataset). A
                # finite spike is only locatable via the report's first-bad
                # slot; non-finite steps are all recoverable host-side.
                host_losses = np.asarray(
                    _dist.host_array(losses_vec)
                ).reshape(-1)
                bad_offsets = {
                    int(j) for j in np.flatnonzero(~np.isfinite(host_losses))
                }
                if first_off >= 0:
                    bad_offsets.add(int(first_off))
                bad_batches = tuple(sorted(
                    {task.dataset_index(start + j) for j in bad_offsets}
                ))
                _metrics.event(
                    "task_numeric_fault", task=task.name, cause=cause,
                    window=window, step=first_off, bad_count=bad_count,
                    batches=list(bad_batches),
                )
                log.warning(
                    "task %s: sentinel tripped (%s) at interval step %d "
                    "(window %d, %d bad step(s)) — discarding interval",
                    task.name, cause, first_off, window, bad_count,
                )
                # Raised BEFORE realized feedback, the checkpoint write and
                # the live-state republish: a faulted interval never becomes
                # durable state, and the engine only advances the cursor
                # (task.reconfigure) on success — so the last published
                # checkpoint is the exact rollback target.
                raise _sentinel.NumericFaultError(
                    task.name, window, cause, step=first_off,
                    loss=loss_val, batch_indices=bad_batches,
                    bad_count=bad_count,
                )
            if rep is not None:
                # Only a healthy interval advances the persisted EWMA carry;
                # a faulted one discards it with the rest of its state.
                task._sentinel_carry = rep[:2].copy()
            t_end = _timeit.default_timer()
            elapsed_all = t_end - t_all0
            bs = task.get_dataset().batch_size
            sps = n * bs / max(elapsed_all, 1e-9)
            first_unit_batches = k if first_fused else 1
            if shared:
                # Co-scheduled: this member's wall clock includes the
                # interleaved neighbors' device windows, so neither
                # samples/sec nor realized per-batch feedback can be read
                # off it here — the group launcher attributes the group's
                # wall time across members (``engine.py``).
                per_batch = elapsed_all / max(n, 1)
            elif len(units) > 1:
                # per-job samples/sec — the BASELINE.md per-job metric — and
                # the realized per-batch time (vs the profiled estimate
                # forecast used).
                task.last_samples_per_sec = sps
                # feed the profiled-vs-realized loop from the steady-state
                # window only (units 2..); a warmup-dominated first unit
                # would otherwise inflate the EWMA and propagate to every
                # sibling strategy. Window-granular: the divisor is the
                # batch count the timed units actually retired.
                per_batch = (t_end - t_steady) / max(n - first_unit_batches, 1)
                task.note_realized_per_batch(per_batch)
            else:
                task.last_samples_per_sec = sps
                per_batch = elapsed_all / max(n, 1)
                if was_warm:
                    # single-unit interval on an already-compiled program:
                    # still a clean sample — without it a task scheduled one
                    # batch per interval never gets corrected.
                    task.note_realized_per_batch(per_batch)
            # Achieved TFLOP/s + MFU for this interval: shardflow's static
            # per-step FLOP count (cached per compiled program) over the
            # measured window wall time, normalized by the block's aggregate
            # peak. Self-reports every run against the prior's 0.45 MFU
            # target without a bench run; omitted when the step can't be
            # traced (fields are additive, consumers treat them as optional).
            perf = {}
            if _metrics.enabled():
                step_flops = self._step_flops(task, devices, config)
                if step_flops:
                    from saturn_tpu.analysis.shardflow.prior import (
                        hardware_model,
                    )

                    achieved = step_flops * n / max(elapsed_all, 1e-9)
                    peak = hardware_model()["peak_flops"]
                    perf["tflops"] = round(achieved / 1e12, 4)
                    perf["mfu"] = round(
                        achieved / (max(len(devices), 1) * peak), 6
                    )
            _metrics.event(
                "task_interval", task=task.name, technique=self.name,
                batches=n, loss=loss_val, samples_per_sec=round(sps, 2),
                per_batch_s=per_batch, window=k, fused_windows=n_windows,
                coscheduled=bool(shared), **perf,
            )
            log.info("task %s [%s]: ran %d batches (K=%d, %d fused windows), "
                     "loss %.4f, %.1f samples/s",
                     task.name, self.name, n, k, n_windows, loss_val, sps)

        # Full train-state checkpoint (params + opt state + step): fixes the
        # reference's dropped-optimizer wart (``FSDP.py:220``). The disk write
        # overlaps the next interval (device->host copy happens here; see
        # utils/checkpoint.save_async) — interval boundaries don't stall the
        # gang on GB-scale npz writes.
        ckpt.save_async(task.ckpt_path, state)
        task._live_state = (key, state)
