"""PartitionSpec rule engines: parallelism as sharding annotations.

The reference implemented each parallelism as a wrapper class (torch FSDP
``FSDP.py:111-118``, GPipe ``Pipeline.py:36-39``, OffloadModel
``Spilled.py:46``). The GSPMD-native equivalent (SURVEY.md §2.2) is a function
from *param tree path + shape* to a ``PartitionSpec`` — XLA inserts the
all-gathers / reduce-scatters / all-reduces that NCCL wrappers did manually.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Tuple

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from saturn_tpu.utils.treepath import path_str as _path_str


def replicated_rules(path: str, shape: Tuple[int, ...], mesh_axes) -> P:
    """DP: params replicated on every device; only the batch is sharded."""
    return P()


def fsdp_rules(axis: str = "data", min_size: int = 1024):
    """ZeRO-3-style rules: shard each param's largest dimension over ``axis``.

    Equivalent to torch-FSDP flat-param sharding (``FSDP.py:111-118``) but
    declarative: XLA emits the all-gather before use and reduce-scatter on
    grads. Small params (< min_size elements) stay replicated — sharding them
    costs more in collective latency than it saves in HBM.
    """

    def rules(path: str, shape: Tuple[int, ...], mesh_axes) -> P:
        n_shard = mesh_axes[axis]
        if int(np.prod(shape)) < min_size or not shape:
            return P()
        # Largest dim divisible by the axis size; prefer later dims on ties
        # (later dims of a scanned stack are the weight matrix dims).
        best, best_size = None, -1
        for i, s in enumerate(shape):
            if s % n_shard == 0 and s >= best_size:
                best, best_size = i, s
        if best is None:
            return P()
        spec = [None] * len(shape)
        spec[best] = axis
        return P(*spec)

    return rules


def tensor_parallel_rules(axis: str = "model"):
    """Megatron-style rules for the GPT-2 param tree (``models/gpt2.py``).

    Column-parallel: qkv and mlp_in kernels (shard output dim) — their
    activation outputs are sharded over heads/ff; row-parallel: attn_out and
    mlp_out kernels (shard input dim) — XLA inserts the psum on their output.
    Embeddings shard over vocab; XLA handles the gather + logits psum.
    Fills the reference's declared-but-unimplemented MEGATRON slot
    (``Strategy.py:34``).
    """

    col = re.compile(r"(qkv|mlp_in|mlp_gate)/kernel$")
    row = re.compile(r"(attn_out|mlp_out)/kernel$")
    colb = re.compile(r"(qkv|mlp_in|mlp_gate)/bias$")
    # Paths are full state paths ('params/wte', 'opt_state/0/mu/wte', ...),
    # so anchor on a path segment, not the whole string.
    vocab = re.compile(r"(^|/)wte$")

    def rules(path: str, shape: Tuple[int, ...], mesh_axes) -> P:
        n_shard = mesh_axes[axis]
        spec = [None] * len(shape)
        if col.search(path) and shape[-1] % n_shard == 0:
            spec[-1] = axis
        elif row.search(path) and shape[-2] % n_shard == 0:
            spec[-2] = axis
        elif colb.search(path) and shape[-1] % n_shard == 0:
            spec[-1] = axis
        elif vocab.search(path) and shape[0] % n_shard == 0:
            spec[0] = axis
        return P(*spec)

    return rules


def compose_rules(*rule_fns):
    """Merge rule functions; later rules fill axes earlier ones left None.

    Lets FSDP compose with TP (2-D mesh: params sharded over both 'model'
    and 'data') without either rule knowing about the other.
    """

    def rules(path: str, shape: Tuple[int, ...], mesh_axes) -> P:
        spec = [None] * len(shape)
        used_axes = set()
        for fn in rule_fns:
            sub = fn(path, shape, mesh_axes)
            for i, a in enumerate(tuple(sub)):
                if a is not None and spec[i] is None and a not in used_axes:
                    spec[i] = a
                    used_axes.add(a)
        return P(*spec)

    return rules


def pspec_tree(params_shapes: Any, rules: Callable, mesh) -> Any:
    """Apply a rule function over an abstract params tree -> PartitionSpec tree.

    Every emitted spec passes the static sharding lint
    (``analysis.jax_lint.enforce_pspec``): an unknown mesh axis or a spec
    longer than the tensor's rank raises ``ShardingLintError`` with the rule
    function's ``file:line`` here, on CPU — not as a GSPMD compile failure
    on the chips."""
    from saturn_tpu.analysis import jax_lint as _jlint

    mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape))

    def one(path, leaf):
        spec = rules(_path_str(path), tuple(leaf.shape), mesh_axes)
        _jlint.enforce_pspec(spec, tuple(leaf.shape), mesh_axes,
                             path=_path_str(path), rules=rules)
        return spec

    return jax.tree_util.tree_map_with_path(one, params_shapes)


def sharding_tree(params_shapes: Any, rules: Callable, mesh, memory_kind=None) -> Any:
    """PartitionSpec tree -> NamedSharding tree over ``mesh``."""
    from jax.sharding import NamedSharding

    specs = pspec_tree(params_shapes, rules, mesh)

    def mk(spec):
        if memory_kind is not None:
            return NamedSharding(mesh, spec, memory_kind=memory_kind)
        return NamedSharding(mesh, spec)

    return jax.tree.map(mk, specs, is_leaf=lambda x: isinstance(x, P))
