"""Kill-replay crash harness: deterministic simulated SIGKILLs at journal
barriers.

PR 2's :mod:`~saturn_tpu.resilience.faults` injects *fleet* failures (slice
preemptions, stragglers); this module injects *controller* death. A real
SIGKILL gives the process no chance to flush buffers, run handlers or close
files — the simulation honors that contract exactly:

- :class:`SimulatedKill` derives from ``BaseException`` so no ordinary
  ``except Exception`` cleanup path can intercept it; the service loop
  treats it as process death (no job fail-out, no journal flush, no
  graceful drain — memory state simply stops existing).
- The kill fires at a named durability **barrier** (see
  ``durability.journal.Journal.barrier``): ``pre-commit`` (buffered records
  die unwritten), ``mid-fsync`` (bytes written but not fsync'd — the
  injector *tears the tail of the write* to model the lost page cache, so
  recovery genuinely exercises the torn-record quarantine), ``post-commit``
  (durable cut advanced, everything after dies), ``pre-rotate`` /
  ``post-rename`` (segment-rotation edges), plus the service loop's own
  ``mid-interval`` (work executed, progress not yet durable) and
  ``post-checkpoint`` (progress + checkpoint publication both durable).
- Kill-points are deterministic: ``CrashInjector("mid-fsync", hit=2)``
  fires on exactly the second armed crossing of that barrier;
  :meth:`CrashInjector.seeded` derives (point, hit) from a seed for chaos
  sweeps that never flake.

The restart-and-assert half lives in ``tests/test_crash.py``: run a service
against a durability dir, kill it, build a fresh service on the same dir,
and assert no admitted job is lost, no durably completed iteration re-runs
(journal sequence numbers are the evidence), and corrupt trailing artifacts
are quarantined rather than fatal.
"""

from __future__ import annotations

import logging
import os
import random
import threading
from typing import Dict, Optional, Sequence

logger = logging.getLogger("saturn_tpu")

#: Every barrier a kill can target. The first five are crossed inside
#: ``Journal.commit``/rotation; the next two are service-loop cuts;
#: ``post-rollback`` is crossed by the health guardian's recovery path right
#: after a faulted task was rolled back (its quarantine/detach records are
#: already durable — the chaos campaign kills here to prove replay restores
#: them). The last two are the sharded checkpoint writer's commit edges
#: (``utils/checkpoint.set_crash_barrier``): ``mid-shard-write`` — shard
#: bytes staged, the shard rename not yet done — and ``pre-manifest-rename``
#: — every shard durable, the manifest (the commit point) not yet renamed.
#: A kill at either must leave the previously published generation fully
#: restorable. ``fused.unfuse`` is the unfuse transition of a fused stack
#: (``parallel/fused.run_fused_interval``): crossed AFTER a detaching
#: member's state is sliced out of the stack but BEFORE its checkpoint
#: lands — a kill here leaves nothing durable from the interval, so replay
#: re-runs it bit-identically and unfuses at the same boundary exactly once.
KILL_POINTS = (
    "pre-commit",
    "mid-fsync",
    "post-commit",
    "pre-rotate",
    "post-rename",
    "mid-interval",
    "post-checkpoint",
    "post-rollback",
    "mid-shard-write",
    "pre-manifest-rename",
    "fused.unfuse",
    # Defrag-wave migration two-phase points (resilience/grow.py): crossed
    # between a move's ``migration_intent`` and ``migration_done`` journal
    # records. ``defrag.pre-publish`` = intent durable, destination
    # checkpoint not yet published (replay rolls the move back);
    # ``defrag.pre-commit`` = checkpoint published, done record buffered but
    # not fsynced (replay resumes the move from the published checkpoint);
    # ``defrag.post-commit`` = done record durable (replay is a no-op).
    # Each outcome must land exactly once with the iteration ledger intact.
    "defrag.pre-publish",
    "defrag.pre-commit",
    "defrag.post-commit",
)


class SimulatedKill(BaseException):
    """The process 'died' at a durability barrier. BaseException on purpose:
    SIGKILL runs no handlers, so no ``except Exception`` may catch this."""


class CrashInjector:
    """Raises one :class:`SimulatedKill` at the Nth armed crossing of a
    barrier. Pass ``barrier`` as the journal's callback::

        inj = CrashInjector("mid-fsync", hit=2, armed=False)
        svc = SaturnService(..., durability_dir=d, crash_barrier=inj.barrier)
        ...submit work...
        inj.arm()
        assert inj.fired.wait(timeout=30)

    ``armed=False`` lets a test finish its setup (submissions commit through
    the same barriers) before the kill window opens. After firing once the
    injector is inert — the process is dead; later crossings (e.g. from a
    launcher thread still unwinding) pass through.
    """

    def __init__(self, point: str, hit: int = 1, armed: bool = True,
                 tear_bytes: int = 7):
        if point not in KILL_POINTS:
            raise ValueError(
                f"unknown kill-point {point!r}; use one of {KILL_POINTS}"
            )
        if hit < 1:
            raise ValueError("hit is 1-based")
        self.point = point
        self.hit = hit
        self.tear_bytes = tear_bytes
        self.fired = threading.Event()
        self._armed = threading.Event()
        if armed:
            self._armed.set()
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    @classmethod
    def seeded(cls, seed: int, max_hit: int = 3,
               points: Sequence[str] = KILL_POINTS, **kw) -> "CrashInjector":
        """Deterministic (point, hit) choice from a seed — the chaos-sweep
        constructor: same seed, same kill, every run."""
        rng = random.Random(seed)
        return cls(rng.choice(list(points)), hit=rng.randint(1, max_hit), **kw)

    def arm(self) -> None:
        self._armed.set()

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def barrier(self, point: str, ctx: Dict) -> None:
        """Journal/service barrier callback. Counts armed crossings; on the
        configured one, optionally tears the in-flight write, then raises."""
        if self.fired.is_set() or not self._armed.is_set():
            return
        with self._lock:
            if self.fired.is_set():
                return
            self._counts[point] = self._counts.get(point, 0) + 1
            if point != self.point or self._counts[point] != self.hit:
                return
            if point == "mid-fsync":
                self._tear(ctx)
            self.fired.set()
        logger.warning(
            "crash harness: simulated SIGKILL at %s (hit %d)",
            point, self.hit,
        )
        raise SimulatedKill(f"simulated SIGKILL at {point} (hit {self.hit})")

    def _tear(self, ctx: Dict) -> None:
        """Model the page cache losing the un-fsync'd tail: truncate the
        just-written bytes mid-record, leaving a genuinely torn trailing
        line for recovery to quarantine."""
        path, start, end = ctx.get("path"), ctx.get("start"), ctx.get("end")
        if not path or start is None or end is None:
            return
        cut = max(start, end - self.tear_bytes)
        if cut >= end:  # nothing written this commit: whole batch vanishes
            cut = start
        try:
            os.truncate(path, cut)
        except OSError:
            logger.exception("crash harness: tear of %s failed", path)


def run_to_kill(injector: CrashInjector, service, timeout: float = 60.0) -> None:
    """Arm the injector, wait for the kill to land, and join the dead
    service loop thread. Raises ``TimeoutError`` if the kill never fires —
    a harness misconfiguration (wrong point/hit), not a product failure."""
    injector.arm()
    if not injector.fired.wait(timeout):
        raise TimeoutError(
            f"kill at {injector.point!r} (hit {injector.hit}) never fired "
            f"within {timeout}s; barrier crossings so far: "
            f"{injector.counts()}"
        )
    thread = getattr(service, "_thread", None)
    if thread is not None:
        thread.join(timeout)
        if thread.is_alive():
            raise TimeoutError("service loop thread outlived its kill")
