"""Elastic scale-up: recovery half of the elastic path (grow + defrag).

The shrink half (PR 2's :class:`~saturn_tpu.resilience.replan.
ElasticReplanner`) degrades gracefully — evict, degrade, pause. This module
makes the fleet recover *aggressively*: on every ``grow``
:class:`~saturn_tpu.resilience.health.TopologyChange` (and on a periodic
opportunistic poll), the :class:`GrowCoordinator`

1. journals a durable ``grow_event`` record (operator view:
   ``python -m saturn_tpu.analysis grow``),
2. short-circuits guardian backoff benches (``unbench_all`` — the fault
   streak ledger stays intact) so parked work restarts *this* interval,
3. exposes the DEFER backlog so the caller's re-solve spans
   live ∪ deferred ∪ parked jobs, journaling a ``backlog_drain`` record
   when previously-deferred work admits, and
4. when a deferred gang *still* can't fit — the schedule has room but
   other tasks' device-resident live state pins too much HBM — plans a
   **defragmentation wave** (:func:`~saturn_tpu.resilience.replan.
   plan_defrag_wave`) and executes it move by move through the existing
   checkpoint-migration path.

Every move is journaled two-phase: a durable ``migration_intent`` before
any state changes, a ``migration_done`` after the victim's checkpoint is
verified durable and its live state released. A kill mid-wave therefore
resolves exactly-once on replay: intent + a later ``ckpt_published`` ⇒
resume (the state safely landed; recovery closes the move as done);
intent alone ⇒ roll back (nothing was released that a fresh restore from
the last checkpoint doesn't cover). Kill-points ``defrag.pre-publish`` /
``defrag.pre-commit`` / ``defrag.post-commit`` arm the crash harness
between the phases.

Saturn itself (arXiv 2311.02840) re-solves on its introspection interval
but never *re-expands* — a preempted resource stays lost to the batch.
This subsystem is the parity delta: the DEFER pool stops being a waiting
room and becomes a backlog the system actively drains.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

from saturn_tpu.resilience.replan import DefragWave, plan_defrag_wave
from saturn_tpu.utils import metrics

log = logging.getLogger("saturn_tpu")

#: Env knob: run the opportunistic defrag poll every N intervals even
#: without a grow event (0 disables the periodic poll).
ENV_GROW_POLL = "SATURN_TPU_GROW_POLL"
DEFAULT_GROW_POLL = 8


def default_resident_bytes(task: Any) -> int:
    """Per-device bytes a task's live state pins between intervals.

    Convention mirrors memlens: unknown ⇒ 0 ⇒ the occupancy gate fails
    open. Tasks (and tests/benches) can declare the figure via a
    ``resident_bytes`` attribute or hint; a task with no device-resident
    live state pins nothing regardless.
    """
    if getattr(task, "_live_state", "absent") is None:
        return 0
    v = getattr(task, "resident_bytes", None)
    if v is None:
        hints = getattr(task, "hints", None)
        if isinstance(hints, dict):
            v = hints.get("resident_bytes")
    try:
        return max(0, int(v or 0))
    except (TypeError, ValueError):
        return 0


class GrowCoordinator:
    """Drives grow-event recovery for one control loop (orchestrator,
    service, or twin). Single-threaded by design — only the owning loop
    calls it, mirroring :class:`~saturn_tpu.service.admission.
    AdmissionController`."""

    def __init__(
        self,
        journal: Any = None,
        poll_every: Optional[int] = None,
        resident_bytes: Callable[[Any], int] = default_resident_bytes,
        cap_bytes: Optional[int] = None,
    ):
        self.journal = journal
        if poll_every is None:
            poll_every = int(os.environ.get(ENV_GROW_POLL, DEFAULT_GROW_POLL))
        self.poll_every = max(0, poll_every)
        self.resident_bytes = resident_bytes
        self._cap_bytes = cap_bytes
        self._wave_seq = 0
        self._last_grow_interval: Optional[int] = None

    def seed_wave_seq(self, past: int) -> None:
        """Advance the wave sequence past a recovered journal's highest
        (``ServiceRecovery.defrag_waves``) so ids stay unique across
        incarnations — the interval half of the id restarts from zero."""
        # sanctioned-unlocked: coordinator is single-threaded by design —
        # seeded once during recovery, before the owning loop starts
        self._wave_seq = max(self._wave_seq, int(past))

    # ------------------------------------------------------------- capacity
    def _capacity_bytes(self, topology) -> int:
        if self._cap_bytes is not None:
            return self._cap_bytes
        try:
            from saturn_tpu.analysis.memlens import passes as ml_passes
            return ml_passes.hbm_capacity_bytes(topology.devices)
        except Exception:
            return 0

    # ----------------------------------------------------------- grow event
    def note_grow(self, change, interval_index: int, *, guardian=None,
                  n_deferred: int = 0, n_parked: int = 0,
                  capacity: int = 0) -> List[str]:
        """Record a surfaced grow event and short-circuit every guardian
        bench. Returns the names released from backoff (streak ledgers
        untouched — see ``FleetGuardian.unbench_all``)."""
        self._last_grow_interval = interval_index
        released: List[str] = []
        if guardian is not None and hasattr(guardian, "unbench_all"):
            released = list(guardian.unbench_all(cause="grow"))
        n_parked = max(n_parked, len(released))
        if self.journal is not None:
            self.journal.log(
                "grow_event", interval=interval_index,
                gained=list(getattr(change, "gained", ()) or ()),
                cause=getattr(change, "cause", ""),
                capacity=capacity, n_deferred=n_deferred,
                n_parked=n_parked, unbenched=released,
            )
        metrics.event(
            "grow_event", interval=interval_index,
            gained=list(getattr(change, "gained", ()) or ()),
            n_deferred=n_deferred, n_parked=n_parked, unbenched=released,
        )
        return released

    def note_drained(self, jobs: Sequence[str], interval_index: int,
                     trigger: str = "grow") -> None:
        """Journal that previously-deferred jobs admitted this interval."""
        if not jobs:
            return
        if self.journal is not None:
            # log (durable now), not append: the drained jobs are already
            # ADMITted by the time this records, so a crash before the next
            # group commit would silently drop the drain attribution the
            # recovered backlog_drained counter and operator views rely on.
            self.journal.log(
                "backlog_drain", interval=interval_index,
                jobs=sorted(jobs), trigger=trigger,
            )
        metrics.event(
            "backlog_drain", interval=interval_index,
            jobs=sorted(jobs), trigger=trigger,
        )

    # -------------------------------------------------------------- polling
    def defrag_due(self, interval_index: int, grew: bool) -> bool:
        """Should this interval attempt a defrag wave? On every grow, and
        opportunistically every ``poll_every`` intervals (a completion may
        have freed HBM without any topology change)."""
        if grew:
            return True
        return self.poll_every > 0 and interval_index > 0 and (
            interval_index % self.poll_every == 0
        )

    # ------------------------------------------------------- occupancy gate
    def occupancy_gate(
        self,
        live_tasks: Callable[[], Sequence],
        current_plan: Callable[[], Any],
        ) -> Callable:
        """Build the admission occupancy gate (see ``AdmissionController.
        occupancy_gate``): verdict on whether an arrival's HBM footprint
        fits around the pinned live state of running tasks. Fail-open
        everywhere information is missing."""

        def gate(task, topology) -> Optional[dict]:
            cap = self._capacity_bytes(topology)
            if cap <= 0:
                return None
            plan = current_plan()
            if plan is None:
                return None
            occ: Dict[int, int] = {}
            for t in live_tasks():
                if t.name == getattr(task, "name", None):
                    continue
                b = self.resident_bytes(t)
                a = plan.assignments.get(t.name)
                if b <= 0 or a is None:
                    continue
                for i in range(a.block.offset, a.block.end):
                    occ[i] = occ.get(i, 0) + b
            if not occ:
                return None  # nothing pinned: occupancy cannot block
            # ``need`` is per-apportionment: a smaller gang shards state
            # over fewer devices and needs MORE bytes per device, so the
            # fit check must price each candidate size on its own — a
            # single largest-gang estimate would under-admit straight into
            # the OOM this gate exists to prevent.
            best = None  # (free, need) of the closest-to-fitting attempt
            for g in sorted(
                    (g for g in task.feasible_strategies()
                     if g <= topology.capacity), reverse=True):
                need = self._need_bytes(task, topology, cap, size=g)
                if need <= 0:
                    return None  # no estimate for this size: fail open
                for blk in topology.blocks(g):
                    used = max(
                        occ.get(i, 0) for i in range(blk.offset, blk.end)
                    )
                    free = cap - used
                    if free >= need:
                        return {"fits": True, "free_bytes": free,
                                "need_bytes": need}
                    if best is None or need - free < best[1] - best[0]:
                        best = (free, need)
            if best is None:
                return None  # no candidate placements: nothing to verdict
            return {"fits": False, "free_bytes": best[0],
                    "need_bytes": best[1]}

        return gate

    def _need_bytes(self, task, topology, cap: int,
                    size: Optional[int] = None) -> int:
        """Per-device HBM bytes the task needs at gang size ``size`` (or
        the largest feasible size when unspecified). memlens prices the
        exact apportionment; the task's own resident-bytes hint is the
        fail-open fallback."""
        try:
            from saturn_tpu.analysis.memlens import passes as ml_passes
            if size is not None:
                sizes = [size]
            else:
                sizes = sorted(
                    (g for g in task.feasible_strategies()
                     if g <= topology.capacity), reverse=True)
            for g in sizes:
                fit = ml_passes.migration_fits(task, topology, g, cap)
                if fit is not None:
                    return int(fit["peak_bytes"])
        except Exception:
            pass
        return self.resident_bytes(task)

    # ---------------------------------------------------------- defrag wave
    def plan_wave(self, blocked_tasks: Sequence, live_tasks: Sequence,
                  topology, previous_plan) -> DefragWave:
        return plan_defrag_wave(
            blocked_tasks, live_tasks, topology, previous_plan,
            self.resident_bytes, cap_bytes=self._capacity_bytes(topology),
        )

    def execute_wave(
        self,
        wave: DefragWave,
        tasks_by_name: Dict[str, Any],
        interval_index: int,
        publish_fn: Optional[Callable[[Any], bool]] = None,
        release_fn: Optional[Callable[[Any], None]] = None,
    ) -> Optional[str]:
        """Execute a planned wave move by move with two-phase journaling.

        Per move: durable ``migration_intent`` → ``defrag.pre-publish``
        barrier → ``publish_fn(task)`` verifies (or forces) the victim's
        checkpoint durable, journaling ``ckpt_published`` — a False return
        rolls the move back without touching state → release the victim's
        device-resident live state → ``defrag.pre-commit`` barrier →
        ``migration_done`` group-committed → ``defrag.post-commit``
        barrier. Recovery closes any intent that lacks a done/rollback
        (see ``durability/recovery.py``): resume iff a ``ckpt_published``
        landed after the intent, else roll back — each exactly once.

        Returns the wave id (None when the wave was empty).
        """
        if wave.empty:
            return None
        # sanctioned-unlocked: coordinator is single-threaded by design —
        # only the owning control loop executes waves (see class docstring)
        self._wave_seq += 1
        wave_id = f"wave-{interval_index}-{self._wave_seq}"
        jnl = self.journal
        moved: List[str] = []
        rolled_back: List[str] = []
        for move in wave.moves:
            task = tasks_by_name.get(move.task)
            if task is None:
                continue
            if jnl is not None:
                jnl.log(
                    "migration_intent", wave=wave_id,
                    interval=interval_index, **move.to_fields(),
                )
                jnl.barrier("defrag.pre-publish", wave=wave_id,
                            task=move.task)
            ok = True
            if publish_fn is not None:
                try:
                    ok = bool(publish_fn(task))
                except Exception as e:
                    log.warning("defrag: publish failed for %s: %r",
                                move.task, e)
                    ok = False
            if not ok:
                rolled_back.append(move.task)
                if jnl is not None:
                    jnl.log(
                        "migration_rollback", wave=wave_id, task=move.task,
                        cause="publish-failed",
                    )
                continue
            if release_fn is not None:
                release_fn(task)
            else:
                release = getattr(task, "release_live_state", None)
                if callable(release):
                    release()
            if jnl is not None:
                jnl.barrier("defrag.pre-commit", wave=wave_id,
                            task=move.task)
                jnl.append(
                    "migration_done", wave=wave_id, task=move.task,
                    interval=interval_index,
                )
                jnl.commit()
                jnl.barrier("defrag.post-commit", wave=wave_id,
                            task=move.task)
            moved.append(move.task)
        if jnl is not None:
            jnl.log(
                "defrag_wave", wave=wave_id, interval=interval_index,
                moves=moved, rolled_back=rolled_back,
                admitted={k: list(v) for k, v in sorted(
                    wave.admitted.items())},
                still_blocked=sorted(wave.still_blocked),
            )
        metrics.event(
            "defrag_wave", wave=wave_id, interval=interval_index,
            moves=moved, rolled_back=rolled_back,
            admitted=sorted(wave.admitted),
            still_blocked=sorted(wave.still_blocked),
        )
        log.info(
            "defrag: wave %s moved %d task(s), unblocked %d gang(s)%s",
            wave_id, len(moved), len(wave.admitted),
            f", {len(wave.still_blocked)} still blocked"
            if wave.still_blocked else "",
        )
        return wave_id
