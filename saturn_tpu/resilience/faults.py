"""Deterministic fault injection for elasticity testing.

Real failure detection on a TPU fleet comes from the platform (XLA aborts,
coordination-service timeouts, preemption notices). None of that is
exercisable in CI, so this module simulates the same *observable effects*
from a deterministic schedule: device loss, whole-slice preemption,
slow-straggler chips, and transient trial crashes. The schedule is either
built programmatically, generated from a seed (:func:`seeded_schedule`), or
parsed from ``SATURN_TPU_FAULTS`` — so a CPU run with
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` reproduces the exact
same fault sequence every time.

The injector never touches devices itself; it drives the
:class:`~saturn_tpu.resilience.health.FleetHealthMonitor` (which the
orchestrator polls) and answers the engine's per-task crash queries. The
split mirrors production: a real deployment replaces THIS module with
platform signals and keeps health/replan unchanged.
"""

from __future__ import annotations

import os
import random
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple


class FaultKind:
    """Fault taxonomy (string constants so schedules serialize trivially)."""

    DEVICE_LOSS = "device_loss"          # individual chips vanish
    SLICE_PREEMPTION = "slice_preemption"  # a whole aligned block vanishes
    STRAGGLER = "straggler"              # chips slow down by `slowdown`x
    TRIAL_CRASH = "trial_crash"          # one task's interval run raises once
    DEVICE_RETURN = "device_return"      # previously lost chips come back
    # Health-fault classes (the PR 8 guardian's detection targets). All are
    # injected at the OBSERVATION level — the sentinel's view of the loss
    # vector, or a host-side stall before dispatch — never into the train
    # state, so a rolled-back retry's trajectory is genuinely fault-free.
    NUMERIC_NAN = "numeric_nan"          # one observed step loss becomes NaN (once)
    LOSS_SPIKE = "loss_spike"            # one observed step loss explodes (once)
    BATCH_POISON = "batch_poison"        # dataset indices observe NaN (persistent)
    DISPATCH_STALL = "dispatch_stall"    # a task's dispatch wedges for stall_s (once)

    ALL = (DEVICE_LOSS, SLICE_PREEMPTION, STRAGGLER, TRIAL_CRASH, DEVICE_RETURN,
           NUMERIC_NAN, LOSS_SPIKE, BATCH_POISON, DISPATCH_STALL)
    # Kinds targeting ONE task's run (not fleet topology): excluded from
    # due()/apply_due and consumed through the engine's per-task queries.
    TASK_LEVEL = (TRIAL_CRASH, NUMERIC_NAN, LOSS_SPIKE, BATCH_POISON,
                  DISPATCH_STALL)


class PreemptedError(RuntimeError):
    """A task's interval run was lost to a device/slice preemption.

    Distinct from an ordinary task failure: the orchestrator requeues a
    preempted task WITHOUT counting it against ``max_task_retries`` — losing
    your chips is the fleet's fault, not the task's.
    """


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault.

    ``at_interval`` is the orchestrator interval index (0-based) the event
    fires in; ``after_s`` delays it that many seconds INTO the interval
    (0.0 = fires at the pre-interval health poll, >0 = mid-interval, applied
    by the engine's watchdog timer).
    """

    at_interval: int
    kind: str
    devices: Tuple[int, ...] = ()        # device indices (loss/preemption/straggler/return)
    task: Optional[str] = None           # task-level target; None = any task
    slowdown: float = 1.0                # STRAGGLER latency multiplier
    after_s: float = 0.0                 # seconds into the interval
    batches: Tuple[int, ...] = ()        # BATCH_POISON dataset indices
    step: int = 0                        # NUMERIC_NAN/LOSS_SPIKE interval-step offset
    stall_s: float = 0.0                 # DISPATCH_STALL wedge duration
    value: float = float("nan")          # injected loss value (NaN default)

    def __post_init__(self) -> None:
        if self.kind not in FaultKind.ALL:
            raise ValueError(f"unknown fault kind {self.kind!r}; use one of {FaultKind.ALL}")

    @property
    def mid_interval(self) -> bool:
        return self.after_s > 0.0


@dataclass
class FaultInjector:
    """Replays a fault schedule against the health monitor and the engine.

    One injector instance is single-use per orchestration: crash events are
    consumed as they fire (a *transient* crash hits once, the retry
    succeeds), and interval polls are idempotent within an interval.
    """

    schedule: List[FaultEvent] = field(default_factory=list)

    def __post_init__(self) -> None:
        self.schedule = sorted(
            self.schedule, key=lambda e: (e.at_interval, e.after_s, e.kind)
        )
        self._consumed_crashes: set = set()
        self._consumed_numeric: set = set()
        self._consumed_stalls: set = set()

    # ------------------------------------------------------------- interval
    def due(self, interval_index: int, mid_interval: bool = False) -> List[FaultEvent]:
        """Topology events due in this interval — at its start
        (``mid_interval=False``, the orchestrator's pre-interval poll) or
        during it (``True``, the engine's watchdog)."""
        return [
            e
            for e in self.schedule
            if e.at_interval == interval_index
            and e.mid_interval == mid_interval
            and e.kind not in FaultKind.TASK_LEVEL
        ]

    def apply_due(self, interval_index: int, monitor, mid_interval: bool = False) -> List[FaultEvent]:
        """Apply every due topology event to ``monitor``; returns them."""
        events = self.due(interval_index, mid_interval=mid_interval)
        for e in events:
            if e.kind in (FaultKind.DEVICE_LOSS, FaultKind.SLICE_PREEMPTION):
                monitor.mark_lost(e.devices, cause=e.kind)
            elif e.kind == FaultKind.DEVICE_RETURN:
                monitor.mark_restored(e.devices)
            elif e.kind == FaultKind.STRAGGLER:
                monitor.mark_straggler(e.devices, e.slowdown)
        return events

    def arm_watchdog(self, interval_index: int, monitor, abort_event) -> List:
        """Arm one timer per mid-interval event due this interval (the
        engine's abort-and-requeue hook). Liveness events additionally set
        ``abort_event`` so launcher threads stop starting new work; the
        caller cancels unexpired timers when the interval ends."""
        import threading

        timers = []
        for ev in self.due(interval_index, mid_interval=True):
            def fire(ev=ev):
                if ev.kind in (FaultKind.DEVICE_LOSS, FaultKind.SLICE_PREEMPTION):
                    monitor.mark_lost(ev.devices, cause=ev.kind)
                    abort_event.set()
                elif ev.kind == FaultKind.STRAGGLER:
                    monitor.mark_straggler(ev.devices, ev.slowdown)
                elif ev.kind == FaultKind.DEVICE_RETURN:
                    monitor.mark_restored(ev.devices)

            t = threading.Timer(ev.after_s, fire)
            t.daemon = True
            t.start()
            timers.append(t)
        return timers

    # ---------------------------------------------------------------- crash
    def crashes(self, task_name: str, interval_index: int) -> bool:
        """Should this task's run raise a transient crash this interval?
        Each TRIAL_CRASH event fires exactly once (transient by definition —
        the reference's retry-able trial failure class)."""
        for i, e in enumerate(self.schedule):
            if (
                e.kind == FaultKind.TRIAL_CRASH
                and e.at_interval == interval_index
                and (e.task is None or e.task == task_name)
                and i not in self._consumed_crashes
            ):
                self._consumed_crashes.add(i)
                return True
        return False

    # --------------------------------------------------------------- health
    def numeric_plan(self, task_name: str, interval_index: int) -> Optional[dict]:
        """The observation-level loss poisoning due for this task's interval
        run, or None.

        Returns ``{"steps": {offset: value}, "batches": {dataset_idx:
        value}}`` — the sentinel overwrites those slots in the OBSERVED loss
        vector before folding. ``numeric_nan`` / ``loss_spike`` events are
        transient (consumed once; the rolled-back retry is clean), while
        ``batch_poison`` is persistent from its interval on (the fault
        follows the dataset index through rollbacks, which is what makes
        quarantine the fix).
        """
        import math

        steps: dict = {}
        batches: dict = {}
        for i, e in enumerate(self.schedule):
            if e.task is not None and e.task != task_name:
                continue
            if e.kind in (FaultKind.NUMERIC_NAN, FaultKind.LOSS_SPIKE):
                if (
                    e.at_interval == interval_index
                    and i not in self._consumed_numeric
                ):
                    self._consumed_numeric.add(i)
                    v = e.value
                    if e.kind == FaultKind.LOSS_SPIKE and math.isnan(v):
                        v = 1e9  # a spike must stay finite to exercise EWMA
                    steps[int(e.step)] = float(v)
            elif e.kind == FaultKind.BATCH_POISON:
                if interval_index >= e.at_interval:
                    for b in e.batches:
                        batches[int(b)] = float(e.value)
        if not steps and not batches:
            return None
        return {"steps": steps, "batches": batches}

    def dispatch_stall_s(self, task_name: str, interval_index: int) -> float:
        """Seconds this task's dispatch should wedge this interval (0 =
        none). Consumed once — the watchdog-abandoned retry runs clean."""
        for i, e in enumerate(self.schedule):
            if (
                e.kind == FaultKind.DISPATCH_STALL
                and e.at_interval == interval_index
                and (e.task is None or e.task == task_name)
                and i not in self._consumed_stalls
            ):
                self._consumed_stalls.add(i)
                return float(e.stall_s)
        return 0.0

    # ------------------------------------------------------------------ env
    @classmethod
    def from_env(cls, var: str = "SATURN_TPU_FAULTS") -> Optional["FaultInjector"]:
        """Parse a schedule from the environment, or None if unset.

        Format: semicolon-separated events
        ``<interval>[+<after_s>]:<kind>:<spec>`` where ``spec`` is a device
        range ``lo-hi`` / comma list for topology events, a task name for
        ``trial_crash``, or ``devs@slowdown`` for ``straggler``. Example::

            SATURN_TPU_FAULTS="1+0.05:slice_preemption:4-7;2:trial_crash:jobA"
        """
        raw = os.environ.get(var, "").strip()
        if not raw:
            return None
        return cls(schedule=[_parse_event(tok) for tok in raw.split(";") if tok.strip()])


def _parse_devices(spec: str) -> Tuple[int, ...]:
    out: List[int] = []
    for part in spec.split(","):
        part = part.strip()
        if "-" in part:
            lo, hi = part.split("-", 1)
            out.extend(range(int(lo), int(hi) + 1))
        elif part:
            out.append(int(part))
    return tuple(out)


def _parse_event(token: str) -> FaultEvent:
    try:
        when, kind, spec = token.strip().split(":", 2)
        after_s = 0.0
        if "+" in when:
            when, after = when.split("+", 1)
            after_s = float(after)
        interval = int(when)
        kind = kind.strip()
        if kind == FaultKind.TRIAL_CRASH:
            return FaultEvent(interval, kind, task=spec.strip() or None, after_s=after_s)
        if kind in (FaultKind.NUMERIC_NAN, FaultKind.LOSS_SPIKE):
            # spec: task[@step]
            name, _, step = spec.partition("@")
            return FaultEvent(
                interval, kind, task=name.strip() or None,
                step=int(step) if step else 0, after_s=after_s,
            )
        if kind == FaultKind.BATCH_POISON:
            # spec: task@i,j,k (dataset indices)
            name, _, idx = spec.partition("@")
            return FaultEvent(
                interval, kind, task=name.strip() or None,
                batches=_parse_devices(idx), after_s=after_s,
            )
        if kind == FaultKind.DISPATCH_STALL:
            # spec: task@seconds
            name, _, secs = spec.partition("@")
            return FaultEvent(
                interval, kind, task=name.strip() or None,
                stall_s=float(secs) if secs else 5.0, after_s=after_s,
            )
        if kind == FaultKind.STRAGGLER:
            devs, _, slow = spec.partition("@")
            return FaultEvent(
                interval, kind, devices=_parse_devices(devs),
                slowdown=float(slow) if slow else 3.0, after_s=after_s,
            )
        return FaultEvent(interval, kind, devices=_parse_devices(spec), after_s=after_s)
    except (ValueError, IndexError) as e:
        raise ValueError(
            f"bad SATURN_TPU_FAULTS event {token!r} "
            "(expected '<interval>[+<after_s>]:<kind>:<spec>')"
        ) from e


def seeded_schedule(
    seed: int,
    n_intervals: int,
    n_devices: int,
    p_preempt: float = 0.15,
    p_crash: float = 0.1,
    p_straggler: float = 0.05,
) -> List[FaultEvent]:
    """Generate a reproducible random fault schedule.

    Per interval, each fault class fires independently with its probability;
    preemptions take an aligned power-of-two block (the unit real spot
    reclaims operate on), stragglers a single chip. The same (seed, shape)
    always yields the same schedule — chaos testing without flakes.
    """
    rng = random.Random(seed)
    events: List[FaultEvent] = []
    for i in range(n_intervals):
        if rng.random() < p_preempt and n_devices >= 2:
            size = 2 ** rng.randint(0, max(0, n_devices.bit_length() - 2))
            offset = rng.randrange(0, n_devices // size) * size
            events.append(
                FaultEvent(
                    i, FaultKind.SLICE_PREEMPTION,
                    devices=tuple(range(offset, offset + size)),
                    after_s=round(rng.uniform(0.0, 0.2), 3),
                )
            )
        if rng.random() < p_crash:
            events.append(FaultEvent(i, FaultKind.TRIAL_CRASH))
        if rng.random() < p_straggler:
            events.append(
                FaultEvent(
                    i, FaultKind.STRAGGLER,
                    devices=(rng.randrange(n_devices),),
                    slowdown=round(rng.uniform(2.0, 6.0), 2),
                )
            )
    return events
