"""Fleet health: per-device liveness/latency tracking and typed change events.

The engine feeds per-block step timings after every interval run
(:func:`FleetHealthMonitor.note_step`); fault injection — or, on a real
fleet, platform preemption notices — feeds liveness transitions
(``mark_lost`` / ``mark_restored``). The orchestrator polls the monitor at
its pre-interval hook and receives at most one aggregated
:class:`TopologyChange` per poll, which it hands to the elastic replanner.

Straggler detection is latency-based: a device whose EWMA per-batch latency
exceeds ``straggler_factor`` x the fleet median is flagged, producing a
``degrade`` event (advisory — the ``degrade-in-place`` recovery policy keeps
running; an operator policy could evict instead).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

#: Env knob: how many consecutive healthy polls a returned device must
#: survive before ``poll()`` surfaces a ``grow`` event (min 1 = immediate).
ENV_GROW_HYSTERESIS = "SATURN_TPU_GROW_HYSTERESIS"
DEFAULT_GROW_HYSTERESIS = 2


@dataclass(frozen=True)
class TopologyChange:
    """One aggregated fleet-health transition, as consumed by the replanner.

    ``kind``: ``"shrink"`` (devices lost), ``"grow"`` (devices returned;
    wins only when nothing was lost in the same poll window), or
    ``"degrade"`` (liveness unchanged, stragglers detected).
    """

    kind: str
    lost: Tuple[int, ...] = ()
    gained: Tuple[int, ...] = ()
    stragglers: Tuple[int, ...] = ()
    cause: str = ""
    at: float = field(default_factory=time.time)

    def to_fields(self) -> dict:
        """Flat JSON-safe dict for the metrics stream."""
        return {
            "change": self.kind,
            "lost": list(self.lost),
            "gained": list(self.gained),
            "stragglers": list(self.stragglers),
            "cause": self.cause,
        }


@dataclass
class DeviceHealth:
    """Liveness + latency state for one device index."""

    alive: bool = True
    latency_ewma: Optional[float] = None   # seconds per batch, EWMA
    slowdown: float = 1.0                  # injected straggler multiplier
    last_seen: float = 0.0


class FleetHealthMonitor:
    """Tracks every device of a :class:`~saturn_tpu.core.mesh.SliceTopology`.

    Thread-safe: engine launcher threads call :meth:`note_step` concurrently
    and the mid-interval fault watchdog calls :meth:`mark_lost` from a timer
    thread while the orchestrator polls from the main thread.
    """

    EWMA_ALPHA = 0.5  # latency observations are whole-interval averages

    def __init__(self, n_devices: int, straggler_factor: float = 3.0,
                 grow_hysteresis: Optional[int] = None):
        if n_devices < 1:
            raise ValueError("n_devices must be positive")
        self.n_devices = n_devices
        self.straggler_factor = straggler_factor
        if grow_hysteresis is None:
            grow_hysteresis = int(
                os.environ.get(ENV_GROW_HYSTERESIS, DEFAULT_GROW_HYSTERESIS)
            )
        self.grow_hysteresis = max(1, grow_hysteresis)
        self._devices: Dict[int, DeviceHealth] = {
            i: DeviceHealth() for i in range(n_devices)
        }
        self._lock = threading.Lock()
        # Pending transitions since the last poll(), aggregated there.
        self._pending_lost: set = set()
        self._pending_gained: set = set()
        self._pending_cause: str = ""
        # Returned devices serving out hysteresis: index -> [streak,
        # loss_surfaced]. ``streak`` counts consecutive healthy polls so
        # far; ``loss_surfaced`` records whether the loss that preceded the
        # return was ever surfaced to the consumer (a poll() reported the
        # shrink) — an in-window blink cancels the shrink before it
        # surfaces, so the consumer still believes the device alive. They
        # are alive (schedulable once a replan runs) but a grow event is
        # withheld until the streak matures, so a blinking device cannot
        # trigger replan churn.
        self._grow_pending: Dict[int, List] = {}
        # id(device object) -> base index, set by for_topology/bind_devices.
        # Monitor indices always refer to the BASE (pre-fault) topology, so
        # fault schedules and metrics name stable device ids across shrinks;
        # the engine translates current-topology device objects through this
        # map (SliceTopology.subset reuses the same objects).
        self._id_to_index: Optional[Dict[int, int]] = None

    @classmethod
    def for_topology(cls, topology, straggler_factor: float = 3.0) -> "FleetHealthMonitor":
        """Monitor bound to a topology's device objects (the normal path)."""
        m = cls(len(topology.devices), straggler_factor)
        m.bind_devices(topology.devices)
        return m

    def bind_devices(self, devices: Sequence) -> None:
        self._id_to_index = {id(d): i for i, d in enumerate(devices)}

    def indices_of(self, devices: Sequence) -> List[int]:
        """Base indices for a block's device objects ([] when unbound —
        an index-only monitor, as in unit tests, stays inert here)."""
        if self._id_to_index is None:
            return []
        return [
            self._id_to_index[id(d)] for d in devices if id(d) in self._id_to_index
        ]

    # -------------------------------------------------------------- feeding
    def note_step(self, device_indices: Sequence[int], per_batch_s: float) -> None:
        """Fold one interval run's realized per-batch seconds into every
        device of the block that ran it (the engine's post-run hook).
        Injected straggler slowdowns inflate the observation, so detection
        exercises the same code path real slow chips would."""
        now = time.time()
        with self._lock:
            for i in device_indices:
                d = self._devices.get(i)
                if d is None or not d.alive:
                    continue
                obs = per_batch_s * d.slowdown
                d.latency_ewma = (
                    obs
                    if d.latency_ewma is None
                    else self.EWMA_ALPHA * obs + (1 - self.EWMA_ALPHA) * d.latency_ewma
                )
                d.last_seen = now

    def mark_lost(self, device_indices: Sequence[int], cause: str = "device_loss") -> None:
        with self._lock:
            surfaced_any = False
            for i in device_indices:
                d = self._devices.get(i)
                if d is None or not d.alive:
                    continue
                d.alive = False
                cand = self._grow_pending.pop(i, None)
                if cand is not None and cand[1]:
                    # Flapped back down before the return was ever surfaced,
                    # and the original loss WAS surfaced: from the consumer's
                    # view the device has been dead the whole time, so no new
                    # shrink event — just drop the hysteresis candidate. One
                    # shrink total per flap storm. (If the original loss was
                    # an in-window blink the consumer never saw, swallowing
                    # here would leave it scheduling on a dead device forever
                    # — fall through and surface the shrink instead.)
                    continue
                surfaced_any = True
                self._pending_lost.add(i)
                self._pending_gained.discard(i)
            if cause and surfaced_any:
                self._pending_cause = cause

    def mark_restored(self, device_indices: Sequence[int]) -> None:
        with self._lock:
            for i in device_indices:
                d = self._devices.get(i)
                if d is not None and not d.alive:
                    d.alive = True
                    d.latency_ewma = None  # returned chip: history is stale
                    d.slowdown = 1.0
                    # An unsurfaced loss (in-window blink) is cancelled —
                    # no shrink fires for a device that is already back. The
                    # loss may still have been consumer-visible (a mid-
                    # interval preemption kills running work before the
                    # return lands), so the return is NOT a non-event: like
                    # any return it must survive ``grow_hysteresis``
                    # consecutive healthy polls, then surfaces as a grow
                    # whose re-solve re-admits the requeued work. Whether the
                    # loss was surfaced is remembered on the candidate: a
                    # re-loss is swallowed only when the consumer already
                    # believes the device dead (see ``mark_lost``).
                    loss_surfaced = i not in self._pending_lost
                    self._pending_lost.discard(i)
                    self._grow_pending[i] = [0, loss_surfaced]

    def mark_straggler(self, device_indices: Sequence[int], slowdown: float) -> None:
        """Injected slowdown (fault schedule); detection stays latency-based."""
        with self._lock:
            for i in device_indices:
                d = self._devices.get(i)
                if d is not None:
                    d.slowdown = max(1.0, slowdown)

    # -------------------------------------------------------------- queries
    def alive_indices(self) -> List[int]:
        with self._lock:
            return [i for i, d in sorted(self._devices.items()) if d.alive]

    def is_alive(self, index: int) -> bool:
        with self._lock:
            d = self._devices.get(index)
            return d is not None and d.alive

    def any_lost(self, device_indices: Sequence[int]) -> bool:
        """Did any device of this block die? The engine's post-run check:
        work computed on a block that lost a chip mid-interval is discarded
        (the last checkpoint is the ground truth the task resumes from)."""
        with self._lock:
            return any(
                (d := self._devices.get(i)) is None or not d.alive
                for i in device_indices
            )

    def max_slowdown(self, device_indices: Sequence[int]) -> float:
        """Worst injected/observed slowdown factor across a block's devices
        (1.0 = nominal). Simulated engines use this to inflate realized
        per-batch time the same way a real straggler chip would."""
        with self._lock:
            return max(
                (
                    self._devices[i].slowdown
                    for i in device_indices
                    if i in self._devices
                ),
                default=1.0,
            )

    def stragglers(self) -> List[int]:
        """Devices whose latency EWMA exceeds straggler_factor x fleet
        median (alive devices with at least one observation)."""
        with self._lock:
            obs = {
                i: d.latency_ewma
                for i, d in self._devices.items()
                if d.alive and d.latency_ewma is not None
            }
        if len(obs) < 2:
            return []
        vals = sorted(obs.values())
        median = vals[len(vals) // 2]
        if median <= 0.0:
            return []
        return sorted(i for i, v in obs.items() if v > self.straggler_factor * median)

    # ---------------------------------------------------------------- polls
    def poll(self) -> Optional[TopologyChange]:
        """Consume pending transitions into one aggregated event (or None).

        Liveness changes win over straggler detection: a shrink forces a
        replan regardless of latency noise. A poll window containing both
        losses and returns reports ``shrink`` with both sets filled — the
        replanner rebuilds from the full alive set either way.

        Grow is hysteresis-gated: a returned device must survive
        ``grow_hysteresis`` consecutive healthy polls before a ``grow``
        surfaces, so a blinking device cannot trigger replan churn. A shrink
        in the meantime flushes candidates into its ``gained`` set (the
        shrink replan rebuilds from the full alive set anyway).
        """
        with self._lock:
            lost = tuple(sorted(self._pending_lost))
            cause = self._pending_cause
            self._pending_lost.clear()
            self._pending_cause = ""
            if lost:
                gained = set(self._pending_gained) | set(self._grow_pending)
                self._pending_gained.clear()
                self._grow_pending.clear()
                return TopologyChange(
                    kind="shrink", lost=lost, gained=tuple(sorted(gained)),
                    cause=cause or "device_loss",
                )
            matured = []
            for i in sorted(self._grow_pending):
                cand = self._grow_pending[i]
                cand[0] += 1
                if cand[0] >= self.grow_hysteresis:
                    matured.append(i)
                    del self._grow_pending[i]
            gained = set(self._pending_gained) | set(matured)
            self._pending_gained.clear()
        if gained:
            return TopologyChange(
                kind="grow", gained=tuple(sorted(gained)), cause="device_return"
            )
        stragglers = self.stragglers()
        if stragglers:
            return TopologyChange(
                kind="degrade", stragglers=tuple(stragglers), cause="straggler"
            )
        return None
