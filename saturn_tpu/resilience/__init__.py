"""Elastic resilience: fault injection, fleet health, topology-aware replanning.

The reference assumed a static device fleet for the lifetime of a batch
(SURVEY.md §5 "no elasticity, no fault injection"); on real TPU fleets
preemption of spot slices is the dominant failure mode. This package turns
the orchestrator's fixed-topology interval loop into an elastic one:

- :mod:`saturn_tpu.resilience.faults` — deterministic, seeded fault
  injection (device loss, slice preemption, stragglers, transient trial
  crashes) so elasticity is testable on CPU with
  ``XLA_FLAGS=--xla_force_host_platform_device_count=N``.
- :mod:`saturn_tpu.resilience.health` — per-device liveness/latency tracking
  fed by engine step timings; raises typed :class:`TopologyChange` events.
- :mod:`saturn_tpu.resilience.replan` — on a shrink/grow event, diffs the
  ``SliceTopology``, re-invokes the SPASE solver over the surviving mesh
  (Amdahl-interpolating never-profiled sizes) under a pluggable recovery
  policy; :func:`plan_defrag_wave` plans occupancy-driven compaction.
- :mod:`saturn_tpu.resilience.grow` — the recovery half: grow-event
  handling (unbench parked work, drain the DEFER backlog) and two-phase
  journaled defragmentation waves.

Cross-mesh checkpoint migration (restoring a task's state onto a mesh of a
different shape than it was saved under) lives in
``saturn_tpu.utils.checkpoint.restore_sharded`` — resharding is one
``jax.device_put`` against the new sharding spec.
"""

from saturn_tpu.resilience.chaos import (
    HEALTH_FAULT_CLASSES,
    CampaignResult,
    CampaignSpec,
    campaign_schedule,
    compare_checkpoints,
    run_campaign,
)
from saturn_tpu.resilience.crash import (
    KILL_POINTS,
    CrashInjector,
    SimulatedKill,
    run_to_kill,
)
from saturn_tpu.resilience.faults import (
    FaultEvent,
    FaultInjector,
    FaultKind,
    PreemptedError,
    seeded_schedule,
)
from saturn_tpu.resilience.grow import GrowCoordinator, default_resident_bytes
from saturn_tpu.resilience.health import DeviceHealth, FleetHealthMonitor, TopologyChange
from saturn_tpu.resilience.netchaos import (
    NET_FAULT_CLASSES,
    NetChaosProxy,
    NetChaosSpec,
    NetChaosStats,
    single_fault_spec,
)
from saturn_tpu.resilience.replan import (
    RECOVERY_POLICIES,
    DefragMove,
    DefragWave,
    ElasticReplanner,
    plan_defrag_wave,
)

__all__ = [
    "GrowCoordinator",
    "default_resident_bytes",
    "DefragMove",
    "DefragWave",
    "plan_defrag_wave",
    "KILL_POINTS",
    "CrashInjector",
    "SimulatedKill",
    "run_to_kill",
    "FaultEvent",
    "FaultInjector",
    "FaultKind",
    "PreemptedError",
    "seeded_schedule",
    "DeviceHealth",
    "FleetHealthMonitor",
    "TopologyChange",
    "ElasticReplanner",
    "RECOVERY_POLICIES",
    "HEALTH_FAULT_CLASSES",
    "CampaignSpec",
    "CampaignResult",
    "campaign_schedule",
    "run_campaign",
    "compare_checkpoints",
    "NET_FAULT_CLASSES",
    "NetChaosProxy",
    "NetChaosSpec",
    "NetChaosStats",
    "single_fault_spec",
]
