"""Chaos campaign harness: seeded mixed-fault sweeps with kill-replay.

PR 4's crash harness kills the controller at journal barriers; PR 8's
sentinel/guardian stack detects and recovers from *training* faults. This
module composes both into one campaign: a seeded schedule draws at least one
event from every health-fault class (NaN loss, loss spike, persistent batch
poisoning, dispatch stall), optionally arms a simulated SIGKILL at the
``post-rollback`` barrier (the window right after a faulted task's
quarantine/detach records went durable), and restarts the batch orchestrator
against the same journal directory until the batch completes — exactly the
operator's restart loop.

What a campaign proves (asserted by ``tests/test_chaos.py`` and summarized
by ``benchmarks/chaos_campaign.py``):

- **zero lost jobs** — every task reaches ``completed`` across restarts;
- **quarantine survives the kill** — the skip-list replayed from the
  journal keeps a restarted run off the poisoned batches;
- **bit-identical recovery** — a faulted task's final checkpoint equals a
  fault-free run over the same surviving batch sequence, byte for byte
  (faults are injected at the observation level, never into train state).

Same seed, same campaign, every run — chaos testing without flakes.
"""

from __future__ import annotations

import logging
import os
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from saturn_tpu.resilience.crash import CrashInjector, SimulatedKill
from saturn_tpu.resilience.faults import FaultEvent, FaultInjector, FaultKind

logger = logging.getLogger("saturn_tpu")

#: The guardian's detection targets — every campaign draws at least one
#: event per class listed in its spec.
HEALTH_FAULT_CLASSES = (
    FaultKind.NUMERIC_NAN,
    FaultKind.LOSS_SPIKE,
    FaultKind.BATCH_POISON,
    FaultKind.DISPATCH_STALL,
)


@dataclass(frozen=True)
class CampaignSpec:
    """One seeded campaign's shape.

    ``poison_range`` bounds the dataset indices batch poisoning may pick —
    keep it within the first interval's window so the fault is guaranteed
    to be observed (and small enough that quarantine never empties the
    dataset). ``stall_s`` is the injected dispatch wedge; pair it with a
    guardian whose watchdog deadline is below it so the watchdog, not
    patience, ends the stall. ``max_intervals_hit`` defaults to 1 — every
    fault lands in interval 0, so the first rollback is to the INITIAL
    state and a faulted run's final checkpoint is exactly comparable to a
    fault-free run with the quarantine pre-applied (a later-interval fault
    rolls back to a checkpoint whose pre-quarantine prefix a pre-applied
    reference never trains).
    """

    seed: int
    fault_classes: Tuple[str, ...] = HEALTH_FAULT_CLASSES
    kill_during_rollback: bool = False
    max_intervals_hit: int = 1     # faults land in intervals [0, hit)
    poison_range: int = 8
    poison_batches: int = 1
    stall_s: float = 0.3
    max_restarts: int = 8


@dataclass
class CampaignResult:
    """What one campaign run did, for the test/benchmark asserts."""

    seed: int
    completed: List[str] = field(default_factory=list)
    failed: Dict[str, str] = field(default_factory=dict)
    restarts: int = 0
    kills: int = 0
    schedule: List[FaultEvent] = field(default_factory=list)
    quarantined: Dict[str, List[int]] = field(default_factory=dict)
    detached: List[str] = field(default_factory=list)


def campaign_schedule(
    task_names: List[str], spec: CampaignSpec
) -> List[FaultEvent]:
    """Draw one fault event per class in ``spec.fault_classes``, targets and
    timing derived from the seed. Deterministic: same (names, spec) → same
    schedule."""
    if not task_names:
        raise ValueError("campaign needs at least one task")
    rng = random.Random(spec.seed)
    hit = max(1, spec.max_intervals_hit)
    events: List[FaultEvent] = []
    for kind in spec.fault_classes:
        target = rng.choice(list(task_names))
        at = rng.randrange(hit)
        if kind in (FaultKind.NUMERIC_NAN, FaultKind.LOSS_SPIKE):
            events.append(
                FaultEvent(at, kind, task=target, step=rng.randrange(4))
            )
        elif kind == FaultKind.BATCH_POISON:
            n = min(spec.poison_batches, spec.poison_range)
            idx = tuple(sorted(rng.sample(range(spec.poison_range), n)))
            events.append(FaultEvent(at, kind, task=target, batches=idx))
        elif kind == FaultKind.DISPATCH_STALL:
            events.append(
                FaultEvent(at, kind, task=target, stall_s=spec.stall_s)
            )
        else:
            raise ValueError(
                f"{kind!r} is not a health-fault class "
                f"(use one of {HEALTH_FAULT_CLASSES})"
            )
    return events


def run_campaign(
    tasks_factory: Callable[[], List[Any]],
    spec: CampaignSpec,
    workdir: str,
    guardian_config: Any = None,
    **orchestrate_kwargs,
) -> CampaignResult:
    """Run one seeded campaign to completion, restarting through kills.

    ``tasks_factory`` must return a FRESH task list per call — each
    incarnation rebuilds its tasks like a restarted process would, and the
    journal replay subtracts durably realized batches from their budgets.
    Keyword arguments are forwarded to ``orchestrate`` (``resume_dir`` and
    ``fault_injector`` are owned by the harness).

    The fault injector is re-created per incarnation, so consumed-once
    transients (NaN, spike, stall) scheduled for an interval index a restart
    revisits fire again — more chaos, same invariants: quarantined batch
    poisoning is restored from the journal and stays skipped, and every job
    still finishes. ``guardian_config`` (a ``GuardianConfig``) builds a
    FRESH guardian per incarnation — a restarted process carries no policy
    state, only what the journal replays.
    """
    from saturn_tpu.durability import recovery as rmod
    from saturn_tpu.executor.orchestrator import orchestrate

    tasks = tasks_factory()
    schedule = campaign_schedule([t.name for t in tasks], spec)
    result = CampaignResult(seed=spec.seed, schedule=list(schedule))

    barrier = None
    if spec.kill_during_rollback:
        barrier = CrashInjector("post-rollback", hit=1).barrier

    while True:
        injector = FaultInjector(schedule=list(schedule))
        guardian = None
        if guardian_config is not None:
            from saturn_tpu.health import TrainingGuardian

            guardian = TrainingGuardian(config=guardian_config)
        try:
            out = orchestrate(
                tasks,
                resume_dir=workdir,
                fault_injector=injector,
                crash_barrier=barrier,
                health_guardian=guardian,
                **orchestrate_kwargs,
            )
        except SimulatedKill:
            result.kills += 1
            result.restarts += 1
            if result.restarts > spec.max_restarts:
                raise RuntimeError(
                    f"campaign seed {spec.seed} exceeded "
                    f"{spec.max_restarts} restarts — runaway kill loop"
                )
            barrier = None  # the injector fired once; the process is "new"
            tasks = tasks_factory()
            logger.warning(
                "chaos campaign (seed %d): killed at post-rollback — "
                "restart %d", spec.seed, result.restarts,
            )
            continue
        break

    result.completed = list(out["completed"])
    result.failed = dict(out["failed"])
    state = rmod.replay_batch_state(workdir)
    result.quarantined = dict(state.quarantined)
    result.detached = list(state.detached)
    return result


def compare_checkpoints(
    dir_a: str, dir_b: str, names: Optional[List[str]] = None
) -> List[str]:
    """Byte-for-byte comparison of final published checkpoints.

    Compares ``{name}.npz`` checkpoints under both directories (all common
    stems when ``names`` is None; per-rank shard files and quarantine
    sidecars are not themselves checkpoints and are skipped) array-by-array
    on the raw buffer — the bit-identity the campaign promises, strict
    enough to catch a single flipped mantissa bit and NaN-safe (``==`` is
    not). Reads through ``checkpoint.load_arrays`` so sharded-manifest and
    legacy single-file checkpoints compare interchangeably. Returns a list
    of human-readable mismatch descriptions; empty means identical.
    """
    from saturn_tpu.utils import checkpoint as ckpt
    from saturn_tpu.utils.checkpoint import _SHARD_RE

    if names is None:
        stems = sorted(
            os.path.splitext(f)[0]
            for f in os.listdir(dir_a)
            if f.endswith(".npz") and ".corrupt" not in f
            and not _SHARD_RE.search(f)
        )
    else:
        stems = list(names)
    mismatches: List[str] = []
    for stem in stems:
        pa = os.path.join(dir_a, f"{stem}.npz")
        pb = os.path.join(dir_b, f"{stem}.npz")
        if not os.path.exists(pb):
            mismatches.append(f"{stem}: missing from {dir_b}")
            continue
        a = ckpt.load_arrays(pa)
        b = ckpt.load_arrays(pb)
        ka, kb = set(a), set(b)
        if ka != kb:
            mismatches.append(
                f"{stem}: key sets differ ({sorted(ka ^ kb)})"
            )
            continue
        for k in sorted(ka):
            va, vb = a[k], b[k]
            if va.shape != vb.shape or va.dtype != vb.dtype:
                mismatches.append(
                    f"{stem}[{k}]: shape/dtype {va.shape}/{va.dtype} "
                    f"vs {vb.shape}/{vb.dtype}"
                )
            elif va.tobytes() != vb.tobytes():
                mismatches.append(f"{stem}[{k}]: bytes differ")
    return mismatches
