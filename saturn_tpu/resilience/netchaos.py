"""Wire-level chaos: a seeded, frame-aware TCP proxy for the gateway.

``chaos.py`` attacks the *training* plane (numeric faults, kills at journal
barriers); this module attacks the *wire* between a
:class:`~saturn_tpu.service.gateway.client.GatewayClient` and its
:class:`~saturn_tpu.service.gateway.server.GatewayServer`. The proxy sits on
its own port, pumps bytes both ways, reassembles them into JSONL frames, and
injects faults per frame from a seeded RNG — same seed, same connection
order, same fault sequence, every run (the chaos-without-flakes discipline
of ``CampaignSpec``).

Fault classes (:data:`NET_FAULT_CLASSES`):

- ``drop``        — cut the connection before the frame is forwarded (the
  client's request or response simply vanishes mid-flight);
- ``delay``       — hold the frame ``delay_s`` before forwarding (stalls
  that race the client's timeout);
- ``partial``     — forward a strict byte prefix of the frame, then cut the
  connection (a torn write: the peer reads garbage-then-EOF);
- ``dup``         — forward the frame twice (the client must discard the
  stray by ``rid``; a duplicated *request* must not double-admit);
- ``reorder``     — hold the frame until after its successor (responses
  arrive out of order; ``rid`` correlation must still match them);
- ``kill_ack``    — server→client only: swallow the response and cut the
  connection. For a submit this is the canonical lost-ACK window — the job
  IS admitted and journaled, the client never hears; only the dedup key
  makes the retry safe.

What a netchaos campaign proves (``tests/test_gateway.py``): across seeds ×
fault classes, **zero lost jobs** (every submitted job completes), **zero
duplicate admissions** (retries never admit a second job for the same dedup
key), and the surviving jobs' trajectories match an in-process run of the
same mix.
"""

from __future__ import annotations

import logging
import random
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("saturn_tpu")

#: Every wire-fault class the proxy can inject (campaigns sweep these).
NET_FAULT_CLASSES: Tuple[str, ...] = (
    "drop", "delay", "partial", "dup", "reorder", "kill_ack",
)

#: Directions a fault can apply to. ``kill_ack`` is response-only by
#: construction — killing a request is just ``drop``.
_C2S = "c2s"
_S2C = "s2c"


@dataclass(frozen=True)
class NetChaosSpec:
    """One seeded wire-chaos configuration.

    ``fault_rate`` is the per-frame probability of drawing a fault;
    ``max_faults_per_conn`` caps how many times one connection can be hit so
    a campaign always makes forward progress (the client's retry budget is
    finite). ``skip_frames`` lets the first N frames of every connection
    pass clean — the hello/session-resume exchange stays intact so faults
    land on real requests, where the invariants actually bite.
    """

    seed: int
    fault_classes: Tuple[str, ...] = NET_FAULT_CLASSES
    fault_rate: float = 0.25
    delay_s: float = 0.05
    max_faults_per_conn: int = 2
    skip_frames: int = 2


@dataclass
class NetChaosStats:
    """What the proxy actually did — campaign asserts read these.
    Counter updates come from every pump thread; all go through the lock."""

    connections: int = 0
    frames: int = 0
    injected: Dict[str, int] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def note(self, kind: str) -> None:
        with self._lock:
            self.injected[kind] = self.injected.get(kind, 0) + 1

    def note_frame(self) -> None:
        with self._lock:
            self.frames += 1

    def total_injected(self) -> int:
        with self._lock:
            return sum(self.injected.values())


class _Pump:
    """One direction of one proxied connection: reassemble frames, consult
    the seeded RNG per frame, forward (or maul) accordingly."""

    def __init__(self, proxy: "NetChaosProxy", conn_id: int, direction: str,
                 src: socket.socket, dst: socket.socket):
        self.proxy = proxy
        self.direction = direction
        self.src = src
        self.dst = dst
        spec = proxy.spec
        # Deterministic per (seed, connection ordinal, direction): the fault
        # sequence depends only on the spec and the connection's arrival
        # order, never on wall-clock or thread interleaving.
        self.rng = random.Random(f"{spec.seed}:{conn_id}:{direction}")
        self.faults_left = spec.max_faults_per_conn
        self.skip = spec.skip_frames
        self.held: Optional[bytes] = None   # a reorder-held frame
        self.classes = [
            c for c in spec.fault_classes
            if c != "kill_ack" or direction == _S2C
        ]

    def run(self) -> None:
        reader = self.src.makefile("rb")
        try:
            while True:
                try:
                    frame = reader.readline()
                except OSError:
                    break
                if not frame:
                    break
                if not self._forward(frame):
                    break
            self._flush_held()
        finally:
            try:
                reader.close()
            except OSError:
                pass
            # Propagate EOF so the peer's reader unblocks; the other pump
            # dies on its own EOF/ECONNRESET.
            for s in (self.src, self.dst):
                try:
                    s.close()
                except OSError:
                    pass

    # ------------------------------------------------------------ forwarding
    def _forward(self, frame: bytes) -> bool:
        """Forward one frame, possibly injecting a fault. Returns False when
        the connection was cut (by a fault or a dead peer)."""
        self.proxy.stats.note_frame()
        fault = self._draw()
        if fault is None:
            return self._send(frame)
        spec = self.proxy.spec
        self.proxy.stats.note(fault)
        logger.info("netchaos: inject %s on %s frame", fault, self.direction)
        if fault == "drop":
            return False
        if fault == "kill_ack":
            # The response vanishes AND the transport dies: the client's
            # view is indistinguishable from a server crash mid-ACK.
            return False
        if fault == "delay":
            time.sleep(spec.delay_s)
            return self._send(frame)
        if fault == "partial":
            cut = max(1, self.rng.randrange(1, max(2, len(frame))))
            try:
                self.dst.sendall(frame[:cut])
            except OSError:
                pass
            return False
        if fault == "dup":
            return self._send(frame) and self._send(frame)
        if fault == "reorder":
            if self.held is None:
                self.held = frame   # hold; released after the next frame
                return True
            return self._send(frame)  # _send flushes the held frame second
        raise AssertionError(f"unknown fault class {fault!r}")

    def _draw(self) -> Optional[str]:
        if self.skip > 0:
            self.skip -= 1
            return None
        if self.faults_left <= 0 or not self.classes:
            return None
        if self.rng.random() >= self.proxy.spec.fault_rate:
            return None
        self.faults_left -= 1
        return self.rng.choice(self.classes)

    def _send(self, frame: bytes) -> bool:
        held, self.held = self.held, None
        try:
            if held is not None:
                # A reorder hold with no successor on the wire must not rot:
                # anything newer flushes it first-in-second.
                self.dst.sendall(frame + held)
            else:
                self.dst.sendall(frame)
        except OSError:
            return False
        return True

    def _flush_held(self) -> None:
        if self.held is not None:
            held, self.held = self.held, None
            try:
                self.dst.sendall(held)
            except OSError:
                pass


class NetChaosProxy:
    """Seeded chaos TCP proxy: listen on :attr:`address`, forward to
    ``(upstream_host, upstream_port)``, maul frames per ``spec``.

    Use as a context manager or call :meth:`start` / :meth:`stop`. Point a
    ``GatewayClient`` at ``proxy.address`` instead of the gateway's.
    """

    def __init__(self, upstream_host: str, upstream_port: int,
                 spec: NetChaosSpec, host: str = "127.0.0.1", port: int = 0):
        self.upstream = (upstream_host, upstream_port)
        self.spec = spec
        self.host = host
        self.port = port
        self.stats = NetChaosStats()
        self._lock = threading.Lock()
        self._stopped = False
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._threads: List[threading.Thread] = []
        self._socks: List[socket.socket] = []
        self.address: Tuple[str, int] = (host, port)

    def start(self) -> "NetChaosProxy":
        sock = socket.create_server((self.host, self.port))
        sock.settimeout(0.2)
        self._listener = sock
        self.address = sock.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="netchaos-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            socks = list(self._socks)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(5.0)
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        for t in list(self._threads):
            t.join(5.0)

    def __enter__(self) -> "NetChaosProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    break
            try:
                client, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                server = socket.create_connection(self.upstream, timeout=5.0)
            except OSError:
                try:
                    client.close()
                except OSError:
                    pass
                continue
            with self._lock:
                if self._stopped:
                    for s in (client, server):
                        try:
                            s.close()
                        except OSError:
                            pass
                    break
                conn_id = self.stats.connections
                self.stats.connections += 1
                self._socks += [client, server]
                for direction, src, dst in (
                    (_C2S, client, server), (_S2C, server, client),
                ):
                    pump = _Pump(self, conn_id, direction, src, dst)
                    t = threading.Thread(
                        target=pump.run,
                        name=f"netchaos-{conn_id}-{direction}", daemon=True,
                    )
                    self._threads.append(t)
                    t.start()


def single_fault_spec(seed: int, fault_class: str,
                      **overrides) -> NetChaosSpec:
    """A spec that injects exactly one fault class — the campaign's
    seeds × classes sweep builds its grid from these."""
    if fault_class not in NET_FAULT_CLASSES:
        raise ValueError(
            f"{fault_class!r} is not a wire-fault class "
            f"(use one of {NET_FAULT_CLASSES})"
        )
    defaults = dict(fault_rate=0.5, max_faults_per_conn=1)
    defaults.update(overrides)
    return NetChaosSpec(
        seed=seed, fault_classes=(fault_class,), **defaults
    )
