"""Elastic replanning: shrink/grow the plan when the fleet changes shape.

On a :class:`~saturn_tpu.resilience.health.TopologyChange` the orchestrator
hands the surviving device set here. The replanner

1. rebuilds the ``SliceTopology`` over the survivors
   (``SliceTopology.subset``),
2. makes every task schedulable on the new capacity — already-profiled
   strategies are reused as-is; never-profiled sizes are synthesized from
   the same Amdahl scaling model the trial runner's grid pruning uses
   (``trial_runner/evaluator.py::_fit_scaling_model``), flagged
   ``interpolated`` so the realized-feedback loop upgrades them once they
   actually run,
3. applies a pluggable **recovery policy** (Piper-style programmable
   scheduling, arXiv 2606.11169) deciding who keeps running, and
4. re-invokes the SPASE solver (``solver/milp.py``) over the surviving mesh.

Built-in policies:

``pause-resolve-resume``
    Pause the batch, full blocking re-solve on the new topology, resume
    everything that fits. The default; best plans, costs one solver run.
``degrade-in-place``
    No solver run: every task keeps its strategy *size* (clamped to the new
    capacity) and is list-scheduled in previous start order. Cheapest
    recovery latency; accepts a worse makespan.
``evict-lowest-priority``
    Like pause-resolve-resume, but first evicts the lowest-priority tasks
    (``task.hints["priority"]``, default 0) until the projected makespan is
    within ``degrade_factor`` x the pre-fault plan. Unschedulable tasks are
    evicted under every policy.

Custom policies register via :func:`register_policy` — a callable
``(tasks, ctx) -> (keep, evict)``.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from saturn_tpu.core.mesh import Block, SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.solver import anytime, milp
from saturn_tpu.utils import metrics

log = logging.getLogger("saturn_tpu")

RECOVERY_POLICIES = (
    "pause-resolve-resume",
    "degrade-in-place",
    "evict-lowest-priority",
)


@dataclass
class ReplanContext:
    """What a recovery policy gets to see."""

    topology: SliceTopology            # the surviving mesh
    previous_plan: Optional[milp.Plan]
    previous_makespan: float
    change_kind: str
    degrade_factor: float


@dataclass
class ReplanResult:
    topology: SliceTopology
    plan: milp.Plan
    evicted: List[str] = field(default_factory=list)
    synthesized: Dict[str, List[int]] = field(default_factory=dict)  # task -> sizes
    migrations: Dict[str, dict] = field(default_factory=dict)        # task -> diff


_POLICIES: Dict[str, Callable] = {}


def register_policy(name: str, fn: Callable) -> None:
    """Register a custom recovery policy ``(tasks, ctx) -> (keep, evict)``."""
    _POLICIES[name] = fn


def get_policy(name: str) -> Callable:
    """Look up a recovery policy ``(tasks, ctx) -> (keep, evict)`` by name.

    Public accessor for callers outside the replanner — the online job
    service reuses ``evict-lowest-priority`` to shed load under admission
    pressure (deadline slack exhausted) without a topology change."""
    try:
        return _POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown recovery policy {name!r}; built-ins: {RECOVERY_POLICIES}, "
            f"registered: {sorted(_POLICIES)}"
        ) from None


def _priority(task) -> float:
    return float(getattr(task, "hints", {}).get("priority", 0.0))


def _runnable(task, capacity: int) -> bool:
    return any(g <= capacity for g in task.feasible_strategies())


def _policy_resolve(tasks, ctx: ReplanContext):
    return list(tasks), []


def _policy_evict_lowest(tasks, ctx: ReplanContext):
    """Drop low-priority work until the survivors' projected makespan is
    within degrade_factor of the pre-fault plan (greedy projection — cheap
    and pessimistic, so eviction errs toward keeping tasks)."""
    keep = sorted(tasks, key=_priority, reverse=True)
    evicted: List = []
    limit = ctx.degrade_factor * max(ctx.previous_makespan, 1e-9)
    while len(keep) > 1:
        proj = milp.greedy_plan(keep, ctx.topology).makespan
        if proj <= limit or ctx.previous_makespan <= 0.0:
            break
        evicted.append(keep.pop())  # lowest priority last after the sort
    return keep, evicted


_POLICIES["pause-resolve-resume"] = _policy_resolve
_POLICIES["degrade-in-place"] = _policy_resolve  # selection identical; the
#                                 difference is skipping the solver run below
_POLICIES["evict-lowest-priority"] = _policy_evict_lowest


class ElasticReplanner:
    """Turns TopologyChange events into (new topology, new plan)."""

    def __init__(
        self,
        policy: str = "pause-resolve-resume",
        degrade_factor: float = 2.0,
    ):
        if policy not in _POLICIES:
            raise ValueError(
                f"unknown recovery policy {policy!r}; built-ins: {RECOVERY_POLICIES}, "
                f"registered: {sorted(_POLICIES)}"
            )
        self.policy = policy
        self.degrade_factor = degrade_factor

    # ----------------------------------------------------------- strategies
    def _synthesize(self, task, capacity: int) -> List[int]:
        """Give ``task`` schedulable strategies at sizes <= capacity it was
        never profiled at, from the Amdahl fit over its measured points.

        Memory feasibility below the smallest measured size was never
        checked (the trial runner refuses to extrapolate there for exactly
        that reason) — a preemption forces the call anyway; the synthesized
        strategy is flagged ``interpolated`` and an execution failure lands
        in the ordinary retry/evict path.
        """
        from saturn_tpu.trial_runner.evaluator import _fit_scaling_model

        feas = task.feasible_strategies()
        pts = [(g, s.per_batch_time) for g, s in feas.items() if s.per_batch_time > 0]
        if not pts:
            return []
        if len(pts) >= 2:
            model = _fit_scaling_model(pts)
        else:
            g0, t0 = pts[0]
            model = lambda g: t0 * g0 / float(g)  # pure-parallel: pessimistic on shrink
        anchor_g = min(pts, key=lambda p: p[0])[0]
        anchor = feas[anchor_g]
        # When every anchor point is itself a shardflow cold-start prior
        # (no trial has run yet), the fit is priors-all-the-way-down: the
        # synthesized strategy must carry ``static_prior`` too, so the
        # solver journal doesn't launder an untested estimate into a
        # "measured" plan.
        all_static = all(
            getattr(feas[g], "static_prior", False) for g, _ in pts
        )
        # The schedule bubble is analytic in the anchor's params (stage and
        # microbatch counts survive the re-synthesis unchanged), so the
        # synthesized strategy keeps pricing co-location correctly — an
        # Amdahl fit can estimate the runtime but not the schedule shape.
        bubble = 0.0
        bf = getattr(anchor.executor, "config_bubble_fraction", None)
        if callable(bf) and anchor.params:
            try:
                bubble = min(max(float(bf(anchor.params)), 0.0), 1.0)
            except Exception:
                bubble = 0.0
        added: List[int] = []
        g = capacity
        while g >= 1:
            if g not in feas and g <= capacity:
                pbt = max(float(model(g)), 1e-9)
                task.strategies[g] = Strategy(
                    executor=anchor.executor,
                    apportionment=g,
                    params=dict(anchor.params or {}),
                    runtime=pbt * max(task.total_batches, 0),
                    per_batch_time=pbt,
                    interpolated=True,
                    static_prior=all_static,
                    bubble_fraction=bubble,
                )
                added.append(g)
                break  # one synthesized size (the largest fitting) is enough
            if g in feas:
                break  # a real profile fits — nothing to synthesize
            g >>= 1
        return added

    # --------------------------------------------------------------- replan
    def replan(
        self,
        task_list: Sequence,
        base_topology: SliceTopology,
        alive_indices: Sequence[int],
        change,
        previous_plan: Optional[milp.Plan] = None,
        time_limit: Optional[float] = None,
    ) -> ReplanResult:
        """Rebuild topology + plan for the surviving fleet.

        ``alive_indices`` index into ``base_topology.devices`` (the monitor's
        view); tasks made unschedulable even after synthesis are evicted
        under every policy. Emits ``replan`` metrics; the caller emits the
        ``topology_change`` event (it owns the metrics scope timing).
        """
        topo = base_topology.subset(alive_indices)
        cap = topo.capacity

        # Memlens destination-fit gate: with a known per-device HBM
        # capacity, the static liveness analysis vets the surviving mesh
        # before any migration commits — both at keep/evict time (a task
        # whose every fitting strategy is predicted OOM on the degraded
        # mesh is evicted, not resharded into an OOM loop) and per planned
        # migration below. Fails open whenever capacity or a trace is
        # unknown.
        try:
            from saturn_tpu.analysis.memlens import passes as ml_passes
            cap_bytes = ml_passes.hbm_capacity_bytes(topo.devices)
        except Exception:
            ml_passes, cap_bytes = None, 0

        synthesized: Dict[str, List[int]] = {}
        keep: List = []
        evicted: List[str] = []
        for t in task_list:
            if not _runnable(t, cap):
                added = self._synthesize(t, cap)
                if added:
                    synthesized[t.name] = added
            if not _runnable(t, cap):
                evicted.append(t.name)
                log.warning(
                    "replan: task %s cannot run on %d-device mesh — evicting",
                    t.name, cap,
                )
            elif cap_bytes > 0 and not ml_passes.task_fits_mesh(
                    t, topo, cap_bytes):
                evicted.append(t.name)
                log.warning(
                    "replan: task %s predicted over HBM at every fitting "
                    "size on the %d-device mesh (memlens) — evicting",
                    t.name, cap,
                )
            else:
                keep.append(t)

        ctx = ReplanContext(
            topology=topo,
            previous_plan=previous_plan,
            previous_makespan=previous_plan.makespan if previous_plan else 0.0,
            change_kind=getattr(change, "kind", "shrink"),
            degrade_factor=self.degrade_factor,
        )
        keep, policy_evicted = _POLICIES[self.policy](keep, ctx)
        evicted.extend(t.name for t in policy_evicted)

        if not keep:
            plan = milp.Plan(assignments={}, makespan=0.0)
        elif self.policy == "degrade-in-place":
            plan = self._degrade_in_place(keep, topo, previous_plan)
        else:
            # Speculative re-solve through the anytime tier ladder.  The old plan
            # may reference dead devices, so it seeds the ladder (``warm``) but is
            # never kept via compare-and-swap (``previous=None``).
            dl = anytime.resolve_deadline(time_limit)
            plan = anytime.anytime_resolve(
                keep, topo, None, dl * 2.0,
                deadline=dl, warm=previous_plan, source="replan",
            )

        migrations = (
            plan.migrations_from(previous_plan) if previous_plan is not None else {}
        )
        # Destination-fit check per planned migration: the restored
        # checkpoint shards plus the steady-state peak must fit the
        # destination block. The verdict is attached to the migration
        # record the caller commits from; a predicted misfit is flagged
        # loudly (the pre-solve eviction above catches the deterministic
        # cases, so a flag here means the chosen size specifically drifted).
        memlens_blocked: List[str] = []
        if cap_bytes > 0:
            by_name = {t.name: t for t in task_list}
            for name, d in migrations.items():
                if not d.get("moved"):
                    continue
                t = by_name.get(name)
                a = plan.assignments.get(name)
                if t is None or a is None:
                    continue
                fit = ml_passes.migration_fits(
                    t, topo, a.apportionment, cap_bytes)
                if fit is None:
                    continue
                d["memlens"] = fit
                if not fit["fits"]:
                    memlens_blocked.append(name)
                    log.warning(
                        "replan: migrating %s to %d chips is predicted over "
                        "HBM (%d B restored shards + peak %d B > %d B)",
                        name, a.apportionment, fit["restored_shard_bytes"],
                        fit["peak_bytes"], cap_bytes,
                    )
        metrics.event(
            "replan",
            policy=self.policy,
            capacity=cap,
            n_tasks=len(keep),
            evicted=sorted(evicted),
            synthesized={k: v for k, v in synthesized.items()},
            makespan_s=plan.makespan,
            migrated=sorted(n for n, d in migrations.items() if d["moved"]),
            memlens_blocked=sorted(memlens_blocked),
        )
        return ReplanResult(
            topology=topo,
            plan=plan,
            evicted=evicted,
            synthesized=synthesized,
            migrations=migrations,
        )

    @staticmethod
    def _degrade_in_place(task_list, topo: SliceTopology, previous: Optional[milp.Plan]) -> milp.Plan:
        """No-solver recovery: clamp each task's previous size to the new
        capacity (largest feasible power of two <= min(prev, capacity)) and
        list-schedule in previous start order via the shared
        ``DeviceTimeline`` primitive. Falls back to greedy when a task has
        no previous assignment."""
        timeline = milp.DeviceTimeline(topo.capacity)

        def prev_start(t):
            a = previous.assignments.get(t.name) if previous else None
            return a.start if a is not None else float("inf")

        assignments: Dict[str, milp.Assignment] = {}
        for t in sorted(task_list, key=prev_start):
            prev_a = previous.assignments.get(t.name) if previous else None
            want = min(prev_a.apportionment, topo.capacity) if prev_a else topo.capacity
            sizes = [g for g in t.feasible_strategies() if g <= want] or [
                g for g in t.feasible_strategies() if g <= topo.capacity
            ]
            size = max(sizes)
            strat = t.feasible_strategies()[size]
            best = None  # (start, block)
            for blk in topo.blocks(size):
                st = timeline.earliest_free(blk, strat.runtime + 1.0)
                if best is None or st < best[0]:
                    best = (st, blk)
            st, blk = best
            timeline.occupy(blk, st, st + strat.runtime + 1.0)
            assignments[t.name] = milp.Assignment(size, blk, st, strat.runtime)
        makespan = max(
            (a.start + a.runtime for a in assignments.values()), default=0.0
        )
        plan = milp.Plan(assignments=assignments, makespan=makespan)
        plan.compute_dependencies()
        return plan


# ----------------------------------------------------------------- defrag
@dataclass(frozen=True)
class DefragMove:
    """One planned victim relocation: release the task's device-resident
    live state (its checkpoint is current at every interval boundary) and
    point its next restore at ``to_block`` instead of ``from_block``."""

    task: str
    from_block: Tuple[int, int]  # (offset, size)
    to_block: Tuple[int, int]
    pinned_bytes: int            # per-device HBM the move frees on the source
    memlens: Optional[dict] = None

    def to_fields(self) -> dict:
        d = {
            "task": self.task,
            "from": list(self.from_block),
            "to": list(self.to_block),
            "pinned_bytes": self.pinned_bytes,
        }
        if self.memlens is not None:
            d["memlens"] = self.memlens
        return d


@dataclass
class DefragWave:
    """A planned compaction wave: moves to execute, gangs that fit after."""

    moves: List[DefragMove] = field(default_factory=list)
    admitted: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    still_blocked: List[str] = field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not self.moves and not self.admitted


def plan_defrag_wave(
    blocked_tasks: Sequence,
    live_tasks: Sequence,
    topology: SliceTopology,
    previous_plan: Optional[milp.Plan],
    resident_bytes: Callable,
    cap_bytes: Optional[int] = None,
) -> DefragWave:
    """Plan a defragmentation wave: compact running jobs onto other blocks
    so a deferred gang's HBM footprint fits somewhere.

    Between intervals a task's train state stays device-resident
    (``task._live_state``) to skip the disk round-trip; that pinned HBM is
    what blocks a large deferred gang even when the *schedule* has room.
    This planner is occupancy-driven: per destination block it selects the
    pinned live tasks overlapping it as victims, finds each victim a
    relocation block with headroom (same size first, then halved feasible
    sizes — "fewer slices"), and admits the gang when every victim
    relocates and the gang's predicted peak fits the freed block.

    ``resident_bytes(task) -> int`` reports the per-device bytes a task's
    live state pins (0/unknown = not counted: the gate fails open, matching
    memlens convention). Deterministic: all candidate orders are sorted.
    The caller executes the moves (two-phase journal) and re-drains.
    """
    if cap_bytes is None:
        try:
            from saturn_tpu.analysis.memlens import passes as ml_passes
            cap_bytes = ml_passes.hbm_capacity_bytes(topology.devices)
        except Exception:
            cap_bytes = 0
    wave = DefragWave()
    if cap_bytes <= 0:
        # No capacity model: occupancy never blocked anyone — nothing to do.
        wave.still_blocked = sorted(t.name for t in blocked_tasks)
        return wave

    try:
        from saturn_tpu.analysis.memlens import passes as ml_passes
    except Exception:
        ml_passes = None

    def _pinned(t) -> int:
        try:
            return max(0, int(resident_bytes(t) or 0))
        except Exception:
            return 0

    # Current placements of pinned live tasks: name -> (Block, task, bytes).
    placements: Dict[str, Tuple[Block, object, int]] = {}
    for t in live_tasks:
        a = previous_plan.assignments.get(t.name) if previous_plan else None
        b = _pinned(t)
        if a is not None and b > 0:
            placements[t.name] = (a.block, t, b)

    # Per-device pinned occupancy (device index -> bytes).
    occ: Dict[int, int] = {}
    for blk, _t, b in placements.values():
        for i in range(blk.offset, blk.end):
            occ[i] = occ.get(i, 0) + b

    def _headroom(blk: Block, extra_occupied: Dict[int, int]) -> int:
        used = max(
            occ.get(i, 0) + extra_occupied.get(i, 0)
            for i in range(blk.offset, blk.end)
        )
        return cap_bytes - used

    moved: Dict[str, Tuple[Block, Block, int, Optional[dict]]] = {}
    reserved: Dict[int, int] = {}  # this wave's placements (gangs + victims)

    def _per_device_need(task, size: int) -> int:
        if ml_passes is not None:
            try:
                fit = ml_passes.migration_fits(task, topology, size, cap_bytes)
            except Exception:
                fit = None
            if fit is not None:
                return int(fit["peak_bytes"])
        return _pinned(task)

    def _relocate(victim_name: str, forbidden: List[Block],
                  extra: Dict[int, int]) -> Optional[Tuple[Block, int]]:
        """Find a block the victim's live state can re-pin after restore."""
        blk, vt, vb = placements[victim_name]
        sizes: List[int] = []
        g = blk.size
        feas = set(vt.feasible_strategies())
        while g >= 1:
            if g in feas:
                sizes.append(g)
            g >>= 1
        for size in sizes:
            for cand in topology.blocks(size):
                if cand.overlaps(blk):
                    continue
                if any(cand.overlaps(f) for f in forbidden):
                    continue
                # Victim's own pinned bytes vacate its old block, which we
                # account for by excluding it below when checking overlap
                # with itself (cand never overlaps blk).
                if _headroom(cand, extra) >= vb:
                    return cand, size
        return None

    for bt in sorted(blocked_tasks, key=lambda t: t.name):
        feas = sorted(
            (g for g in bt.feasible_strategies() if g <= topology.capacity),
            reverse=True,
        )
        placed = False
        for size in feas:
            need = _per_device_need(bt, size)
            for dest in topology.blocks(size):
                victims = sorted(
                    n for n, (blk, _t, _b) in placements.items()
                    if n not in moved and blk.overlaps(dest)
                )
                # Occupancy on the destination if every victim vacates.
                extra = dict(reserved)
                trial_occ_delta: Dict[int, int] = {}
                for n in victims:
                    blk, _t, b = placements[n]
                    for i in range(blk.offset, blk.end):
                        trial_occ_delta[i] = trial_occ_delta.get(i, 0) - b
                merged = dict(extra)
                for i, d in trial_occ_delta.items():
                    merged[i] = merged.get(i, 0) + d
                if _headroom(dest, merged) < need:
                    continue
                # Find every victim a home outside the destination.
                relocs: List[Tuple[str, Block, Block, int]] = []
                trial_extra = dict(merged)
                ok = True
                for n in victims:
                    r = _relocate(n, [dest], trial_extra)
                    if r is None:
                        ok = False
                        break
                    cand, _sz = r
                    blk, _t, vb = placements[n]
                    for i in range(cand.offset, cand.end):
                        trial_extra[i] = trial_extra.get(i, 0) + vb
                    relocs.append((n, blk, cand, vb))
                if not ok:
                    continue
                # Commit the wave step.
                for n, blk, cand, vb in relocs:
                    _vblk, vt, _vb = placements[n]
                    fit = None
                    if ml_passes is not None:
                        try:
                            fit = ml_passes.migration_fits(
                                vt, topology, cand.size, cap_bytes)
                        except Exception:
                            fit = None
                    moved[n] = (blk, cand, vb, fit)
                    for i in range(blk.offset, blk.end):
                        occ[i] = occ.get(i, 0) - vb
                    for i in range(cand.offset, cand.end):
                        reserved[i] = reserved.get(i, 0) + vb
                for i in range(dest.offset, dest.end):
                    reserved[i] = reserved.get(i, 0) + need
                wave.admitted[bt.name] = (dest.offset, dest.size)
                placed = True
                break
            if placed:
                break
        if not placed:
            wave.still_blocked.append(bt.name)

    wave.moves = [
        DefragMove(
            task=n,
            from_block=(f.offset, f.size),
            to_block=(t.offset, t.size),
            pinned_bytes=b,
            memlens=fit,
        )
        for n, (f, t, b, fit) in sorted(moved.items())
    ]
    return wave
