"""One-command TPU session: run the chip checklist in priority order.

The axon tunnel can be down for hours and come back without warning
(round 3 lost its whole measurement window; round 4's tunnel never came
up). When a window opens, ONE command should capture everything the
VERDICT asks for, most important first, each step bounded so a mid-run
hang cannot eat the window:

1. step_variants  — attention x loss x scan-unroll matrix (VERDICT #1)
2. bench.py       — the headline number + MFU
3. config_sweeps --config 2 — first on-chip multi-job makespan (VERDICT #3)
4. billion_scale  — gptj-1b3 under offload stream (VERDICT #4)
5. memory_contract — predicted-vs-actual HBM rows
6. longcontext_bench --mode chip — seq-scaling rows

Each step is a subprocess with its own timeout; results and tails land in
one JSONL (default /tmp/chip_session.jsonl) and stdout. Steps that fail
or time out are recorded and the session continues. Probes the tunnel
first (bounded) and exits 2 immediately if it is down.

Run: PYTHONPATH=/root/repo:$PYTHONPATH python benchmarks/chip_session.py
     [--only step_variants bench] [--log PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STEPS = [
    ("step_variants", [sys.executable, "benchmarks/step_variants.py"], 2400),
    ("bench", [sys.executable, "bench.py"], 900),
    ("config2", [sys.executable, "benchmarks/config_sweeps.py",
                 "--config", "2"], 2400),
    ("billion_scale", [sys.executable, "benchmarks/billion_scale.py"], 2400),
    ("memory_contract", [sys.executable, "benchmarks/memory_contract.py"],
     3600),
    ("longcontext", [sys.executable, "benchmarks/longcontext_bench.py",
                     "--mode", "chip"], 2400),
]


def probe(timeout_s: float = 90.0) -> bool:
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); print('PLAT='+d[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        return any(line.startswith("PLAT=") and "cpu" not in line
                   for line in r.stdout.splitlines())
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--log", default="/tmp/chip_session.jsonl")
    ap.add_argument("--only", nargs="*", default=None,
                    choices=[n for n, _, _ in STEPS],
                    help="subset of step names to run, in the given order")
    ap.add_argument("--skip-probe", action="store_true")
    args = ap.parse_args()

    if not args.skip_probe and not probe():
        print("chip_session: tunnel down (probe failed) — aborting",
              file=sys.stderr)
        raise SystemExit(2)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    steps = STEPS
    if args.only:
        by_name = dict((n, (n, c, t)) for n, c, t in STEPS)
        steps = [by_name[n] for n in args.only]

    with open(args.log, "a") as logf:
        for name, cmd, budget in steps:
            t0 = time.time()
            rec = {"step": name, "cmd": " ".join(cmd), "started": t0}
            print(f"== chip_session: {name} (budget {budget}s) ==",
                  flush=True)
            try:
                r = subprocess.run(
                    cmd, cwd=REPO, env=env, capture_output=True, text=True,
                    timeout=budget,
                )
                rec["rc"] = r.returncode
                rec["tail"] = (r.stdout or "")[-4000:]
                rec["stderr_tail"] = (r.stderr or "")[-1500:]
                print(rec["tail"])
            except subprocess.TimeoutExpired as e:
                def _tail(stream):
                    s = stream or b""
                    if isinstance(s, bytes):
                        s = s.decode(errors="replace")
                    return s[-4000:]

                rec["rc"] = "timeout"
                rec["tail"] = _tail(e.stdout)
                # stderr carries the diagnostic text (XLA errors, hang
                # traces) for exactly the steps that need diagnosis
                rec["stderr_tail"] = _tail(e.stderr)
                print(f"chip_session: {name} timed out after {budget}s",
                      file=sys.stderr)
            rec["wall_s"] = round(time.time() - t0, 1)
            logf.write(json.dumps(rec) + "\n")
            logf.flush()
    print(f"chip_session: done, log at {args.log}")


if __name__ == "__main__":
    main()
