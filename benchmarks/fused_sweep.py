"""Fused multi-model stacking microbenchmark: 8-member sweep, one program.

Round 21's tentpole claim, measured end-to-end through the engine: N sweep
jobs that differ only in learning rate (same test-tiny GPT-2, same batch
shape, same optimizer family) train as ONE compiled SPMD program — params
and optimizer state stacked along a leading ``model`` axis, the step
function vmapped over it, per-member LR passed as a stacked array
(``parallel/fused.py``). The baseline is the pre-round-21 best for the same
sweep: co-scheduled pairs, each pair interleaving its solo programs on a
shared block.

Prints ONE JSON line (self-validated by ``bench_guard.validate_fused_row``
before printing — a row whose fused members diverged from their solo
references is refused, not recorded):

    {"metric": "fused_sweep_tokens_per_sec", "value": <fused aggregate>,
     "workload": "fused_sweep", "n_members": 8,
     "coscheduled_tokens_per_sec": ..., "speedup_vs_coschedule": ...,
     "loss_divergence": 0.0, ...}

``workload`` makes the row shape-distinct for ``bench_guard.py``: a fused
record never gates a ``bench.py`` record or vice versa.

Hardware-free by construction (CPU forced before jax imports) and sized for
a one-core CI host: at toy model sizes per-program dispatch overhead
dominates, which is exactly the regime the paper's sweep workloads live in
— N tiny programs pay N dispatch/readback pipelines, the stack pays one.
The members' loss trajectories are REQUIRED to match their co-scheduled
(= solo-program) references: the speedup must come from stacking, never
from changing the math. Run: ``python benchmarks/fused_sweep.py``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import timeit

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

from benchmarks.bench_guard import validate_fused_row
from saturn_tpu import HParams, Task
from saturn_tpu.core.mesh import Block, SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.data.lm_dataset import make_lm_dataset
from saturn_tpu.executor import engine
from saturn_tpu.models.gpt2 import build_gpt2
from saturn_tpu.models.loss import pretraining_loss
from saturn_tpu.parallel import fused
from saturn_tpu.parallel.dp import DataParallel
from saturn_tpu.solver.milp import Assignment, Plan
from saturn_tpu.utils import checkpoint as ckpt
from saturn_tpu.utils import metrics

SEQ_LEN = 16
BATCH_SIZE = 1
N_MEMBERS = 8
N_BATCHES = 24          # per member; every member retires all of them
WINDOW = 8


def make_member(save_root: str, i: int) -> Task:
    def loader():
        return make_lm_dataset(
            context_length=SEQ_LEN, batch_size=BATCH_SIZE, vocab_size=256,
            n_tokens=SEQ_LEN * BATCH_SIZE * 32,
        )

    t = Task(
        get_model=lambda **kw: build_gpt2("test-tiny", seq_len=SEQ_LEN, **kw),
        get_dataloader=loader,
        loss_fn=pretraining_loss,
        # LR is the sweep axis: it rides along as a stacked hparam and is
        # excluded from the fusion fingerprint, so all N members fuse.
        hparams=HParams(lr=1e-3 * (1.0 + 0.05 * i), batch_count=N_BATCHES),
        chip_range=[1],
        name=f"sweep{i}",
        save_dir=os.path.join(save_root, f"sweep{i}"),
    )
    t.strategies = {
        1: Strategy(executor=DataParallel(), apportionment=1, params={},
                    runtime=1.0, per_batch_time=0.01)
    }
    return t


def run_coscheduled_pairs(tmp: str, metrics_path: str) -> float:
    """Baseline arm: the sweep as 4 co-scheduled pairs, each pair
    interleaving its two solo programs on its own one-device block."""
    members = [make_member(os.path.join(tmp, "cos"), i)
               for i in range(N_MEMBERS)]
    assignments = {}
    groups = []
    for p in range(N_MEMBERS // 2):
        a, b = members[2 * p], members[2 * p + 1]
        for t in (a, b):
            assignments[t.name] = Assignment(1, Block(p, 1), 0.0, 1.0)
        groups.append([a.name, b.name])
    plan = Plan(assignments=assignments, makespan=1.0, coschedule=groups)
    plan.compute_dependencies()
    topo = SliceTopology(jax.devices())
    # warm every pair's programs outside the timed region (compile tax is
    # not the thing under test)
    for t in members:
        tech = t.strategies[1].executor
        block = plan.assignments[t.name].block
        bundle = tech.build(t, topo.block_devices(block), {})
        bundle.fused_compiled(WINDOW)
        _ = bundle.compiled
    batches = {t.name: N_BATCHES for t in members}
    with metrics.scoped(metrics_path):
        t0 = timeit.default_timer()
        errors = engine.execute(members, batches, 300.0, plan, topo)
        dt = timeit.default_timer() - t0
    if errors:
        raise RuntimeError(f"co-scheduled arm failed: {errors}")
    return dt


def run_fused_stack(tmp: str, metrics_path: str) -> float:
    """Fused arm: the whole sweep as one stacked program through the
    engine's fused launcher (``Plan.fused`` group)."""
    members = [make_member(os.path.join(tmp, "fus"), i)
               for i in range(N_MEMBERS)]
    assignments = {
        t.name: Assignment(1, Block(0, 1), 0.0, 1.0) for t in members
    }
    plan = Plan(assignments=assignments, makespan=1.0,
                fused=[[t.name for t in members]])
    plan.compute_dependencies()
    topo = SliceTopology(jax.devices())
    # warm the stacked program outside the timed region
    devices = topo.block_devices(Block(0, 1))
    prog = fused.build_fused_program(members, devices)
    prog.window_compiled(WINDOW)
    prog.single_compiled()
    batches = {t.name: N_BATCHES for t in members}
    with metrics.scoped(metrics_path):
        t0 = timeit.default_timer()
        errors = engine.execute(members, batches, 300.0, plan, topo)
        dt = timeit.default_timer() - t0
    if errors:
        raise RuntimeError(f"fused arm failed: {errors}")
    return dt


def read_losses(metrics_path: str) -> dict:
    """Per-member final losses, from either arm's event stream (solo
    programs emit ``task_interval``, the stack emits ``fused_interval``),
    rounded alike so the divergence check compares like with like."""
    losses: dict = {}
    for ev in metrics.read_events(metrics_path, kind="task_interval"):
        losses[ev["task"]] = round(float(ev["loss"]), 6)
    for ev in metrics.read_events(metrics_path, kind="fused_interval"):
        for name, v in (ev.get("losses") or {}).items():
            losses[name] = round(float(v), 6)
    return losses


def main() -> int:
    os.environ.setdefault("SATURN_TPU_MAX_WINDOW", str(WINDOW))
    with tempfile.TemporaryDirectory() as tmp:
        cos_events = os.path.join(tmp, "cos.jsonl")
        fus_events = os.path.join(tmp, "fus.jsonl")
        t_cos = run_coscheduled_pairs(tmp, cos_events)
        t_fus = run_fused_stack(tmp, fus_events)
        solo_losses = read_losses(cos_events)
        fused_losses = read_losses(fus_events)
        # drain async checkpoint writers before the tmp dir disappears
        ckpt.flush()
    divergence = max(
        abs(fused_losses.get(f"sweep{i}", float("inf"))
            - solo_losses.get(f"sweep{i}", float("-inf")))
        for i in range(N_MEMBERS)
    )
    total_tokens = N_MEMBERS * N_BATCHES * BATCH_SIZE * SEQ_LEN
    out = {
        "metric": "fused_sweep_tokens_per_sec",
        "value": round(total_tokens / t_fus, 1),
        "workload": "fused_sweep",
        "platform": jax.devices()[0].platform,
        "n_members": N_MEMBERS,
        "batches_per_member": N_BATCHES,
        "batch_size": BATCH_SIZE,
        "seq_len": SEQ_LEN,
        "window": WINDOW,
        "coscheduled_tokens_per_sec": round(total_tokens / t_cos, 1),
        "fused_s": round(t_fus, 3),
        "coscheduled_s": round(t_cos, 3),
        "speedup_vs_coschedule": round(t_cos / t_fus, 3),
        "loss_divergence": divergence,
        "status": "ok",
    }
    problems = validate_fused_row(out)
    if problems:
        out["status"] = "invalid"
        out["problems"] = problems
        print(json.dumps(out))
        return 1
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
