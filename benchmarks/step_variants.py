"""A/B matrix for the config-#1 train step: attention x loss x scan-unroll.

One run produces every pending chip measurement for the MFU work
(VERDICT r2 item 1c): flash vs dense attention, fused vs logits
cross-entropy, and the layer-scan unroll factor (the round-3 trace showed
the scan's activation-stash dynamic-update-slices dragging MLP matmul
fusions to ~0.4-0.5 efficiency — unrolling lets XLA address the stash
statically at the cost of compile time).

Timing protocol matches bench.py: donated state, compile+warmup excluded,
queued steps with ONE host sync (the tunneled TPU adds ~70ms round-trip per
sync, so per-call block_until_ready would swamp the signal).

Run: ``python benchmarks/step_variants.py [--attentions flash dense]
[--losses fused logits] [--unrolls 1 4 12]``
Prints a markdown table for BASELINE.md; flags the fastest variant.
"""

from __future__ import annotations

import argparse
import itertools
import timeit


def time_variant(preset, batch, seq, attention, loss, unroll, n_timed=20):
    import jax
    import jax.numpy as jnp
    import optax

    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    spec = build_gpt2(
        preset, seq_len=seq, attention=attention, scan_unroll=unroll
    )
    ds = make_lm_dataset(
        context_length=seq, batch_size=batch,
        vocab_size=spec.config.vocab_size, n_tokens=seq * batch * 8,
    )
    tx = optax.adamw(3e-4)

    if loss == "fused":
        if spec.fused_loss_fn is None:
            # don't silently time the logits path under a 'fused' label
            raise ValueError(f"{preset} has no fused loss (moe/non-causal)")
        loss_of = spec.fused_loss_fn
    else:
        loss_of = lambda p, b: pretraining_loss(spec.apply_fn(p, b), b)

    def init_state():
        p = spec.init_fn(jax.random.PRNGKey(0))
        return {"params": p, "opt": tx.init(p)}

    def step(state, batch):
        l, g = jax.value_and_grad(loss_of)(state["params"], batch)
        up, opt = tx.update(g, state["opt"], state["params"])
        return {"params": optax.apply_updates(state["params"], up),
                "opt": opt}, l

    jstep = jax.jit(step, donate_argnums=(0,))
    state = jax.jit(init_state)()
    batches = [jnp.asarray(ds.batch(i)) for i in range(4)]
    t0 = timeit.default_timer()
    for _ in range(3):
        state, l = jstep(state, batches[0])
    float(jax.device_get(l))          # sync: see utils/timing.py
    compile_s = timeit.default_timer() - t0

    t0 = timeit.default_timer()
    for i in range(n_timed):
        state, l = jstep(state, batches[i % len(batches)])
    float(jax.device_get(l))
    dt = (timeit.default_timer() - t0) / n_timed
    del state
    return dt, compile_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2-small")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--attentions", nargs="+", default=["flash", "dense"])
    ap.add_argument("--losses", nargs="+", default=["fused", "logits"])
    ap.add_argument("--unrolls", type=int, nargs="+", default=[1, 4, 12])
    args = ap.parse_args()

    import jax

    if jax.default_backend() != "tpu":
        raise SystemExit("variant timing is only meaningful on the TPU")

    print(f"preset={args.preset} b{args.batch}x{args.seq} "
          f"({jax.devices()[0].device_kind})\n")
    print("| attention | loss | unroll | ms/step | tokens/s | compile s |")
    print("|---|---|---|---|---|---|", flush=True)
    best = None
    for attn, loss, unroll in itertools.product(
        args.attentions, args.losses, args.unrolls
    ):
        try:
            dt, compile_s = time_variant(
                args.preset, args.batch, args.seq, attn, loss, unroll
            )
            tps = args.batch * args.seq / dt
            row = (attn, loss, unroll, dt)
            if best is None or dt < best[3]:
                best = row
            print(f"| {attn} | {loss} | {unroll} | {dt*1e3:.1f} "
                  f"| {tps:,.0f} | {compile_s:.0f} |", flush=True)
        except Exception as e:
            print(f"| {attn} | {loss} | {unroll} | FAIL "
                  f"({type(e).__name__}: {str(e)[:60]}) | | |", flush=True)
    if best:
        print(f"\nfastest: attention={best[0]} loss={best[1]} "
              f"unroll={best[2]} at {best[3]*1e3:.1f} ms/step")


if __name__ == "__main__":
    main()
