"""Twin at scale: the real control plane over 100k jobs and 32 virtual slices.

Two phases, both through :mod:`saturn_tpu.twin`:

1. **Scale row** — synthesize >= 100k jobs (Poisson + diurnal bursts, the
   same seeded generator the gateway bench uses) against a 32-slice /
   256-chip virtual fleet. Every submission passes through the *real*
   gateway window, the *real* admission controller and the *real* anytime
   solver tier ladder racing its actual CPU-time deadline — only chip time
   and the clock are simulated. Acceptance bar: **zero** solver deadline
   misses across the whole campaign.

2. **Fidelity row** — run the real gateway bench (``benchmarks/
   online_arrivals.py``, 500 jobs over real sockets and threads) with its
   write-ahead journal on, replay that journal through the twin, and check
   the twin's solver-tier shares / admission verdict mix / makespan against
   journaled reality within the documented band
   (``saturn_tpu.twin.trace.DEFAULT_BAND``).

Prints one JSON line per phase (the scale row last — it is the headline)
and self-validates against ``bench_guard.TWIN_ROW_REQUIRED`` before
printing:

    {"metric": "twin_fidelity", "within_band": true, ...}
    {"metric": "twin_scale", "n_jobs": 100000, "n_slices": 32,
     "deadline_misses": 0, "tier_counts": {...}, "status": "ok", ...}

Run: ``python benchmarks/twin_scale.py`` (``--quick`` shrinks the scale
phase to 2k jobs / 8 slices for smoke runs; ``--skip-fidelity`` drops the
real-service phase).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

from saturn_tpu.twin.runner import CampaignConfig, run_campaign
from saturn_tpu.twin.trace import fidelity_compare, load_trace, tier_shares

SEED = 7

#: Scale-phase shape. ~2.4k arrivals per 600-simulated-second interval keep
#: the live set at a size the LP-rounding tier solves in ~2-4s of real CPU
#: time — comfortably inside the 5s budget (measured; the zero-deadline-miss
#: bar is checked, not assumed). The inflight window is sized ABOVE the peak
#: live set on purpose: this row measures scheduling throughput, and a shed
#: job never reaches the solver (the shed path gets its workout from the
#: gateway bench and the fidelity replay below).
FULL = dict(n_jobs=100_000, n_slices=32, base_rate_hz=4.0,
            burst_rate_hz=12.0, max_inflight=8_000)
QUICK = dict(n_jobs=2_000, n_slices=8, base_rate_hz=4.0,
             burst_rate_hz=12.0, max_inflight=4_000)

#: Fidelity-phase twin shape: must mirror the real gateway bench exactly —
#: same 8-chip mesh, same 0.2s interval (deadline = interval/2), same window,
#: and the bench's pre-profiled flat per-batch cost.
FIDELITY_JOBS = 500
FIDELITY_TWIN = dict(
    n_slices=1, chips_per_slice=8, interval_s=0.2, solve_deadline_s=0.1,
    max_inflight=12, flat_per_batch_s=0.004, metrics=False, seed=SEED,
)


def run_scale_phase(mode: str, out_dir: str, fidelity: dict) -> dict:
    shape = FULL if mode == "full" else QUICK
    cfg = CampaignConfig(
        n_jobs=shape["n_jobs"], n_slices=shape["n_slices"],
        chips_per_slice=8, interval_s=600.0, solve_deadline_s=5.0,
        base_rate_hz=shape["base_rate_hz"],
        burst_rate_hz=shape["burst_rate_hz"],
        total_batches=3, max_inflight=shape["max_inflight"],
        metrics=False, compact_every=8, seed=SEED, max_intervals=400,
    )
    s = run_campaign(cfg, out_dir)
    return {
        "metric": "twin_scale",
        "mode": mode,
        "n_jobs": cfg.n_jobs,
        "n_slices": cfg.n_slices,
        "chips": cfg.n_slices * cfg.chips_per_slice,
        "submitted": s["submitted"],
        "scheduled": s["admission"].get("admit", 0),
        "completed": s["completed"],
        "failed": s["failed"],
        "evicted": s["evicted"],
        "shed": s["shed_total"],
        "solves": s["solves"],
        "deadline_misses": s["deadline_misses"],
        "tier_counts": s["tier_counts"],
        "intervals": s["intervals"],
        "makespan_sim_s": s["makespan_s"],
        "wall_s": s["wall_s"],
        "sim_speedup": s["sim_speedup"],
        "seed": SEED,
        "fidelity": fidelity,
        "status": s["status"],
    }


def run_fidelity_phase(work_dir: str) -> dict:
    """Real gateway run -> journal -> twin replay -> band comparison."""
    from online_arrivals import run_gateway_phase
    from saturn_tpu import library as lib
    from saturn_tpu.core.mesh import SliceTopology
    import online_arrivals

    lib.register("bench-online", online_arrivals.BenchTech)
    topo = SliceTopology([online_arrivals.FakeDev() for _ in range(8)])
    durability_dir = os.path.join(work_dir, "real-journal")
    metrics_path = os.path.join(work_dir, "real-metrics.jsonl")
    real_row = run_gateway_phase(
        topo, n_jobs=FIDELITY_JOBS, durability_dir=durability_dir,
        metrics_path=metrics_path, seed=SEED,
    )
    real_trace = load_trace(durability_dir)
    real_side = {
        "tier_shares": tier_shares(metrics_path),
        "verdict_shares": real_trace.verdict_shares,
        "makespan_s": real_row["makespan_s"],
    }
    twin_cfg = CampaignConfig(trace_dir=durability_dir, **FIDELITY_TWIN)
    twin = run_campaign(twin_cfg, os.path.join(work_dir, "twin-replay"))
    twin_side = {
        "tier_shares": twin["tier_shares"],
        "verdict_shares": twin["verdict_shares"],
        "makespan_s": twin["makespan_s"],
    }
    cmp = fidelity_compare(twin_side, real_side)
    return {
        "metric": "twin_fidelity",
        "n_jobs": FIDELITY_JOBS,
        "real_accepted": real_row["accepted"],
        "real_shed": real_row["shed"],
        "twin_submitted": twin["submitted"],
        "twin_shed": twin["shed_total"],
        "twin_tier_shares": twin_side["tier_shares"],
        "real_tier_shares": real_side["tier_shares"],
        "twin_verdict_shares": twin_side["verdict_shares"],
        "real_verdict_shares": real_side["verdict_shares"],
        "twin_makespan_s": twin_side["makespan_s"],
        "real_makespan_s": real_side["makespan_s"],
        "deadline_misses": twin["deadline_misses"],
        "seed": SEED,
        **cmp,
    }


def main() -> None:
    quick = "--quick" in sys.argv[1:]
    skip_fidelity = "--skip-fidelity" in sys.argv[1:]
    work_dir = tempfile.mkdtemp(prefix="twin_scale_")
    try:
        fidelity: dict = {}
        if not skip_fidelity:
            fid_row = run_fidelity_phase(work_dir)
            print(json.dumps(fid_row))
            fidelity = {
                "within_band": fid_row["within_band"],
                "tier_share_deltas": fid_row["tier_share_deltas"],
                "verdict_share_deltas": fid_row["verdict_share_deltas"],
                "makespan_ratio": fid_row["makespan_ratio"],
            }
        row = run_scale_phase(
            "quick" if quick else "full",
            os.path.join(work_dir, "scale"), fidelity,
        )
        import bench_guard
        problems = bench_guard.validate_twin_row(row)
        if problems:
            raise SystemExit(f"twin row failed self-validation: {problems}")
        print(json.dumps(row))
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
