"""Long-context scaling rows: flash + fused-CE-recompute, ring, ulysses.

SURVEY.md §5 names long context as first-class; the single-chip story is
flash attention (O(T) memory) + the fused CE's recompute mode (zero O(N,V)
memory), and the multi-chip story is ring/Ulysses sequence parallelism.
This bench produces the BASELINE.md scaling table:

- single-chip: GPT-2-small at seq {2k, 4k, 8k, 16k} iso-token (batch
  shrinks as seq grows), flash + fused CE (stash auto-flips to recompute
  past STASH_BYTES_MAX) — tokens/s and peak HBM;
- CPU-mesh (--mode cpu, reduced shapes): ring and ulysses over a
  (data=1, seq=4) mesh at seq {256, 512} on the tiny preset — mechanism
  numbers proving the schedule scales, not throughput claims.

Run on TPU:  PYTHONPATH=/root/repo:$PYTHONPATH python \
    benchmarks/longcontext_bench.py --mode chip
CPU smoke:   python benchmarks/longcontext_bench.py --mode cpu
"""

from __future__ import annotations

import argparse
import json
import timeit


def _chip_rows(preset: str, seqs, tokens_per_step: int):
    import jax
    import jax.numpy as jnp
    import optax

    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    rows = []
    for seq in seqs:
        batch = max(tokens_per_step // seq, 1)
        spec = build_gpt2(preset, seq_len=seq)
        ds = make_lm_dataset(
            context_length=seq, batch_size=batch,
            vocab_size=spec.config.vocab_size, n_tokens=seq * batch * 4,
        )
        tx = optax.adamw(3e-4)
        loss_of = spec.fused_loss_fn or (
            lambda p, b: pretraining_loss(spec.apply_fn(p, b), b)
        )

        def step(state, b):
            l, g = jax.value_and_grad(loss_of)(state["params"], b)
            up, opt = tx.update(g, state["opt"], state["params"])
            return {"params": optax.apply_updates(state["params"], up),
                    "opt": opt}, l

        jstep = jax.jit(step, donate_argnums=(0,))
        try:
            state = jax.jit(
                lambda: {"params": spec.init_fn(jax.random.PRNGKey(0)),
                         "opt": tx.init(spec.init_fn(jax.random.PRNGKey(0)))}
            )()
            batches = [jnp.asarray(ds.batch(i)) for i in range(2)]
            for _ in range(2):
                state, l = jstep(state, batches[0])
            float(jax.device_get(l))
            n_timed = 10
            t0 = timeit.default_timer()
            for i in range(n_timed):
                state, l = jstep(state, batches[i % 2])
            float(jax.device_get(l))
            dt = (timeit.default_timer() - t0) / n_timed
            stats = getattr(jax.devices()[0], "memory_stats", lambda: None)() or {}
            rows.append({
                "seq": seq, "batch": batch,
                "tokens_per_s": round(batch * seq / dt, 1),
                "step_s": round(dt, 4),
                "hbm_peak_gib": round(
                    stats.get("peak_bytes_in_use", 0) / 2**30, 2),
            })
        except Exception as e:  # OOM rows are data, not failures
            rows.append({"seq": seq, "batch": batch,
                         "error": type(e).__name__})
        finally:
            state = None
    return rows


def _cpu_mesh_rows(seqs):
    import numpy as np

    import jax

    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.loss import pretraining_loss
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.parallel.ring import RingSequenceParallel
    from saturn_tpu.parallel.ulysses import UlyssesSequenceParallel

    devices = jax.devices()[:4]
    rows = []
    for mode, tech in (("ring", RingSequenceParallel()),
                       ("ulysses", UlyssesSequenceParallel())):
        for seq in seqs:
            task = Task(
                get_model=lambda **kw: build_gpt2(
                    "test-tiny", seq_len=seq, **kw
                ),
                get_dataloader=lambda: make_lm_dataset(
                    context_length=seq, batch_size=2, vocab_size=256,
                    n_tokens=seq * 2 * 3,
                ),
                loss_fn=pretraining_loss,
                hparams=HParams(lr=1e-3, batch_count=2),
                save_dir="/tmp/saturn_longctx_ckpts",
            )
            bundle = tech.build(task, devices, {"sp": 4, "remat": True})
            state = bundle.init()
            b = jax.device_put(task.batch_at(0), bundle.batch_sharding)
            t0 = timeit.default_timer()
            state, loss = bundle.step(state, b)
            lv = float(jax.device_get(loss))
            dt = timeit.default_timer() - t0
            assert np.isfinite(lv)
            rows.append({"mode": mode, "seq": seq, "sp": 4,
                         "first_step_s": round(dt, 1),
                         "loss": round(lv, 3)})
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["chip", "cpu"], required=True)
    ap.add_argument("--preset", default="gpt2-small")
    ap.add_argument("--tokens-per-step", type=int, default=16384,
                    help="chip mode: iso-token budget per step (must be >= "
                         "the largest seq or the table stops being "
                         "iso-token)")
    args = ap.parse_args()

    if args.mode == "cpu":
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4"
            + " --xla_cpu_collective_call_terminate_timeout_seconds=600"
        )
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        rows = _cpu_mesh_rows([256, 512])
    else:
        rows = _chip_rows(args.preset, [2048, 4096, 8192, 16384],
                          args.tokens_per_step)
    print(json.dumps({"metric": "long_context_scaling", "mode": args.mode,
                      "rows": rows}))


if __name__ == "__main__":
    main()
