"""Solver quality/scale harness (VERDICT r1 item 5).

Compares the native C++ scheduler (``native/spase.cpp``) against the exact
HiGHS MILP on random instances at MILP-tractable sizes (gap %), and
stress-tests the native path at the north-star scale (16-32 tasks, capacity
64 — the v4-64 flagship config, BASELINE.md) where the exact formulation's
O(N²·devices) big-M rows are far beyond any MILP budget.

Run: ``python benchmarks/solver_quality.py [--quick]``. Prints a markdown
table; paste into BASELINE.md. Hardware-free (solver consumes only numbers,
reference ``milp.py:77-81``).
"""

from __future__ import annotations

import argparse
import time

import numpy as np

from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.solver import native_sched
from saturn_tpu.solver.milp import greedy_plan, makespan_lower_bound, solve


class _Dev:
    pass


class _Task:
    def __init__(self, name, runtimes):
        self.name = name
        self.strategies = {
            g: Strategy(object(), g, {}, rt, 0.1) for g, rt in runtimes.items()
        }

    def feasible_strategies(self):
        return self.strategies


def rand_tasks(n, cap, rng):
    """Random HPO-batch-like instances: per-task base runtime 20-200s,
    sublinear scaling across power-of-two sizes (efficiency 0.6-0.95)."""
    sizes = [s for s in (1, 2, 4, 8, 16, 32, 64) if s <= cap]
    tasks = []
    for i in range(n):
        base = float(rng.uniform(20, 200))
        rts = {s: base / (s ** float(rng.uniform(0.6, 0.95))) for s in sizes}
        tasks.append(_Task(f"t{i}", rts))
    return tasks


def topo(cap):
    return SliceTopology([_Dev() for _ in range(cap)])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="fewer seeds, shorter limits")
    args = ap.parse_args()
    seeds = range(3) if args.quick else range(5)
    exact_limit = 30.0 if args.quick else 120.0

    print("## native scheduler vs exact MILP (capacity 8)\n")
    print("| n tasks | exact mk (mean) | native mk (mean) | gap mean | gap max | exact vs LB | exact s | native s |")
    print("|---|---|---|---|---|---|---|---|")
    for n in (6, 8, 10, 12):
        gaps, e_mks, n_mks, e_ts, n_ts, e_lb_gaps = [], [], [], [], [], []
        for seed in seeds:
            rng = np.random.default_rng(1000 * n + seed)
            tasks = rand_tasks(n, 8, rng)
            t0 = time.perf_counter()
            ep = solve(tasks, topo(8), time_limit=exact_limit, ordering_slack=0.0)
            e_ts.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            np_ = native_sched.solve_native(
                tasks, topo(8), time_limit=2.0, ordering_slack=0.0
            )
            n_ts.append(time.perf_counter() - t0)
            gaps.append(np_.makespan / ep.makespan - 1.0)
            e_mks.append(ep.makespan)
            n_mks.append(np_.makespan)
            e_lb_gaps.append(ep.makespan / makespan_lower_bound(tasks, topo(8)) - 1.0)
        # exact-vs-LB calibrates the LB's looseness where the optimum is known
        print(
            f"| {n} | {np.mean(e_mks):.1f} | {np.mean(n_mks):.1f} "
            f"| {100*np.mean(gaps):+.1f}% | {100*np.max(gaps):+.1f}% "
            f"| +{100*np.mean(e_lb_gaps):.1f}% "
            f"| {np.mean(e_ts):.1f} | {np.mean(n_ts):.1f} |"
        )

    print("\n## native scheduler at north-star scale (capacity 64)\n")
    print("LB = makespan_lower_bound (longest-task / whole-ring-serial /")
    print("assignment-LP max) — a LOOSE bound: 'vs LB' overstates the true")
    print("optimality gap (VERDICT r2 item 5).\n")
    print("| n tasks | greedy mk | native mk (1s) | native mk (5s) | vs greedy | LB | native 5s vs LB | native 5s wall |")
    print("|---|---|---|---|---|---|---|---|")
    for n in (16, 24, 32):
        g_mks, n1_mks, n5_mks, n5_ts, lbs, lb_gaps = [], [], [], [], [], []
        for seed in seeds:
            rng = np.random.default_rng(2000 * n + seed)
            tasks = rand_tasks(n, 64, rng)
            gp = greedy_plan(tasks, topo(64))
            g_mks.append(gp.makespan)
            p1 = native_sched.solve_native(
                tasks, topo(64), time_limit=1.0, ordering_slack=0.0
            )
            n1_mks.append(p1.makespan)
            t0 = time.perf_counter()
            p5 = native_sched.solve_native(
                tasks, topo(64), time_limit=5.0, ordering_slack=0.0
            )
            n5_ts.append(time.perf_counter() - t0)
            n5_mks.append(p5.makespan)
            lb = makespan_lower_bound(tasks, topo(64))
            lbs.append(lb)
            lb_gaps.append(p5.makespan / lb - 1.0)
        print(
            f"| {n} | {np.mean(g_mks):.1f} | {np.mean(n1_mks):.1f} "
            f"| {np.mean(n5_mks):.1f} | {100*(np.mean(n5_mks)/np.mean(g_mks)-1):+.1f}% "
            f"| {np.mean(lbs):.1f} | +{100*np.mean(lb_gaps):.1f}% (max +{100*np.max(lb_gaps):.1f}%) "
            f"| {np.mean(n5_ts):.1f}s |"
        )


if __name__ == "__main__":
    main()
