"""Validate the memory-analysis contract on real hardware (VERDICT r2 item 6).

The framework replaced the reference's try/except OOM-probe loops
(``/root/reference/examples/wikitext103/executors/Spilled.py:68-87``) with
XLA compile-time memory analysis (``utils/timing.hbm_bytes_required``) gated
by a 0.92 headroom factor (``parallel/spmd_base.py::_fits_memory``). This
script proves the replacement on a chip: for each (model size, remat) it
compares the predicted peak HBM against the device's measured
``peak_bytes_in_use`` after one real step, and records whether the
feasibility verdict matched reality (a feasible-predicted config must not
OOM; an infeasible-predicted one is attempted anyway for calibration).

Each config runs in its OWN subprocess: ``peak_bytes_in_use`` is a
process-lifetime high-water mark with no reset API, so sharing a process
would make every row after the hungriest config report a stale peak.

Run on TPU: ``PYTHONPATH=/root/repo:$PYTHONPATH python
benchmarks/memory_contract.py``. Prints a markdown table for BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def run_one(preset: str, remat: bool, batch: int, seq: int) -> dict:
    """Measure one config (executed in a child process; prints JSON)."""
    import jax
    import jax.numpy as jnp
    import optax

    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss
    from saturn_tpu.utils.timing import device_hbm_bytes, hbm_bytes_required

    dev = jax.devices()[0]
    limit = device_hbm_bytes(dev)
    spec = build_gpt2(preset, seq_len=seq, remat=remat)
    ds = make_lm_dataset(
        context_length=seq, batch_size=batch,
        vocab_size=spec.config.vocab_size, n_tokens=seq * batch * 2,
    )
    tx = optax.adamw(3e-4)

    def init_state():
        p = spec.init_fn(jax.random.PRNGKey(0))
        return {"params": p, "opt": tx.init(p)}

    def step(state, b):
        def loss_of(p):
            return pretraining_loss(spec.apply_fn(p, b), b)

        loss, g = jax.value_and_grad(loss_of)(state["params"])
        up, opt = tx.update(g, state["opt"], state["params"])
        return {"params": optax.apply_updates(state["params"], up),
                "opt": opt}, loss

    out = {"preset": preset, "remat": remat, "limit": limit}
    shapes = jax.eval_shape(init_state)
    batch_sds = jax.ShapeDtypeStruct(
        ds.example_batch().shape, ds.example_batch().dtype
    )
    try:
        compiled = jax.jit(step, donate_argnums=(0,)).lower(
            shapes, batch_sds).compile()
        out["predicted"] = hbm_bytes_required(compiled)
    except Exception as e:
        # the compiler rejecting an over-HBM program IS the infeasible
        # verdict, with XLA's own accounting in the message
        msg = str(e)
        out["compile_oom"] = msg[max(msg.find("Used"), 0):][:80]
        return out

    try:
        state = jax.jit(init_state)()
        b = jnp.asarray(ds.batch(0))
        state, loss = compiled(state, b)
        float(jax.device_get(loss))
        stats = dev.memory_stats() or {}
        out["peak"] = stats.get("peak_bytes_in_use")
        out["ran"] = "ok"
    except Exception as e:
        out["ran"] = f"OOM ({type(e).__name__})"
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--presets", nargs="+",
        default=["gpt2-small", "gpt2-medium", "gpt2-large"],
    )
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--one", nargs=2, metavar=("PRESET", "REMAT"),
                    help="internal: measure a single config, print JSON")
    args = ap.parse_args()

    if args.one:
        print("RESULT " + json.dumps(
            run_one(args.one[0], args.one[1] == "1", args.batch, args.seq)
        ))
        return

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    print(f"batch={args.batch} seq={args.seq} (one subprocess per config — "
          f"peak_bytes_in_use is a process-lifetime high-water mark)\n")
    print("| preset | remat | predicted GiB | verdict (0.92 headroom) | "
          "actual peak GiB | pred/actual | ran? |")
    print("|---|---|---|---|---|---|---|", flush=True)
    for preset in args.presets:
        for remat in (False, True):
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--one", preset, "1" if remat else "0",
                 "--batch", str(args.batch), "--seq", str(args.seq)],
                capture_output=True, text=True, env=env, timeout=1200,
            )
            res = None
            for line in r.stdout.splitlines():
                if line.startswith("RESULT "):
                    res = json.loads(line[len("RESULT "):])
            if res is None:
                tail = (r.stderr or r.stdout).strip().splitlines()
                print(f"| {preset} | {remat} | child failed "
                      f"(rc={r.returncode}): {tail[-1][:60] if tail else ''} "
                      f"| | | | |", flush=True)
                continue
            if "compile_oom" in res:
                print(f"| {preset} | {remat} | compile-OOM | infeasible | — "
                      f"| — | no ({res['compile_oom'][:40]}) |", flush=True)
                continue
            limit, pred, peak = res["limit"], res["predicted"], res.get("peak")
            feasible = limit <= 0 or pred <= 0.92 * limit
            peak_s = f"{peak/2**30:.2f}" if peak else "—"
            ratio = f"{pred/peak:.2f}" if peak else "—"
            print(f"| {preset} | {remat} | {pred/2**30:.2f} "
                  f"| {'feasible' if feasible else 'infeasible'} "
                  f"| {peak_s} | {ratio} | {res['ran']} |", flush=True)


if __name__ == "__main__":
    main()
