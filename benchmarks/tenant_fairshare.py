"""Multi-tenant fairness under a noisy neighbour, through the real gateway.

Three phases against a live ``SaturnService`` + ``GatewayServer`` with a
:class:`~saturn_tpu.tenancy.TenantLedger` wired in:

1. **Solo baseline** — one quiet tenant alone on the gateway; records its
   p99 client-observed admission latency. This is the number the fairness
   bar is measured against.

2. **Contended mix** — >= 3 tenants share the front door. The bursty
   tenant's arrival weight is 10x each quiet tenant's (the same seeded
   tenant-tagged generator the twin uses, so bench and twin mixes can't
   drift), and it runs under a tight ``max_inflight`` quota. The bar:
   the bursty tenant sheds (``GW_TENANT_OVER_QUOTA``, with its own
   ``retry_after_s``) while every quiet tenant sheds NOTHING and its p99
   admission latency stays within 2x the solo baseline.

3. **Compile-ahead warm phase** — jobs expose the ``compile_ahead`` hook,
   so admission hands their executables to the background pool the moment
   a strategy is picked. The technique models first dispatch the way a
   real step function would: ``pool.acquire`` hit -> no compile wait;
   miss -> pay the inline compile. The bar: warm hit rate >= 80% and a
   mean first-dispatch compile wait of ~0.

Prints one JSON line (self-validated against
``bench_guard.TENANT_ROW_REQUIRED`` / ``validate_tenant_row``):

    {"metric": "tenant_fairshare", "n_tenants": 3, "burst_skew": 10.0,
     "shed": {"burst": ...}, "p99_ratio": ..., "warm_hit_rate": ...,
     "status": "ok", ...}

Run: ``python benchmarks/tenant_fairshare.py``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.service import (
    GatewayClient,
    GatewayError,
    GatewayServer,
    SaturnService,
)
from saturn_tpu.service.gateway import protocol
from saturn_tpu.tenancy import CompileAheadPool, TenantLedger, TenantQuota
from saturn_tpu.twin.arrivals import arrival_stream

SEED = 11
PER_BATCH_S = 0.003
BATCHES = 2               # tiny jobs: the front door, not the mesh, is measured
INTERVAL_S = 0.1

BURSTY = "burst"
QUIET = ["quiet-a", "quiet-b"]
BURST_SKEW = 10.0         # bursty arrival weight : each quiet tenant's
TENANT_MIX = {BURSTY: BURST_SKEW, **{t: 1.0 for t in QUIET}}
N_SOLO = 30               # solo-baseline submissions (one quiet tenant)
N_MIX = 240               # contended-phase arrivals across all tenants
BURST_WINDOW = 3          # bursty tenant's max_inflight quota
BURST_RETRY_S = 0.25      # its personal backoff hint on a shed

COMPILE_S = 0.05          # modeled XLA compile cost per job
N_WARM = 12               # compile-ahead phase jobs


class FakeDev:
    pass


class FairTech(BaseTechnique):
    """Pre-profiled executor: sleeps per batch; on a task's FIRST dispatch
    consults the compile-ahead pool (hit -> warm executable, no wait;
    miss -> pay the inline compile), recording the wait per task."""

    name = "bench-tenant"

    def __init__(self):
        self.pool = None
        self.first_waits = {}
        self._lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        with self._lock:
            first = task.name not in self.first_waits
            if first:
                self.first_waits[task.name] = 0.0
        if first and self.pool is not None:
            exe = self.pool.acquire(f"ca-{task.name}", timeout=0.5)
            if exe is None:
                # compile-ahead missed: the dispatch pays for XLA inline
                time.sleep(COMPILE_S)
                with self._lock:
                    self.first_waits[task.name] = COMPILE_S
        time.sleep(PER_BATCH_S * (override_batch_count or 1))

    def search(self, task, devices, tid):
        return {}, PER_BATCH_S


class FakeTask:
    """Duck-typed pre-profiled task (admission skips the trial sweep)."""

    def __init__(self, name, total_batches, tech):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = 1000
        self.hints = {}
        self.chip_range = None
        self.strategies = {
            g: Strategy(tech, g, {}, PER_BATCH_S * total_batches, PER_BATCH_S)
            for g in (4, 8)
        }
        self.selected_strategy = None

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length


class WarmTask(FakeTask):
    """FakeTask exposing the compile-ahead hook the service duck-types."""

    def compile_ahead(self, topology):
        def compile_exe(name=self.name):
            time.sleep(COMPILE_S)  # the background pool pays this, not dispatch
            return f"exe-{name}"

        return [(f"ca-{self.name}", compile_exe)]


def _provider(tech, warm=False):
    cls = WarmTask if warm else FakeTask

    def provide(payload):
        return cls(payload["task"], payload["remaining_batches"], tech)

    return provide


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def _run_phase(n_jobs, tenancy, tenant_mix, *, seed, prefix,
               gateway_window=64):
    """Drive ``n_jobs`` through a fresh service+gateway; per-tenant
    latencies and sheds. ``max_attempts=1`` on purpose: a shed is counted,
    not retried away — retry loops would hide the behavior under test."""
    tech = FairTech()
    svc = SaturnService(
        topology=SliceTopology([FakeDev() for _ in range(8)]),
        interval=INTERVAL_S, poll_s=0.02, task_provider=_provider(tech),
        health_guardian=False, tenancy=tenancy,
    ).start()
    gw = GatewayServer(svc, max_inflight=gateway_window,
                       max_inflight_per_session=gateway_window).start()
    latencies = {t: [] for t in tenant_mix}
    sheds = {t: 0 for t in tenant_mix}
    submitted = {t: 0 for t in tenant_mix}
    accepted = []
    try:
        with GatewayClient(*gw.address, session="bench-tenant", seed=seed,
                           timeout_s=30.0, max_attempts=1) as client:
            for arr in arrival_stream(n_jobs, base_rate_hz=40.0,
                                      burst_rate_hz=120.0, seed=seed,
                                      tenant_mix=tenant_mix):
                time.sleep(min(arr.gap_s, 0.02))
                tenant = arr.tenant
                submitted[tenant] += 1
                t0 = time.monotonic()
                try:
                    jid = client.submit(
                        name=f"{prefix}-{arr.index}", total_batches=BATCHES,
                        priority=arr.priority, spec={"sizes": [4, 8]},
                        tenant=tenant,
                    )
                except GatewayError as e:
                    # Retriable sheds surface as GW_UNAVAILABLE under
                    # max_attempts=1 (the client wraps the last refusal).
                    if e.code not in (protocol.GW_TENANT_OVER_QUOTA,
                                      protocol.GW_RETRY_AFTER,
                                      protocol.GW_UNAVAILABLE):
                        raise
                    sheds[tenant] += 1
                    continue
                latencies[tenant].append(time.monotonic() - t0)
                accepted.append(jid)
            for jid in accepted:
                out = client.wait(jid, timeout=300)
                if out["state"] != "DONE":
                    raise SystemExit(f"tenant bench job not DONE: {out}")
    finally:
        gw.shutdown(timeout=10, reason="bench-complete")
        svc.stop(timeout=60)
    for t in latencies:
        latencies[t].sort()
    return latencies, sheds, submitted


def run_warm_phase():
    """Compile-ahead: admitted jobs prewarm in the background pool; the
    technique's first dispatch acquires. Returns (hit_rate, mean_wait_s)."""
    tech = FairTech()
    pool = CompileAheadPool(workers=2)
    tech.pool = pool
    svc = SaturnService(
        topology=SliceTopology([FakeDev() for _ in range(8)]),
        interval=INTERVAL_S, poll_s=0.02,
        task_provider=_provider(tech, warm=True),
        health_guardian=False, compile_ahead=pool,
    ).start()
    gw = GatewayServer(svc, max_inflight=64).start()
    try:
        with GatewayClient(*gw.address, session="bench-warm", seed=SEED,
                           timeout_s=30.0) as client:
            jobs = []
            for i in range(N_WARM):
                jobs.append(client.submit(
                    name=f"warm-{i}", total_batches=BATCHES,
                    spec={"sizes": [4, 8]},
                ))
                # Arrivals pace in: admission prewarms each job ahead of
                # its first dispatch at the next interval boundary.
                time.sleep(INTERVAL_S / 2)
            for jid in jobs:
                out = client.wait(jid, timeout=300)
                if out["state"] != "DONE":
                    raise SystemExit(f"warm bench job not DONE: {out}")
        ledger = pool.ledger()
    finally:
        gw.shutdown(timeout=10, reason="bench-complete")
        svc.stop(timeout=60)
    waits = list(tech.first_waits.values())
    mean_wait = sum(waits) / len(waits) if waits else 0.0
    return ledger, mean_wait


def main() -> None:
    t_start = time.monotonic()

    # Phase 1: one quiet tenant alone — the latency baseline.
    solo_lat, _, _ = _run_phase(
        N_SOLO, TenantLedger(), {QUIET[0]: 1.0}, seed=SEED, prefix="solo")
    solo_p99 = _percentile(solo_lat[QUIET[0]], 0.99)

    # Phase 2: the contended mix. The bursty tenant runs under a tight
    # inflight quota with its own backoff hint; quiet tenants are unquota'd.
    ledger = TenantLedger()
    ledger.set_quota(BURSTY, TenantQuota(max_inflight=BURST_WINDOW,
                                         retry_after_s=BURST_RETRY_S))
    mix_lat, sheds, submitted = _run_phase(
        N_MIX, ledger, TENANT_MIX, seed=SEED, prefix="mix")
    quiet_all = sorted(x for t in QUIET for x in mix_lat[t])
    quiet_p99 = _percentile(quiet_all, 0.99)
    ratio = quiet_p99 / solo_p99 if solo_p99 > 0 else 0.0

    # Phase 3: compile-ahead warm hit rate + first-dispatch wait.
    ca_ledger, mean_wait = run_warm_phase()

    row = {
        "metric": "tenant_fairshare",
        "n_tenants": len(TENANT_MIX),
        "n_jobs": N_MIX,
        "burst_skew": BURST_SKEW,
        "bursty_tenant": BURSTY,
        "submitted": dict(sorted(submitted.items())),
        "admitted": {t: len(mix_lat[t]) for t in sorted(mix_lat)},
        "shed": dict(sorted(sheds.items())),
        "solo_p99_s": round(solo_p99, 6),
        "quiet_p99_s": round(quiet_p99, 6),
        "p99_ratio": round(ratio, 4),
        "warm_hit_rate": ca_ledger["hit_rate"],
        "first_dispatch_wait_s": round(mean_wait, 6),
        "compile_ahead": {k: ca_ledger[k] for k in
                          ("requested", "ready", "ahead_hits",
                           "ahead_misses", "errors")},
        "wall_s": round(time.monotonic() - t_start, 3),
        "seed": SEED,
        "status": "ok",
    }
    import bench_guard
    problems = bench_guard.validate_tenant_row(row)
    if problems:
        raise SystemExit(f"tenant row failed self-validation: {problems}")
    print(json.dumps(row))


if __name__ == "__main__":
    main()
