"""GPipe vs 1F1B steady-state step time at the same stage cuts (round 20).

The schedule swap's whole pitch: same spans, same microbatches, bit-identical
summed gradients (pinned in ``tests/test_pipeline.py``) — but the backward
launches ``2(S-1)`` ticks behind its forward instead of after the full
forward flush, so the warmup-cooldown bubble shrinks from ``(S-1)/(M+S-1)``
to ``(S-1)/(M+2(S-1))`` and the activation stash from ``M`` microbatches to
``min(M, 2S-1)``. This bench times both schedules through the same executor
at ``M = S`` (the acceptance point: the smallest microbatch count where
GPipe's AD program still runs) and emits one self-validated row.

Run: ``python benchmarks/pipeline_schedule.py [--preset test-tiny] [--json]``
"""

from __future__ import annotations

import argparse
import json
import os


def main():
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="test-tiny")
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--microbatches", type=int, default=0,
                    help="0 = stages (the M = S acceptance point)")
    ap.add_argument("--cpu", action="store_true", help="force the CPU backend")
    ap.add_argument("--json", action="store_true",
                    help="print the row as one JSON line")
    args = ap.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss
    from saturn_tpu.ops.pipeline import schedule_bubble_fraction
    from saturn_tpu.parallel.pp import Pipeline
    from saturn_tpu.utils.timing import time_train_step

    devices = jax.devices()
    n = 1 << (len(devices).bit_length() - 1)
    devices = devices[:n]
    s = min(args.stages, n, args.layers)
    while n % s != 0:
        s -= 1
    m = args.microbatches or s
    print(f"backend={devices[0].platform} devices={n} preset={args.preset} "
          f"seq={args.seq} batch={args.batch} stages={s} microbatches={m}")

    task = Task(
        get_model=lambda **kw: build_gpt2(
            args.preset, seq_len=args.seq, n_layers=args.layers, **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=args.seq, batch_size=args.batch,
            vocab_size=256 if args.preset == "test-tiny" else 50304,
            n_tokens=args.seq * args.batch * 4,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=4),
        save_dir="/tmp/pp_schedule_bench_ckpts",
    )

    pp = Pipeline()
    times = {}
    for schedule in ("gpipe", "1f1b"):
        cfg = {"stages": s, "microbatches": m, "schedule": schedule,
               "remat": False}
        bundle = pp.build(task, devices, cfg)
        state = bundle.init()
        batch = jax.device_put(
            task.get_dataset().batch(0), bundle.batch_sharding)
        dt = time_train_step(bundle.compiled, state, batch,
                             n_timed=5, n_warmup=2)
        times[schedule] = dt
        tput = args.batch * args.seq / dt
        print(f"{schedule:6s} {dt*1e3:9.1f} ms/step  {tput:10.0f} tok/s  "
              f"bubble={schedule_bubble_fraction(schedule, s, m):.3f}")

    row = {
        "metric": "pipeline_schedule",
        "stages": s,
        "microbatches": m,
        "devices": n,
        "gpipe_ms": round(times["gpipe"] * 1e3, 3),
        "f1b_ms": round(times["1f1b"] * 1e3, 3),
        "speedup_1f1b_vs_gpipe": round(times["gpipe"] / times["1f1b"], 4),
        "bubble_gpipe": schedule_bubble_fraction("gpipe", s, m),
        "bubble_1f1b": schedule_bubble_fraction("1f1b", s, m),
        "status": "ok",
    }

    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_guard_pp", os.path.join(os.path.dirname(__file__),
                                       "bench_guard.py"))
    guard = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(guard)
    problems = guard.validate_pipeline_row(row)
    if problems:
        row["status"] = "invalid"
        for p in problems:
            print(f"ROW INVALID: {p}")
    if args.json:
        print(json.dumps(row, sort_keys=True))
    else:
        print(row)
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
