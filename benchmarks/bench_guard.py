"""Bench regression guard: fail if headline throughput drops >10%.

Compares a fresh ``bench.py`` run against the most recent recorded
``BENCH_r*.json`` in the repo root (the driver's per-round bench archive).
The comparison is shape-aware: a degraded (b2x256 CPU) record only gates
degraded runs on the same platform — a TPU number must never gate a CPU
fallback or vice versa (the per-shape baseline-key rule from round 4).

Prints ONE JSON line and exits non-zero on regression:

    {"metric": "bench_guard", "status": "ok"|"regression"|"skipped",
     "value": <new tokens/s>, "reference": <recorded tokens/s>, ...}

Run: ``python benchmarks/bench_guard.py`` (CI) — threshold overridable via
``SATURN_BENCH_GUARD_PCT`` (default 10).
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def latest_record():
    """(round, parsed-result) of the newest BENCH_r*.json with a parsed
    value, or None when no usable record exists (fresh clone)."""
    best = None
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed")
        if not isinstance(parsed, dict):
            continue
        value = parsed.get("value")
        if not isinstance(value, (int, float)) or value <= 0:
            continue
        n = int(m.group(1))
        if best is None or n > best[0]:
            best = (n, parsed)
    return best


def run_bench() -> dict:
    """Run bench.py in a subprocess and parse its single JSON stdout line."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=1200,
    )
    for line in reversed(r.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(
        f"bench.py produced no JSON line (rc={r.returncode}): "
        f"{(r.stderr or r.stdout).strip().splitlines()[-1:]}"
    )


def shape_key(parsed: dict) -> tuple:
    """What must match for two bench numbers to be comparable."""
    return (
        parsed.get("workload"),    # e.g. benchmarks/coschedule.py tags its
        parsed.get("platform"),    # row "coschedule_pair"; bench.py rows
        parsed.get("batch_size"),  # carry no tag — the two never gate each
        parsed.get("seq_len"),     # other. batch_size: degraded runs only.
    )


def main() -> int:
    ref = latest_record()
    threshold = float(os.environ.get("SATURN_BENCH_GUARD_PCT", "10")) / 100.0
    if ref is None:
        print(json.dumps({
            "metric": "bench_guard", "status": "skipped",
            "reason": "no BENCH_r*.json with a parsed value",
        }))
        return 0
    n, parsed_ref = ref
    new = run_bench()
    out = {
        "metric": "bench_guard",
        "value": new.get("value"),
        "reference": parsed_ref["value"],
        "reference_round": n,
        "threshold_pct": threshold * 100.0,
    }
    if shape_key(new) != shape_key(parsed_ref):
        # e.g. the reference is a degraded CPU record but this host has a
        # live TPU — different workload shapes, no comparison to make.
        out["status"] = "skipped"
        out["reason"] = (
            f"shape mismatch: ran {shape_key(new)} vs "
            f"recorded {shape_key(parsed_ref)}"
        )
        print(json.dumps(out))
        return 0
    floor = parsed_ref["value"] * (1.0 - threshold)
    if new.get("value", 0.0) < floor:
        out["status"] = "regression"
        out["floor"] = round(floor, 1)
        print(json.dumps(out))
        return 1
    out["status"] = "ok"
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
