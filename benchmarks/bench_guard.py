"""Bench regression guard: fail if headline throughput drops >10%.

Compares a fresh ``bench.py`` run against the most recent recorded
``BENCH_r*.json`` in the repo root (the driver's per-round bench archive).
The comparison is shape-aware: a degraded (b2x256 CPU) record only gates
degraded runs on the same platform — a TPU number must never gate a CPU
fallback or vice versa (the per-shape baseline-key rule from round 4).

Prints ONE JSON line and exits non-zero on regression:

    {"metric": "bench_guard", "status": "ok"|"regression"|"skipped",
     "value": <new tokens/s>, "reference": <recorded tokens/s>, ...}

Run: ``python benchmarks/bench_guard.py`` (CI) — threshold overridable via
``SATURN_BENCH_GUARD_PCT`` (default 10).
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def latest_record():
    """(round, parsed-result) of the newest BENCH_r*.json with a parsed
    value, or None when no usable record exists (fresh clone)."""
    best = None
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed")
        if not isinstance(parsed, dict):
            continue
        value = parsed.get("value")
        if not isinstance(value, (int, float)) or value <= 0:
            continue
        if parsed.get("tsan"):
            continue  # instrumented rows never serve as baselines
        n = int(m.group(1))
        if best is None or n > best[0]:
            best = (n, parsed)
    return best


def run_bench() -> dict:
    """Run bench.py in a subprocess and parse its single JSON stdout line."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=1200,
    )
    for line in reversed(r.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(
        f"bench.py produced no JSON line (rc={r.returncode}): "
        f"{(r.stderr or r.stdout).strip().splitlines()[-1:]}"
    )


def bench_plan_errors(new: dict) -> list:
    """Static plan verification for the benchmark's workload (saturn-lint).

    The headline bench is a single job on the measuring host's slice; its
    plan form is one full-capacity assignment. Running it through the real
    verifier end-to-end (Block/SliceTopology arithmetic, launch + capacity
    + timeline checks) means an analyzer or topology regression refuses the
    row loudly instead of silently blessing numbers from a state the
    orchestrator would reject.  Returns error diagnostics (JSON form).
    """
    sys.path.insert(0, REPO)
    from saturn_tpu.analysis import verify_plan
    from saturn_tpu.core.mesh import Block, SliceTopology
    from saturn_tpu.solver import milp

    topo = SliceTopology(devices=[object()])
    plan = milp.Plan(
        assignments={
            "bench_gpt2": milp.Assignment(
                apportionment=topo.capacity,
                block=Block(0, topo.capacity),
                start=0.0,
                runtime=1.0,
            )
        },
        makespan=1.0,
    )
    plan.compute_dependencies()
    report = verify_plan(plan, topology=topo, subject="bench_guard")
    return [d.to_json() for d in report.errors]


def bench_shardflow_errors() -> list:
    """Unsanctioned SAT-X findings over the technique + kernel sources
    (saturn-shardflow).

    The headline number is produced by a technique's step function; a row
    measured while that code carries an unsanctioned sharding funnel
    (SAT-X002 gather-to-replicated and friends) bakes the defect into the
    baseline every later round is compared against. AST-only — same
    any-environment rule as the ``tools/lint.py`` gate.  Returns error
    diagnostics (JSON form); sanctioned findings are info and pass.
    """
    sys.path.insert(0, REPO)
    from saturn_tpu.analysis.diagnostics import AnalysisReport
    from saturn_tpu.analysis.shardflow import passes as sf_passes

    report = AnalysisReport(subject="bench_guard-shardflow")
    sf_passes.scan_sources(sf_passes.default_source_paths(REPO), report)
    return [d.to_json() for d in report.errors]


def bench_memlens_errors() -> list:
    """Unsanctioned SAT-M findings over the in-tree techniques
    (saturn-memlens).

    The headline number is produced by a technique's step function; a row
    measured while that step carries an unsanctioned memory defect
    (SAT-M003 missed donation, or SAT-M001 predicted OOM under a declared
    capacity) bakes the defect into the baseline. The audit traces on
    virtual CPU devices, and the device-count flag must land before jax
    initializes — so it runs as the CLI subprocess, not in-process.
    Returns error diagnostics (JSON form); sanctioned findings are info
    and pass.
    """
    # The source tree is where THIS file lives, not REPO: REPO is the
    # record-lookup root and tests point it at a tmp dir, which must not
    # break the subprocess's ability to import saturn_tpu.
    src = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = src + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "saturn_tpu.analysis", "--json", "memlens"],
        capture_output=True, text=True, timeout=900, cwd=REPO, env=env,
    )
    if r.returncode == 2:
        raise RuntimeError(
            f"memlens audit unavailable: {(r.stderr or '').strip()[-200:]}"
        )
    for line in reversed(r.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            payload = json.loads(line)
            return [d for d in payload.get("diagnostics", [])
                    if d.get("severity") == "error"]
    raise RuntimeError(
        f"memlens audit produced no JSON line (rc={r.returncode})"
    )


#: Required key -> type for the ``benchmarks/sweep_cache.py`` static-prune
#: row. Same contract as the other ROW_REQUIRED tables: the bench
#: self-validates before printing, and recorded rows can be re-checked
#: without re-running it.
SWEEP_PRUNE_ROW_REQUIRED = {
    "metric": str,
    "grid_points": int,
    "pruned_before_lowering": int,     # acceptance bar: >= 1
    "rejected_after_lowering": int,    # the "before" sweep's compile waste
    "contradictions": int,             # _fits_memory vs memlens-feasible: 0
    "before_s": float,
    "after_s": float,
    "saved_s": float,
    "capacity_bytes": int,
    "status": str,
}


def validate_sweep_prune_row(row) -> list:
    """Schema-check one static-prune sweep row; returns human-readable
    problems (empty list = valid)."""
    if not isinstance(row, dict):
        return [f"row is not a dict ({type(row).__name__})"]
    problems = []
    for key, typ in SWEEP_PRUNE_ROW_REQUIRED.items():
        if key not in row:
            problems.append(f"missing key {key!r}")
            continue
        val = row[key]
        if typ in (int, float) and isinstance(val, bool):
            problems.append(f"{key!r} is bool, expected {typ.__name__}")
        elif typ is float and isinstance(val, int):
            pass  # whole-number float serialized as int is fine
        elif not isinstance(val, typ):
            problems.append(
                f"{key!r} is {type(val).__name__}, expected {typ.__name__}"
            )
    if row.get("metric") != "sweep_static_prune":
        problems.append(
            f"metric is {row.get('metric')!r}, expected 'sweep_static_prune'"
        )
    pruned = row.get("pruned_before_lowering")
    if isinstance(pruned, int) and not isinstance(pruned, bool) and pruned < 1:
        problems.append(
            "pruned_before_lowering < 1 (the static pass pruned nothing)"
        )
    c = row.get("contradictions")
    if isinstance(c, int) and not isinstance(c, bool) and c != 0:
        problems.append(
            f"contradictions {c} != 0 (_fits_memory rejected a point "
            "memlens called feasible)"
        )
    return problems


#: Required key -> type for the ``benchmarks/pipeline_schedule.py`` row.
#: Same contract as the other ROW_REQUIRED tables: the bench self-validates
#: before printing, and recorded rows can be re-checked without re-running.
PIPELINE_ROW_REQUIRED = {
    "metric": str,
    "stages": int,
    "microbatches": int,
    "devices": int,
    "gpipe_ms": float,                 # AD-GPipe steady-state step time
    "f1b_ms": float,                   # staged 1F1B steady-state step time
    "speedup_1f1b_vs_gpipe": float,    # acceptance bar: >= 1.0 at M = S
    "bubble_gpipe": float,             # analytic (S-1)/(M+S-1)
    "bubble_1f1b": float,              # analytic (S-1)/(M+2(S-1))
    "status": str,
}


def validate_pipeline_row(row) -> list:
    """Schema-check one pipeline-schedule row; returns human-readable
    problems (empty list = valid)."""
    if not isinstance(row, dict):
        return [f"row is not a dict ({type(row).__name__})"]
    problems = []
    for key, typ in PIPELINE_ROW_REQUIRED.items():
        if key not in row:
            problems.append(f"missing key {key!r}")
            continue
        val = row[key]
        if typ in (int, float) and isinstance(val, bool):
            problems.append(f"{key!r} is bool, expected {typ.__name__}")
        elif typ is float and isinstance(val, int):
            pass  # whole-number float serialized as int is fine
        elif not isinstance(val, typ):
            problems.append(
                f"{key!r} is {type(val).__name__}, expected {typ.__name__}"
            )
    if row.get("metric") != "pipeline_schedule":
        problems.append(
            f"metric is {row.get('metric')!r}, expected 'pipeline_schedule'"
        )
    s = row.get("stages")
    if isinstance(s, int) and not isinstance(s, bool) and s < 2:
        problems.append("stages < 2 (no pipeline to schedule)")
    sp = row.get("speedup_1f1b_vs_gpipe")
    if isinstance(sp, (int, float)) and not isinstance(sp, bool) and sp < 1.0:
        problems.append(
            f"speedup_1f1b_vs_gpipe {sp} < 1.0 (1F1B must beat GPipe "
            "steady-state at M = S)"
        )
    bg, bf = row.get("bubble_gpipe"), row.get("bubble_1f1b")
    for key, b in (("bubble_gpipe", bg), ("bubble_1f1b", bf)):
        if (isinstance(b, (int, float)) and not isinstance(b, bool)
                and not 0.0 <= b < 1.0):
            problems.append(f"{key} {b} outside [0, 1)")
    if (isinstance(bg, (int, float)) and isinstance(bf, (int, float))
            and not isinstance(bg, bool) and not isinstance(bf, bool)
            and bf >= bg):
        problems.append(
            f"bubble_1f1b {bf} >= bubble_gpipe {bg} (1F1B's warmup-"
            "cooldown bubble must be the smaller one)"
        )
    return problems


#: Required key -> type for one ``benchmarks/chaos_campaign.py`` output row.
#: The campaign bench self-validates against this before printing, and CI
#: can re-check recorded rows — a schema drift (renamed key, stringified
#: count) breaks the comparison silently otherwise.
CHAOS_ROW_REQUIRED = {
    "metric": str,
    "seeds": list,
    "fault_classes": list,
    "jobs": int,
    "jobs_lost": int,
    "restarts": int,
    "quarantined_batches": int,
    "makespan_inflation": float,
    "trajectory_bit_identical": bool,
    "sentinel_overhead_pct": float,
    "platform": str,
    "status": str,
}


def validate_chaos_row(row) -> list:
    """Schema-check one chaos-campaign row; returns human-readable problems
    (empty list = valid)."""
    if not isinstance(row, dict):
        return [f"row is not a dict ({type(row).__name__})"]
    problems = []
    for key, typ in CHAOS_ROW_REQUIRED.items():
        if key not in row:
            problems.append(f"missing key {key!r}")
            continue
        val = row[key]
        if typ in (int, float) and isinstance(val, bool):
            # bool is an int subclass; a True in a count field is a bug
            problems.append(f"{key!r} is bool, expected {typ.__name__}")
        elif typ is float and isinstance(val, int):
            pass  # a whole-number float serialized as int is fine
        elif not isinstance(val, typ):
            problems.append(
                f"{key!r} is {type(val).__name__}, expected {typ.__name__}"
            )
    if row.get("metric") != "chaos_campaign":
        problems.append(
            f"metric is {row.get('metric')!r}, expected 'chaos_campaign'"
        )
    if isinstance(row.get("seeds"), list) and len(row["seeds"]) < 3:
        problems.append("fewer than 3 seeds")
    if (isinstance(row.get("fault_classes"), list)
            and len(row["fault_classes"]) < 4):
        problems.append("fewer than 4 fault classes")
    return problems


#: Required key -> type for the ``benchmarks/online_arrivals.py`` gateway
#: row. Same contract as CHAOS_ROW_REQUIRED: the bench self-validates before
#: printing, and recorded rows can be re-checked without re-running it.
ONLINE_ROW_REQUIRED = {
    "metric": str,
    "n_jobs": int,
    "accepted": int,
    "shed": int,
    "shed_rate": float,
    "admission_p50_s": float,
    "admission_p99_s": float,
    "makespan_s": float,
    "base_rate_hz": float,
    "burst_rate_hz": float,
    "gateway_window": int,
    "seed": int,
    "status": str,
}


def validate_online_row(row) -> list:
    """Schema-check one online-arrivals gateway row; returns human-readable
    problems (empty list = valid)."""
    if not isinstance(row, dict):
        return [f"row is not a dict ({type(row).__name__})"]
    problems = []
    for key, typ in ONLINE_ROW_REQUIRED.items():
        if key not in row:
            problems.append(f"missing key {key!r}")
            continue
        val = row[key]
        if typ in (int, float) and isinstance(val, bool):
            problems.append(f"{key!r} is bool, expected {typ.__name__}")
        elif typ is float and isinstance(val, int):
            pass  # whole-number float serialized as int is fine
        elif not isinstance(val, typ):
            problems.append(
                f"{key!r} is {type(val).__name__}, expected {typ.__name__}"
            )
    if row.get("metric") != "online_arrivals":
        problems.append(
            f"metric is {row.get('metric')!r}, expected 'online_arrivals'"
        )
    if (isinstance(row.get("accepted"), int)
            and isinstance(row.get("shed"), int)
            and isinstance(row.get("n_jobs"), int)
            and row["accepted"] + row["shed"] != row["n_jobs"]):
        problems.append("accepted + shed != n_jobs (lost arrivals)")
    sr = row.get("shed_rate")
    if isinstance(sr, (int, float)) and not isinstance(sr, bool):
        if not 0.0 <= sr <= 1.0:
            problems.append(f"shed_rate {sr} outside [0, 1]")
    p50, p99 = row.get("admission_p50_s"), row.get("admission_p99_s")
    if (isinstance(p50, (int, float)) and isinstance(p99, (int, float))
            and not isinstance(p50, bool) and not isinstance(p99, bool)
            and p99 < p50):
        problems.append("admission_p99_s < admission_p50_s")
    return problems


#: Required key -> type for the ``benchmarks/solver_scaling.py`` row. Same
#: contract as the other ROW_REQUIRED tables: the bench self-validates before
#: printing, and recorded rows can be re-checked without re-running it.
SOLVER_ROW_REQUIRED = {
    "metric": str,
    "mode": str,                 # "quick" or "full"
    "n_jobs": int,
    "deadline_s": float,
    "resolves": int,
    "deadline_misses": int,      # hard acceptance bar: must be 0
    "tier_counts": dict,         # tier name -> adoption count
    "solve_p50_s": float,
    "solve_p99_s": float,
    "admission_p50_s": float,
    "admission_p99_s": float,
    "quality_delta_pct": float,  # anytime vs exact MILP on subsampled instances
    "quality_samples": int,
    "seed": int,
    "status": str,
}


def validate_solver_row(row) -> list:
    """Schema-check one solver-scaling row; returns human-readable problems
    (empty list = valid)."""
    if not isinstance(row, dict):
        return [f"row is not a dict ({type(row).__name__})"]
    problems = []
    for key, typ in SOLVER_ROW_REQUIRED.items():
        if key not in row:
            problems.append(f"missing key {key!r}")
            continue
        val = row[key]
        if typ in (int, float) and isinstance(val, bool):
            problems.append(f"{key!r} is bool, expected {typ.__name__}")
        elif typ is float and isinstance(val, int):
            pass  # whole-number float serialized as int is fine
        elif not isinstance(val, typ):
            problems.append(
                f"{key!r} is {type(val).__name__}, expected {typ.__name__}"
            )
    if row.get("metric") != "solver_scaling":
        problems.append(
            f"metric is {row.get('metric')!r}, expected 'solver_scaling'"
        )
    if isinstance(row.get("n_jobs"), int) and row["n_jobs"] < 1:
        problems.append(f"n_jobs {row['n_jobs']} < 1")
    dm = row.get("deadline_misses")
    if isinstance(dm, int) and not isinstance(dm, bool) and dm != 0:
        problems.append(
            f"deadline_misses {dm} != 0 (a re-solve blew its budget)"
        )
    for lo, hi in (("solve_p50_s", "solve_p99_s"),
                   ("admission_p50_s", "admission_p99_s")):
        a, b = row.get(lo), row.get(hi)
        if (isinstance(a, (int, float)) and isinstance(b, (int, float))
                and not isinstance(a, bool) and not isinstance(b, bool)
                and b < a):
            problems.append(f"{hi} < {lo}")
    qd = row.get("quality_delta_pct")
    if isinstance(qd, (int, float)) and not isinstance(qd, bool):
        if qd > 10.0:
            problems.append(
                f"quality_delta_pct {qd} > 10 (anytime plan quality drifted "
                "too far from the exact MILP)"
            )
    tc = row.get("tier_counts")
    if isinstance(tc, dict):
        bad = [k for k, v in tc.items()
               if not isinstance(k, str)
               or isinstance(v, bool) or not isinstance(v, int)]
        if bad:
            problems.append(f"tier_counts has non-(str -> int) entries: {bad}")
    return problems


#: Required key -> type for the ``benchmarks/billion_scale.py`` checkpoint
#: I/O row (allgather-writer vs sharded-manifest save/restore timings). Same
#: contract as the other ROW_REQUIRED tables: the bench self-validates
#: before printing, and recorded rows can be re-checked without re-running.
CKPT_ROW_REQUIRED = {
    "metric": str,                  # "ckpt_io"
    "preset": str,
    "platform": str,
    "n_devices": int,
    "state_bytes": int,             # full train-state bytes on host
    "allgather_save_s": float,      # emulated legacy single-writer save
    "sharded_save_s": float,        # manifest + per-rank shard files, cold
    "sharded_async_block_s": float,  # caller-visible save_async latency
    "sharded_restore_s": float,     # restore_sharded onto a resized mesh
    "restore_bit_identical": bool,  # hard acceptance bar: must be True
    "shard_files": int,
    "speedup_vs_allgather": float,  # allgather_save_s / sharded_save_s
    "status": str,
}


def latest_ckpt_record():
    """(round, ckpt-row) of the newest ``BENCH_r*.json`` carrying a valid
    ``ckpt`` row, or None. Lives under the record's ``"ckpt"`` key — never
    under ``"parsed"`` — so checkpoint rows and headline-throughput rows
    can't gate each other."""
    best = None
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        row = rec.get("ckpt")
        if not isinstance(row, dict) or validate_ckpt_row(row):
            continue
        n = int(m.group(1))
        if best is None or n > best[0]:
            best = (n, row)
    return best


def validate_ckpt_row(row, reference=None, pct=10.0) -> list:
    """Schema-check one checkpoint-I/O row; returns human-readable problems
    (empty list = valid). With ``reference`` (a previously recorded row of
    the same shape) also enforces the regression bar: the sharded save must
    not be more than ``pct`` percent slower than the recorded one."""
    if not isinstance(row, dict):
        return [f"row is not a dict ({type(row).__name__})"]
    problems = []
    for key, typ in CKPT_ROW_REQUIRED.items():
        if key not in row:
            problems.append(f"missing key {key!r}")
            continue
        val = row[key]
        if typ in (int, float) and isinstance(val, bool):
            problems.append(f"{key!r} is bool, expected {typ.__name__}")
        elif typ is float and isinstance(val, int):
            pass  # whole-number float serialized as int is fine
        elif not isinstance(val, typ):
            problems.append(
                f"{key!r} is {type(val).__name__}, expected {typ.__name__}"
            )
    if row.get("metric") != "ckpt_io":
        problems.append(f"metric is {row.get('metric')!r}, expected 'ckpt_io'")
    if row.get("restore_bit_identical") is not True:
        problems.append(
            "restore_bit_identical is not True — the sharded round trip "
            "corrupted at least one leaf"
        )
    for key in ("sharded_save_s", "allgather_save_s", "sharded_restore_s"):
        v = row.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v <= 0:
            problems.append(f"{key} {v} <= 0")
    blk = row.get("sharded_async_block_s")
    cold = row.get("sharded_save_s")
    if (isinstance(blk, (int, float)) and isinstance(cold, (int, float))
            and not isinstance(blk, bool) and not isinstance(cold, bool)
            and cold > 0 and blk > cold * 1.5):
        problems.append(
            f"sharded_async_block_s {blk} > 1.5x cold save {cold} — the "
            "async path is not overlapping the disk write"
        )
    if isinstance(reference, dict):
        same_shape = all(
            row.get(k) == reference.get(k)
            for k in ("preset", "platform", "n_devices")
        )
        ref_s = reference.get("sharded_save_s")
        new_s = row.get("sharded_save_s")
        if (same_shape
                and isinstance(ref_s, (int, float))
                and isinstance(new_s, (int, float))
                and not isinstance(ref_s, bool)
                and not isinstance(new_s, bool)
                and ref_s > 0
                and new_s > ref_s * (1.0 + pct / 100.0)):
            problems.append(
                f"sharded_save_s {new_s} regressed >{pct}% vs recorded "
                f"{ref_s}"
            )
    return problems


#: Required key -> type for the ``benchmarks/fused_sweep.py`` row. Same
#: contract as the other ROW_REQUIRED tables: the bench self-validates
#: before printing, and recorded rows can be re-checked without re-running.
FUSED_ROW_REQUIRED = {
    "metric": str,                     # "fused_sweep_tokens_per_sec"
    "workload": str,                   # "fused_sweep"
    "platform": str,
    "n_members": int,                  # >= 2 or there is no stack
    "batches_per_member": int,
    "batch_size": int,
    "seq_len": int,
    "window": int,
    "value": float,                    # fused aggregate tokens/sec
    "coscheduled_tokens_per_sec": float,
    "fused_s": float,
    "coscheduled_s": float,
    "speedup_vs_coschedule": float,    # acceptance bar: >= 1.0
    "loss_divergence": float,          # max |fused - solo ref|: ~0 required
    "status": str,
}

#: The fused row's per-member losses are compared after the event stream's
#: 6-decimal rounding, so bit-identical trajectories read back as <= 1e-6
#: apart; anything past this tolerance means the stacked program changed
#: the math, and the row is a lie about "the same training, faster".
FUSED_LOSS_TOL = 1e-5


def validate_fused_row(row) -> list:
    """Schema-check one fused-sweep row; returns human-readable problems
    (empty list = valid). Refuses rows whose speedup claim is measured
    against diverged members: ``loss_divergence`` past FUSED_LOSS_TOL means
    the fused trajectories are not the solo trajectories."""
    if not isinstance(row, dict):
        return [f"row is not a dict ({type(row).__name__})"]
    problems = []
    for key, typ in FUSED_ROW_REQUIRED.items():
        if key not in row:
            problems.append(f"missing key {key!r}")
            continue
        val = row[key]
        if typ in (int, float) and isinstance(val, bool):
            problems.append(f"{key!r} is bool, expected {typ.__name__}")
        elif typ is float and isinstance(val, int):
            pass  # whole-number float serialized as int is fine
        elif not isinstance(val, typ):
            problems.append(
                f"{key!r} is {type(val).__name__}, expected {typ.__name__}"
            )
    if row.get("metric") != "fused_sweep_tokens_per_sec":
        problems.append(
            f"metric is {row.get('metric')!r}, expected "
            "'fused_sweep_tokens_per_sec'"
        )
    n = row.get("n_members")
    if isinstance(n, int) and not isinstance(n, bool) and n < 2:
        problems.append(f"n_members {n} < 2 (no stack to fuse)")
    sp = row.get("speedup_vs_coschedule")
    if isinstance(sp, (int, float)) and not isinstance(sp, bool) and sp < 1.0:
        problems.append(
            f"speedup_vs_coschedule {sp} < 1.0 (the stack must beat the "
            "co-scheduled pairs it replaces)"
        )
    div = row.get("loss_divergence")
    if isinstance(div, (int, float)) and not isinstance(div, bool):
        if not div <= FUSED_LOSS_TOL:
            problems.append(
                f"loss_divergence {div} > {FUSED_LOSS_TOL} (a fused member's "
                "final loss diverged from its solo reference — refusing to "
                "record a speedup over different training)"
            )
    return problems


#: Required key -> type for the ``benchmarks/twin_scale.py`` row. Same
#: contract as the other ROW_REQUIRED tables: the bench self-validates
#: before printing, and recorded rows can be re-checked without re-running.
TWIN_ROW_REQUIRED = {
    "metric": str,               # "twin_scale"
    "mode": str,                 # "quick" or "full"
    "n_jobs": int,               # full mode: >= 100_000 synthesized jobs
    "n_slices": int,             # full mode: >= 32 virtual slices
    "chips": int,
    "submitted": int,            # accepted by the real gateway
    "scheduled": int,            # ADMITted by the real admission controller
    "completed": int,
    "failed": int,
    "evicted": int,
    "shed": int,                 # gateway sheds (window/deadline/draining)
    "solves": int,               # real anytime_resolve calls
    "deadline_misses": int,      # hard acceptance bar: must be 0
    "tier_counts": dict,         # solver tier -> adoption count
    "makespan_sim_s": float,     # simulated campaign makespan
    "wall_s": float,             # real seconds the campaign took
    "seed": int,
    "fidelity": dict,            # twin-vs-real band check (may be empty
    #                              when the fidelity phase was skipped)
    "status": str,
}


def validate_twin_row(row) -> list:
    """Schema-check one twin-scale row; returns human-readable problems
    (empty list = valid).

    Enforces the twin's acceptance bars: zero solver deadline misses, the
    full-mode scale floor (>= 100k jobs over >= 32 virtual slices), a
    conservation check (every scheduled job reaches exactly one terminal
    verdict), and — when a fidelity phase ran — ``within_band``."""
    if not isinstance(row, dict):
        return [f"row is not a dict ({type(row).__name__})"]
    problems = []
    for key, typ in TWIN_ROW_REQUIRED.items():
        if key not in row:
            problems.append(f"missing key {key!r}")
            continue
        val = row[key]
        if typ in (int, float) and isinstance(val, bool):
            problems.append(f"{key!r} is bool, expected {typ.__name__}")
        elif typ is float and isinstance(val, int):
            pass  # whole-number float serialized as int is fine
        elif not isinstance(val, typ):
            problems.append(
                f"{key!r} is {type(val).__name__}, expected {typ.__name__}"
            )
    if row.get("metric") != "twin_scale":
        problems.append(
            f"metric is {row.get('metric')!r}, expected 'twin_scale'"
        )
    dm = row.get("deadline_misses")
    if isinstance(dm, int) and not isinstance(dm, bool) and dm != 0:
        problems.append(
            f"deadline_misses {dm} != 0 (a twin re-solve blew its real-"
            "clock budget)"
        )
    if row.get("mode") == "full":
        nj, ns = row.get("n_jobs"), row.get("n_slices")
        if isinstance(nj, int) and not isinstance(nj, bool) and nj < 100_000:
            problems.append(f"full-mode n_jobs {nj} < 100000")
        if isinstance(ns, int) and not isinstance(ns, bool) and ns < 32:
            problems.append(f"full-mode n_slices {ns} < 32")
    ints = {k: row.get(k)
            for k in ("scheduled", "completed", "failed", "evicted")}
    if all(isinstance(v, int) and not isinstance(v, bool)
           for v in ints.values()):
        done = ints["completed"] + ints["failed"] + ints["evicted"]
        if done < ints["scheduled"]:
            problems.append(
                f"completed+failed+evicted {done} < scheduled "
                f"{ints['scheduled']} (jobs left in limbo)"
            )
    tc = row.get("tier_counts")
    if isinstance(tc, dict):
        bad = [k for k, v in tc.items()
               if not isinstance(k, str)
               or isinstance(v, bool) or not isinstance(v, int)]
        if bad:
            problems.append(f"tier_counts has non-(str -> int) entries: {bad}")
        if not tc and row.get("solves", 0):
            problems.append("solves > 0 but tier_counts is empty")
    fid = row.get("fidelity")
    if isinstance(fid, dict) and fid and fid.get("within_band") is not True:
        problems.append(
            "fidelity.within_band is not True (the twin's tier/verdict/"
            "makespan distributions drifted outside the documented band)"
        )
    return problems


#: Pinned-seed twin regression campaign: the standing scheduling-policy
#: guard (ROADMAP item 3 headroom). A small tenant-tagged mix runs the real
#: control plane (gateway window, admission controller, anytime solver tier
#: ladder) on virtual slices in under a second; its tier shares, verdict
#: shares and simulated makespan are pinned here with bands. A scheduling-
#: policy change that shifts which solver tier wins, flips admission
#: verdicts, or moves the campaign makespan outside the band fails the
#: guard — BEFORE it gets to record a new headline baseline. Values pinned
#: from the seeded run (deterministic: simulated clock, seeded arrivals).
TWIN_REGRESSION = {
    "seed": 23,
    "n_jobs": 600,
    "n_slices": 4,
    "tenant_mix": {"burst": 10.0, "quiet-a": 1.0, "quiet-b": 1.0},
    "tier_shares": {"1": 0.5, "2": 0.5},
    "tier_band": 0.15,           # absolute share drift allowed per tier
    "verdict_shares": {"admit": 1.0},
    "verdict_band": 0.10,        # absolute share drift allowed per verdict
    "makespan_s": 1200.22,
    "makespan_tol": 0.20,        # +/- fraction
}


def twin_regression_errors() -> list:
    """Run the pinned-seed twin campaign and compare against the recorded
    band. Returns human-readable problems (empty list = in band).

    The campaign drives the REAL admission controller and solver over a
    simulated fleet, so this is the cheapest end-to-end check that a
    scheduling-policy change kept its distributional behavior: same tier
    adoption, same verdict mix, same makespan — and the tenant-tagged
    arrival mix keeps the fair-share path on the measured surface.
    """
    import shutil
    import tempfile

    sys.path.insert(0, REPO)
    from saturn_tpu.twin.runner import CampaignConfig, run_campaign

    pin = TWIN_REGRESSION
    out_dir = tempfile.mkdtemp(prefix="twin_regression_")
    try:
        cfg = CampaignConfig(
            n_jobs=pin["n_jobs"], n_slices=pin["n_slices"],
            chips_per_slice=8, interval_s=600.0, solve_deadline_s=5.0,
            base_rate_hz=4.0, burst_rate_hz=12.0, total_batches=3,
            max_inflight=2_000, metrics=False, compact_every=8,
            seed=pin["seed"], max_intervals=200,
            tenant_mix=dict(pin["tenant_mix"]),
        )
        s = run_campaign(cfg, out_dir)
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)
    problems = []
    if s.get("status") != "ok":
        problems.append(f"campaign status {s.get('status')!r}, expected 'ok'")
    if s.get("deadline_misses"):
        problems.append(
            f"{s['deadline_misses']} solver deadline miss(es) in a campaign "
            "shape that historically has zero"
        )
    got_tiers = {str(k): v for k, v in (s.get("tier_shares") or {}).items()}
    for tier in set(pin["tier_shares"]) | set(got_tiers):
        want = pin["tier_shares"].get(tier, 0.0)
        got = got_tiers.get(tier, 0.0)
        if abs(got - want) > pin["tier_band"]:
            problems.append(
                f"tier {tier} share {got:.3f} outside pinned "
                f"{want:.3f} +/- {pin['tier_band']}"
            )
    got_verdicts = dict(s.get("verdict_shares") or {})
    for verdict in set(pin["verdict_shares"]) | set(got_verdicts):
        want = pin["verdict_shares"].get(verdict, 0.0)
        got = got_verdicts.get(verdict, 0.0)
        if abs(got - want) > pin["verdict_band"]:
            problems.append(
                f"verdict {verdict!r} share {got:.3f} outside pinned "
                f"{want:.3f} +/- {pin['verdict_band']}"
            )
    mk = s.get("makespan_s")
    if isinstance(mk, (int, float)) and not isinstance(mk, bool):
        lo = pin["makespan_s"] * (1.0 - pin["makespan_tol"])
        hi = pin["makespan_s"] * (1.0 + pin["makespan_tol"])
        if not lo <= mk <= hi:
            problems.append(
                f"makespan_sim {mk:.1f}s outside pinned "
                f"[{lo:.1f}, {hi:.1f}]s"
            )
    else:
        problems.append(f"campaign makespan_s missing/bad: {mk!r}")
    # The tenant mix must actually skew: the fair-share surface is only
    # exercised when the noisy neighbour dominates the arrival stream.
    sub = s.get("tenant_submitted") or {}
    bursty = sub.get("burst", 0)
    quiet = [v for k, v in sub.items() if k != "burst"]
    if not quiet or any(bursty < 4 * q for q in quiet):
        problems.append(
            f"tenant mix lost its burst skew: {sub!r} (burst must "
            "dominate every quiet tenant at least 4:1)"
        )
    return problems


#: Required key -> type for the ``benchmarks/tenant_fairshare.py`` row.
#: Same contract as the other ROW_REQUIRED tables: the bench self-validates
#: before printing, and recorded rows can be re-checked without re-running.
TENANT_ROW_REQUIRED = {
    "metric": str,                # "tenant_fairshare"
    "n_tenants": int,             # >= 3
    "n_jobs": int,                # contended-phase arrivals
    "burst_skew": float,          # bursty:quiet arrival-weight ratio, >= 10
    "bursty_tenant": str,
    "submitted": dict,            # tenant -> submit attempts
    "admitted": dict,             # tenant -> accepted admissions
    "shed": dict,                 # tenant -> gateway sheds
    "solo_p99_s": float,          # quiet tenant alone on the gateway
    "quiet_p99_s": float,         # quiet tenants under the burst
    "p99_ratio": float,           # quiet_p99 / solo_p99, must stay <= 2
    "warm_hit_rate": float,       # compile-ahead warm phase, must be >= .8
    "first_dispatch_wait_s": float,  # mean compile wait at first dispatch
    "wall_s": float,
    "seed": int,
    "status": str,
}

#: Acceptance bars for the tenant row (shared with the bench so the
#: self-validation and any later re-check apply identical thresholds).
TENANT_MIN_TENANTS = 3
TENANT_MIN_SKEW = 10.0
TENANT_P99_RATIO_MAX = 2.0
TENANT_WARM_HIT_MIN = 0.8


def validate_tenant_row(row) -> list:
    """Schema-check one tenant-fairness row; returns human-readable
    problems (empty list = valid).

    Enforces the fairness acceptance bars: >= 3 tenants at >= 10:1 burst
    skew, the bursty tenant sheds while every quiet tenant sheds NOTHING,
    quiet-tenant p99 admission latency within 2x its solo baseline, and a
    compile-ahead warm hit rate of at least 80%."""
    if not isinstance(row, dict):
        return [f"row is not a dict ({type(row).__name__})"]
    problems = []
    for key, typ in TENANT_ROW_REQUIRED.items():
        if key not in row:
            problems.append(f"missing key {key!r}")
            continue
        val = row[key]
        if typ in (int, float) and isinstance(val, bool):
            problems.append(f"{key!r} is bool, expected {typ.__name__}")
        elif typ is float and isinstance(val, int):
            pass  # whole-number float serialized as int is fine
        elif not isinstance(val, typ):
            problems.append(
                f"{key!r} is {type(val).__name__}, expected {typ.__name__}"
            )
    if row.get("metric") != "tenant_fairshare":
        problems.append(
            f"metric is {row.get('metric')!r}, expected 'tenant_fairshare'"
        )
    nt = row.get("n_tenants")
    if isinstance(nt, int) and not isinstance(nt, bool) \
            and nt < TENANT_MIN_TENANTS:
        problems.append(f"n_tenants {nt} < {TENANT_MIN_TENANTS}")
    skew = row.get("burst_skew")
    if isinstance(skew, (int, float)) and not isinstance(skew, bool) \
            and skew < TENANT_MIN_SKEW:
        problems.append(f"burst_skew {skew} < {TENANT_MIN_SKEW}")
    bursty = row.get("bursty_tenant")
    shed = row.get("shed")
    if isinstance(shed, dict) and isinstance(bursty, str):
        if not shed.get(bursty):
            problems.append(
                f"bursty tenant {bursty!r} shed nothing — the quota/"
                "pressure path was not exercised"
            )
        quiet_shed = {t: n for t, n in shed.items() if t != bursty and n}
        if quiet_shed:
            problems.append(
                f"quiet tenant(s) shed work under the burst: {quiet_shed!r}"
            )
    ratio = row.get("p99_ratio")
    if isinstance(ratio, (int, float)) and not isinstance(ratio, bool) \
            and ratio > TENANT_P99_RATIO_MAX:
        problems.append(
            f"quiet-tenant p99 ratio {ratio} > {TENANT_P99_RATIO_MAX}x "
            "solo baseline (the burst degraded the quiet tenants)"
        )
    hr = row.get("warm_hit_rate")
    if isinstance(hr, (int, float)) and not isinstance(hr, bool) \
            and hr < TENANT_WARM_HIT_MIN:
        problems.append(
            f"warm_hit_rate {hr} < {TENANT_WARM_HIT_MIN} (compile-ahead "
            "missed on jobs it was told about at admission)"
        )
    return problems


#: Required key -> type for the ``benchmarks/grow_defrag.py`` row. Same
#: contract as the other ROW_REQUIRED tables: the bench self-validates
#: before printing, and recorded rows can be re-checked without re-running.
GROW_ROW_REQUIRED = {
    "metric": str,               # "grow_defrag"
    "drained": int,              # deferred jobs admitted after the wave, >= 1
    "defrag_admitted": int,      # gangs the wave unblocked, >= 1
    "moves": int,                # victim relocations executed
    "grow_events": int,          # hysteresis-matured grow events surfaced
    "migrations_done": int,      # two-phase moves that reached migration_done
    "lost_jobs": int,            # unresolved intents + still-blocked, must be 0
    "cap_bytes": int,
    "need_bytes": int,
    "wall_s": float,
    "status": str,
}


def validate_grow_row(row) -> list:
    """Schema-check one grow/defrag row; returns human-readable problems
    (empty list = valid).

    Enforces the elastic scale-up acceptance bars: the wave actually
    unblocked a gang (defrag_admitted >= 1) and the backlog drained
    (drained >= 1) with nothing lost — every journaled ``migration_intent``
    reached a ``migration_done``/``migration_rollback`` and no gang stayed
    blocked (lost_jobs == 0)."""
    if not isinstance(row, dict):
        return [f"row is not a dict ({type(row).__name__})"]
    problems = []
    for key, typ in GROW_ROW_REQUIRED.items():
        if key not in row:
            problems.append(f"missing key {key!r}")
            continue
        val = row[key]
        if typ in (int, float) and isinstance(val, bool):
            problems.append(f"{key!r} is bool, expected {typ.__name__}")
        elif typ is float and isinstance(val, int):
            pass  # whole-number float serialized as int is fine
        elif not isinstance(val, typ):
            problems.append(
                f"{key!r} is {type(val).__name__}, expected {typ.__name__}"
            )
    if row.get("metric") != "grow_defrag":
        problems.append(
            f"metric is {row.get('metric')!r}, expected 'grow_defrag'"
        )
    dr = row.get("drained")
    if isinstance(dr, int) and not isinstance(dr, bool) and dr < 1:
        problems.append(
            f"drained {dr} < 1 (the DEFER backlog never drained — the "
            "occupancy gate stayed closed after the wave)"
        )
    da = row.get("defrag_admitted")
    if isinstance(da, int) and not isinstance(da, bool) and da < 1:
        problems.append(
            f"defrag_admitted {da} < 1 (the wave planned no gang admission)"
        )
    lj = row.get("lost_jobs")
    if isinstance(lj, int) and not isinstance(lj, bool) and lj != 0:
        problems.append(
            f"lost_jobs {lj} != 0 (a migration intent never closed, or a "
            "gang stayed blocked after the wave)"
        )
    return problems


def grow_defrag_errors() -> list:
    """Run the hardware-free grow/defrag bench and validate its row.

    Cheap (<1s, no JAX): the real monitor, occupancy gate, defrag planner
    and two-phase journal drive a scripted heal-and-compact loop. A
    scheduling or durability change that stops the backlog draining — or
    leaves a migration intent unresolved — fails the guard here."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import grow_defrag

    row = grow_defrag.run()
    return validate_grow_row(row)


#: Required key -> type for the ``benchmarks/comm_overlap.py`` row.
OVERLAP_ROW_REQUIRED = {
    "metric": str,               # "comm_overlap"
    "platform": str,
    "host_cores": int,
    "pairs": dict,               # per-lowering serial/overlapped results
    "headline": str,
    "serial_ms": float,
    "overlapped_ms": float,
    "speedup": float,
    "mfu_serial": float,
    "mfu_overlapped": float,
    "bit_identical": bool,       # SGD loss trajectories bitwise equal
    "priced": dict,              # shardflow static pricing, serial vs over
}

#: Measured-step-time noise tolerance. On a host that cannot overlap (one
#: core: XLA runs every thunk serially) the double-buffered program pays a
#: small copy tax over serial — bounded, not a regression. On hardware with
#: real DMA/compute concurrency the bar tightens to "no slower than serial".
OVERLAP_TOL_PCT = float(os.environ.get("SATURN_OVERLAP_TOL_PCT", "15"))


def validate_overlap_row(row) -> list:
    """Schema + acceptance check for one comm_overlap row.

    Bars: every pair's loss trajectory bitwise equal across the knob flip
    (overlap must never change arithmetic); measured overlapped step time
    within ``OVERLAP_TOL_PCT`` of serial everywhere and <= serial outright
    on hosts that can actually overlap (TPU, or multi-core CPU); MFU
    non-decreasing within the same tolerance; and the shardflow-priced
    speedup strictly > 1 — the deterministic witness that the per-op-class
    overlap factors re-price the placement."""
    if not isinstance(row, dict):
        return [f"row is not a dict ({type(row).__name__})"]
    problems = []
    for key, typ in OVERLAP_ROW_REQUIRED.items():
        if key not in row:
            problems.append(f"missing key {key!r}")
            continue
        val = row[key]
        if typ in (int, float) and isinstance(val, bool):
            problems.append(f"{key!r} is bool, expected {typ.__name__}")
        elif typ is float and isinstance(val, int):
            pass
        elif not isinstance(val, typ):
            problems.append(
                f"{key!r} is {type(val).__name__}, expected {typ.__name__}"
            )
    if row.get("metric") != "comm_overlap":
        problems.append(
            f"metric is {row.get('metric')!r}, expected 'comm_overlap'"
        )
    if row.get("bit_identical") is not True:
        problems.append(
            "bit_identical is not true (an overlap knob changed the "
            "arithmetic, not just the communication schedule)"
        )
    tol = OVERLAP_TOL_PCT / 100.0
    sp = row.get("speedup")
    if isinstance(sp, (int, float)) and not isinstance(sp, bool):
        can_overlap = (
            row.get("platform") == "tpu" or int(row.get("host_cores", 1)) > 1
        )
        if can_overlap and sp < 1.0:
            problems.append(
                f"headline speedup {sp} < 1.0 on a host that can overlap "
                "(overlapped step time exceeds serial)"
            )
        elif sp < 1.0 - tol:
            problems.append(
                f"headline speedup {sp} < {1.0 - tol:.2f} (the overlapped "
                "program costs more than the serialized-host copy tax)"
            )
    mfu_s, mfu_o = row.get("mfu_serial"), row.get("mfu_overlapped")
    if all(isinstance(x, (int, float)) and not isinstance(x, bool)
           for x in (mfu_s, mfu_o)) and mfu_o < mfu_s * (1.0 - tol):
        problems.append(
            f"mfu_overlapped {mfu_o} dropped more than {OVERLAP_TOL_PCT}% "
            f"below mfu_serial {mfu_s}"
        )
    priced = row.get("priced")
    if isinstance(priced, dict):
        psp = priced.get("speedup")
        if not (isinstance(psp, (int, float)) and not isinstance(psp, bool)
                and psp > 1.0):
            problems.append(
                f"priced speedup {psp!r} not > 1.0 (the overlap factors "
                "no longer discount the overlapped lowering's wire time)"
            )
    return problems


def comm_overlap_errors() -> list:
    """Run the comm/compute overlap bench and validate its row.

    The heavyweight part of the guard (a few minutes of jit on a cold CPU
    host): three serial/overlapped program pairs stepped for bit-identity
    and timed, plus the shardflow-priced pair. Kept at low reps — the
    validation bars are tolerance-based, not throughput-based."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import comm_overlap

    row = comm_overlap.run(reps=3, steps=2)
    return validate_overlap_row(row)


def shape_key(parsed: dict) -> tuple:
    """What must match for two bench numbers to be comparable."""
    return (
        parsed.get("workload"),    # e.g. benchmarks/coschedule.py tags its
        parsed.get("platform"),    # row "coschedule_pair"; bench.py rows
        parsed.get("batch_size"),  # carry no tag — the two never gate each
        parsed.get("seq_len"),     # other. batch_size: degraded runs only.
    )


def main() -> int:
    ref = latest_record()
    threshold = float(os.environ.get("SATURN_BENCH_GUARD_PCT", "10")) / 100.0
    if ref is None:
        print(json.dumps({
            "metric": "bench_guard", "status": "skipped",
            "reason": "no BENCH_r*.json with a parsed value",
        }))
        return 0
    n, parsed_ref = ref
    if os.environ.get("SATURN_TPU_TSAN", "") == "1":
        # The sanitizer's traced locks/queues sit on the measured hot path:
        # numbers produced under instrumentation are not comparable to (or
        # recordable as) baselines.
        print(json.dumps({
            "metric": "bench_guard", "status": "tsan_instrumented",
            "reason": "refusing to gate: SATURN_TPU_TSAN=1 instruments "
                      "the measured hot path",
        }))
        return 1
    new = run_bench()
    if new.get("tsan"):
        print(json.dumps({
            "metric": "bench_guard", "status": "tsan_instrumented",
            "value": new.get("value"),
            "reason": "bench row was produced under SATURN_TPU_TSAN=1",
        }))
        return 1
    try:
        plan_errors = bench_plan_errors(new)
    except Exception as e:
        plan_errors = [{"code": "SAT-P000", "severity": "error",
                        "message": f"verifier unavailable: "
                                   f"{type(e).__name__}: {e}"}]
    if plan_errors:
        # Refuse to record: a row measured under a plan the static verifier
        # rejects is not a baseline anyone should compare against.
        print(json.dumps({
            "metric": "bench_guard", "status": "plan_verification_failed",
            "value": new.get("value"), "diagnostics": plan_errors,
        }))
        return 1
    try:
        sf_errors = bench_shardflow_errors()
    except Exception as e:
        sf_errors = [{"code": "SAT-X000", "severity": "error",
                      "message": f"shardflow pass unavailable: "
                                 f"{type(e).__name__}: {e}"}]
    if sf_errors:
        # Same refusal for the sharding pass: the row was measured by a
        # technique whose source carries an unsanctioned SAT-X funnel.
        print(json.dumps({
            "metric": "bench_guard", "status": "shardflow_findings",
            "value": new.get("value"), "diagnostics": sf_errors,
        }))
        return 1
    try:
        ml_errors = bench_memlens_errors()
    except Exception as e:
        ml_errors = [{"code": "SAT-M000", "severity": "error",
                      "message": f"memlens pass unavailable: "
                                 f"{type(e).__name__}: {e}"}]
    if ml_errors:
        # Same refusal for the liveness pass: the row was measured by a step
        # function carrying an unsanctioned SAT-M memory defect.
        print(json.dumps({
            "metric": "bench_guard", "status": "memlens_findings",
            "value": new.get("value"), "diagnostics": ml_errors,
        }))
        return 1
    try:
        tw_errors = twin_regression_errors()
    except Exception as e:
        tw_errors = [f"twin regression campaign unavailable: "
                     f"{type(e).__name__}: {e}"]
    if tw_errors:
        # Same refusal for the scheduling policy: the row was measured by a
        # control plane whose tier/verdict/makespan distributions drifted
        # out of the pinned twin band.
        print(json.dumps({
            "metric": "bench_guard", "status": "twin_regression",
            "value": new.get("value"), "diagnostics": tw_errors,
        }))
        return 1
    try:
        gd_errors = grow_defrag_errors()
    except Exception as e:
        gd_errors = [f"grow/defrag bench unavailable: "
                     f"{type(e).__name__}: {e}"]
    if gd_errors:
        # Same refusal for the recovery path: the row was measured by a
        # control plane whose grow/defrag loop lost work or left a
        # migration intent unresolved.
        print(json.dumps({
            "metric": "bench_guard", "status": "grow_defrag_failed",
            "value": new.get("value"), "diagnostics": gd_errors,
        }))
        return 1
    try:
        ov_errors = comm_overlap_errors()
    except Exception as e:
        ov_errors = [f"comm overlap bench unavailable: "
                     f"{type(e).__name__}: {e}"]
    if ov_errors:
        # Same refusal for the overlapped lowerings: a knob flip that
        # changed arithmetic (or an overlapped program that got slower
        # than its serial twin beyond the serialized-host tax) must not
        # be recorded as a baseline.
        print(json.dumps({
            "metric": "bench_guard", "status": "comm_overlap_failed",
            "value": new.get("value"), "diagnostics": ov_errors,
        }))
        return 1
    out = {
        "metric": "bench_guard",
        "value": new.get("value"),
        "reference": parsed_ref["value"],
        "reference_round": n,
        "threshold_pct": threshold * 100.0,
    }
    if shape_key(new) != shape_key(parsed_ref):
        # e.g. the reference is a degraded CPU record but this host has a
        # live TPU — different workload shapes, no comparison to make.
        out["status"] = "skipped"
        out["reason"] = (
            f"shape mismatch: ran {shape_key(new)} vs "
            f"recorded {shape_key(parsed_ref)}"
        )
        print(json.dumps(out))
        return 0
    floor = parsed_ref["value"] * (1.0 - threshold)
    if new.get("value", 0.0) < floor:
        out["status"] = "regression"
        out["floor"] = round(floor, 1)
        print(json.dumps(out))
        return 1
    out["status"] = "ok"
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
