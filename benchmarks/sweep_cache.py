"""Cold vs warm trial-sweep benchmark: what the persistent profile cache buys.

Runs ``saturn_tpu.search`` twice over the standard two-task CPU fixture
(tiny GPT-2, 8 virtual devices — the ``tests/test_e2e.py`` shape) against a
fresh cache directory: the first sweep compiles real trials, the second must
resolve every grid point from the profile cache without a single
``technique.search`` execution. Prints ONE JSON line like ``bench.py``:

    {"metric": "sweep_cache_warm_speedup", "value": <cold/warm>, "unit": "x",
     "cold_s": ..., "warm_s": ...}

Hardware-free by construction (``JAX_PLATFORMS=cpu`` is forced before jax
imports), so the number is about orchestration overhead, not TPU compiles —
on real hardware the gap widens by the ~1 min/trial compile cost this
eliminates. Run: ``python benchmarks/sweep_cache.py``.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import timeit

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import saturn_tpu
from saturn_tpu import HParams, Task, library
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.data.lm_dataset import make_lm_dataset
from saturn_tpu.models.gpt2 import build_gpt2
from saturn_tpu.models.loss import pretraining_loss


def make_task(save_dir: str, name: str, lr: float) -> Task:
    return Task(
        get_model=lambda **kw: build_gpt2("test-tiny", **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256, n_tokens=64 * 8 * 8
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=lr, batch_count=8),
        chip_range=[4],
        name=name,
        save_dir=save_dir,
    )


def run_sweep(cache_dir: str, work_dir: str, tag: str) -> float:
    # Fresh task objects per sweep: a warm hit must come from the persistent
    # cache's content fingerprints, not from state left on the task.
    tasks = [
        make_task(work_dir, f"{tag}-lr3", 1e-3),
        make_task(work_dir, f"{tag}-lr4", 1e-4),
    ]
    topo = SliceTopology(jax.devices())
    t0 = timeit.default_timer()
    saturn_tpu.search(
        tasks, technique_names=["dp"], topology=topo, profile_cache=cache_dir
    )
    dt = timeit.default_timer() - t0
    for t in tasks:
        assert t.feasible_strategies(), f"no feasible strategy for {t.name}"
    return dt


def main() -> None:
    library.register_default_library()
    root = tempfile.mkdtemp(prefix="saturn_sweep_cache_")
    cache_dir = os.path.join(root, "profiles")
    try:
        cold = run_sweep(cache_dir, os.path.join(root, "w1"), "cold")
        warm = run_sweep(cache_dir, os.path.join(root, "w2"), "warm")
    finally:
        shutil.rmtree(root, ignore_errors=True)
    print(
        json.dumps(
            {
                "metric": "sweep_cache_warm_speedup",
                "value": round(cold / warm, 2) if warm > 0 else None,
                "unit": "x",
                "cold_s": round(cold, 3),
                "warm_s": round(warm, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
