"""Elastic scale-up + defrag wave: drain a blocked gang, hardware-free.

Scripted deterministic mini-loop over the real recovery components — no
JAX, no solver, no threads:

1. a degraded fleet (half the devices lost) heals: the real
   ``FleetHealthMonitor`` surfaces the ``grow`` event through its
   hysteresis gate and the ``GrowCoordinator`` journals it;
2. a deferred gang's HBM footprint (``need`` bytes per device) fits no
   block because two running tasks pin live state on opposite halves of
   the ring — the coordinator's occupancy gate says ``fits: False``;
3. ``plan_defrag_wave`` compacts the pinned tasks (victim relocation with
   headroom checks) and ``execute_wave`` runs the moves through the
   two-phase ``migration_intent``/``migration_done`` journal;
4. the gate flips to ``fits: True`` — the gang drains — and the journal
   is re-folded (the same fold ``analysis grow`` uses) to prove every
   intent closed: ``lost_jobs`` counts unresolved intents, so 0 means a
   crash replay would have nothing left open either.

Prints ONE JSON line like ``bench.py``:

    {"metric": "grow_defrag", "drained": 1, "defrag_admitted": 1,
     "moves": 1, "lost_jobs": 0, ...}

``bench_guard.validate_grow_row`` enforces drained >= 1,
defrag_admitted >= 1 and lost_jobs == 0. Run:
``python benchmarks/grow_defrag.py``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

#: Modeled per-device HBM capacity (bytes). Small round numbers keep the
#: arithmetic legible: live tasks pin 60 B/device, the gang needs 80.
CAP_BYTES = 100
PIN_BYTES = 60
NEED_BYTES = 80


class FakeDev:
    process_index = 0


class FakeTask:
    """Minimal task surface the recovery components touch."""

    def __init__(self, name, sizes, resident=0):
        self.name = name
        self._sizes = tuple(sizes)
        self.resident_bytes = resident
        self._live_state = object() if resident else None
        self.hints = {}
        self.released = 0

    def feasible_strategies(self):
        return list(self._sizes)

    def release_live_state(self):
        self._live_state = None
        self.released += 1


class FakePlan:
    def __init__(self, assignments):
        self.assignments = assignments


class _Slot:
    def __init__(self, block):
        self.block = block


def run() -> dict:
    from saturn_tpu.analysis.cli import _fold_grow_records
    from saturn_tpu.core.mesh import Block, SliceTopology
    from saturn_tpu.durability import journal as jmod
    from saturn_tpu.resilience import FleetHealthMonitor, GrowCoordinator

    t0 = time.time()
    topo = SliceTopology([FakeDev() for _ in range(8)], slice_size=8)

    # Two running tasks pin live state on opposite halves of the ring;
    # the deferred gang needs a 4-device block with NEED_BYTES headroom.
    live1 = FakeTask("live-a", (2,), resident=PIN_BYTES)
    live2 = FakeTask("live-b", (2,), resident=PIN_BYTES)
    gang = FakeTask("gang-big", (4,), resident=NEED_BYTES)
    plan = FakePlan({
        "live-a": _Slot(Block(0, 2)),
        "live-b": _Slot(Block(4, 2)),
    })
    live = [live1, live2]

    out_dir = tempfile.mkdtemp(prefix="grow_defrag_")
    jnl = jmod.Journal(out_dir)
    # cap_bytes pinned on the coordinator, NOT via SATURN_TPU_HBM_BYTES —
    # mutating the process env here would poison bench_guard's memlens
    # gate running later in the same process.
    coord = GrowCoordinator(journal=jnl, poll_every=0, cap_bytes=CAP_BYTES)
    gate = coord.occupancy_gate(lambda: live + [gang], lambda: plan)

    # 1. the fleet heals: shrink consumed earlier, the return matures
    # through the hysteresis gate and surfaces as a grow.
    mon = FleetHealthMonitor(8, grow_hysteresis=1)
    mon.mark_lost([4, 5, 6, 7], cause="slice_preemption")
    assert mon.poll().kind == "shrink"
    mon.mark_restored([4, 5, 6, 7])
    change = mon.poll()
    assert change is not None and change.kind == "grow"
    grow_events = 1
    coord.note_grow(change, interval_index=1, n_deferred=1,
                    capacity=topo.capacity)

    # 2. occupancy blocks the gang even after the grow.
    before = gate(gang, topo)
    assert before is not None and before["fits"] is False

    # 3. plan + execute the defrag wave (two-phase journaled moves).
    wave = coord.plan_wave([gang], live, topo, plan)
    wave_id = coord.execute_wave(
        wave, {t.name: t for t in live}, interval_index=1,
        publish_fn=lambda task: True,
    )
    for mv in wave.moves:
        plan.assignments[mv.task] = _Slot(Block(*mv.to_block))

    # 4. the gate flips; the gang drains.
    after = gate(gang, topo)
    drained = 1 if (after is None or after["fits"]) else 0
    if drained:
        coord.note_drained([gang.name], interval_index=1, trigger="grow")
    jnl.close()

    folded = _fold_grow_records(jmod.replay(out_dir))
    lost_jobs = len(folded["unresolved_intents"]) + len(wave.still_blocked)
    row = {
        "metric": "grow_defrag",
        "drained": drained,
        "defrag_admitted": len(wave.admitted),
        "moves": len(wave.moves),
        "released_live_states": sum(t.released for t in live),
        "grow_events": grow_events,
        "journaled_grow_events": len(folded["grow_events"]),
        "migrations_done": folded["migrations"]["done"],
        "lost_jobs": lost_jobs,
        "wave": wave_id,
        "cap_bytes": CAP_BYTES,
        "need_bytes": NEED_BYTES,
        "wall_s": round(time.time() - t0, 6),
        "status": "ok" if (drained and wave.admitted and not lost_jobs)
                  else "blocked",
    }
    return row


def main() -> int:
    row = run()
    from bench_guard import validate_grow_row

    problems = validate_grow_row(row)
    if problems:
        row["status"] = "invalid"
        row["problems"] = problems
    print(json.dumps(row, sort_keys=True))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
