"""Capture and summarize a jax.profiler trace of the config-#1 train step.

VERDICT r2 item 1(b): name the top time sinks of the GPT-2-small b8x512 step
on the real chip so the MFU work acts on measurements, not guesses.

Runs the same step bench.py measures — model default attention ('auto' →
flash on TPU) and the fused head+loss when the model provides one
(``--loss logits`` forces the unfused pipeline for A/B traces) — traces a
few steps with jax.profiler, then parses the xplane proto with xprof and
prints the per-op rollup.

Run: ``python benchmarks/profile_step.py [--attention auto|dense|flash]
[--loss fused|logits] [--outdir /tmp/saturn_trace]``
"""

from __future__ import annotations

import argparse
import glob
import json
import os


def summarize_xplane(trace_dir: str, top_k: int = 25):
    """Extract per-op self-times from the captured .xplane.pb."""
    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        raise SystemExit(f"no xplane.pb under {trace_dir}")
    path = max(paths, key=os.path.getmtime)
    from xprof.convert import raw_to_tool_data as rtd

    # op_profile: per-HLO-category/op rollup with fused-op attribution.
    data, _ = rtd.xspace_to_tool_data([path], "op_profile", params={})
    return path, json.loads(data)


def walk_op_profile(node, depth=0, rows=None, path=()):
    """Flatten op_profile's byProgram/byCategory tree into (name, time) rows."""
    if rows is None:
        rows = []
    name = node.get("name", "?")
    metrics = node.get("metrics") or {}
    t = metrics.get("rawTime", 0)
    kids = node.get("children") or []
    if not kids and t:
        rows.append(("/".join(path + (name,)), t, metrics))
    for ch in kids:
        walk_op_profile(ch, depth + 1, rows, path + (name,))
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--attention", default="auto",
                    choices=["auto", "dense", "flash"])
    ap.add_argument("--loss", default="fused", choices=["fused", "logits"])
    ap.add_argument("--outdir", default="/tmp/saturn_trace")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument(
        "--parse-only", action="store_true",
        help="skip the run; just summarize an existing trace in --outdir",
    )
    args = ap.parse_args()

    if not args.parse_only:
        import jax
        import jax.numpy as jnp
        import optax

        from saturn_tpu.data.lm_dataset import make_lm_dataset
        from saturn_tpu.models.gpt2 import build_gpt2
        from saturn_tpu.models.loss import pretraining_loss

        spec = build_gpt2(
            "gpt2-small", seq_len=args.seq, attention=args.attention
        )
        ds = make_lm_dataset(
            context_length=args.seq, batch_size=args.batch,
            vocab_size=spec.config.vocab_size,
            n_tokens=args.seq * args.batch * 8,
        )
        tx = optax.adamw(3e-4)

        def init_state():
            p = spec.init_fn(jax.random.PRNGKey(0))
            return {"params": p, "opt": tx.init(p)}

        if args.loss == "fused" and spec.fused_loss_fn is not None:
            loss_of_params = spec.fused_loss_fn
        else:
            loss_of_params = lambda p, b: pretraining_loss(
                spec.apply_fn(p, b), b
            )

        def train_step(state, batch):
            loss, g = jax.value_and_grad(loss_of_params)(
                state["params"], batch
            )
            up, opt = tx.update(g, state["opt"], state["params"])
            return {"params": optax.apply_updates(state["params"], up),
                    "opt": opt}, loss

        step = jax.jit(train_step, donate_argnums=(0,))
        state = jax.jit(init_state)()
        batches = [jnp.asarray(ds.batch(i)) for i in range(4)]
        for _ in range(3):  # compile + warm
            state, loss = step(state, batches[0])
        float(jax.device_get(loss))

        os.makedirs(args.outdir, exist_ok=True)
        with jax.profiler.trace(args.outdir):
            for i in range(args.steps):
                state, loss = step(state, batches[i % len(batches)])
            float(jax.device_get(loss))

    path, prof = summarize_xplane(args.outdir)
    print(f"trace: {path}\n")
    rows = walk_op_profile(
        prof.get("byProgramExcludeIdle") or prof.get("byCategory") or prof
    )
    total = sum(t for _, t, _ in rows) or 1
    rows.sort(key=lambda r: -r[1])
    print(f"| % of device time | op (category/op) | FLOPS util |")
    print(f"|---|---|---|")
    for name, t, metrics in rows[: args.top]:
        util = metrics.get("flops", 0)
        print(f"| {100.0 * t / total:5.1f}% | {name} | {util:.3f} |")


if __name__ == "__main__":
    main()
