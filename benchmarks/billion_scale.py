"""Billion-parameter single-chip capability row (VERDICT r3 item 4).

The reference demonstrates its spilled executor on GPT-J-6B
(``examples/wikitext103/WikiText103.py:62-71``, ``Spilled.py:23-28``); the
saturn_tpu analog is ``parallel/offload.py`` (pinned_host params + per-layer
scan streaming). This script instantiates a GPT-J-class >=1B preset under
the offload executor on ONE chip and records the BASELINE.md capability
row: parameter count, samples/s, achieved tokens/s, and the XLA-analyzed
vs measured HBM high-water.

Each config runs in this process directly (run one config per invocation —
``peak_bytes_in_use`` is a process-lifetime high-water mark).

Run on TPU:
  PYTHONPATH=/root/repo:$PYTHONPATH python benchmarks/billion_scale.py \
      [--preset gptj-1b3] [--batch 4] [--seq 1024] [--steps 3]
CPU smoke (tiny shapes, mechanism only):
  python benchmarks/billion_scale.py --preset gptj-6b --layers 2 \
      --batch 1 --seq 128 --steps 1 --platform cpu
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import timeit

import numpy as np


def _ckpt_phase(args, spec_shapes) -> dict:
    """Round-19 checkpoint-I/O row: time the legacy allgather-one-writer
    save (emulated inline — the production writer no longer has a gather
    path) against the sharded-manifest format, cold and async-overlapped,
    then restore onto a *resized* mesh and bit-compare every leaf.

    The legacy emulation is exactly what ``utils/checkpoint.save`` used to
    do: replicate each leaf across the mesh (``P()``), pull the full array
    to one host, and write a single ``np.savez`` archive. The sharded
    writer copies only per-shard local bytes, so the delta is the gather
    funnel the round removed.
    """
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from saturn_tpu.utils import checkpoint as ckpt

    devices = jax.devices()
    ndev = len(devices)
    mesh = Mesh(np.asarray(devices), ("dp",))

    # Deterministic host tree at the model's real leaf shapes (plus the 0-d
    # step counter every train state carries) — cheap to build, and the
    # bytes are reproducible for the bit-identity check.
    rng = np.random.default_rng(0)
    host = {"step": np.asarray(1234, dtype=np.int32)}
    for i, leaf in enumerate(jax.tree_util.tree_leaves(spec_shapes)):
        host[f"w{i}"] = rng.standard_normal(
            int(np.prod(leaf.shape)), dtype=np.float32
        ).reshape(leaf.shape)
    state_bytes = sum(a.nbytes for a in host.values())

    def rule(arr):
        if arr.ndim and arr.shape[0] % ndev == 0:
            return NamedSharding(mesh, P("dp"))
        return NamedSharding(mesh, P())

    state = {k: jax.device_put(v, rule(v)) for k, v in host.items()}

    base = os.path.join(args.ckpt_dir, "bench")
    os.makedirs(args.ckpt_dir, exist_ok=True)
    legacy_path = base + ".legacy.npz"
    sharded_path = base + ".npz"

    # -- legacy allgather writer (what save() did before round 19) --------
    t0 = timeit.default_timer()
    gathered = {}
    for k, v in state.items():
        rep = jax.device_put(v, NamedSharding(mesh, P()))
        gathered[k] = np.asarray(jax.device_get(rep.addressable_data(0)))
    tmp = legacy_path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **gathered)
    os.replace(tmp, legacy_path)
    allgather_s = timeit.default_timer() - t0
    del gathered

    # -- sharded manifest, cold ------------------------------------------
    t0 = timeit.default_timer()
    ckpt.save(sharded_path, state)
    sharded_s = timeit.default_timer() - t0

    # -- sharded manifest, async: caller-visible latency is snapshot-only -
    t0 = timeit.default_timer()
    ckpt.save_async(sharded_path, state)
    async_block_s = timeit.default_timer() - t0
    ckpt.flush()

    # -- restore onto a resized mesh (migration path) --------------------
    half = max(ndev // 2, 1)
    mesh2 = Mesh(np.asarray(devices[:half]), ("dp",))

    def rule2(tree_path, shape_struct):
        if shape_struct.ndim and shape_struct.shape[0] % half == 0:
            return NamedSharding(mesh2, P("dp"))
        return NamedSharding(mesh2, P())

    t0 = timeit.default_timer()
    restored = ckpt.restore_sharded(sharded_path, state, rule2)
    jax.block_until_ready(restored)
    restore_s = timeit.default_timer() - t0

    identical = all(
        np.asarray(jax.device_get(restored[k])).tobytes() == host[k].tobytes()
        for k in host
    )

    manifest_bytes = os.path.getsize(sharded_path)
    shard_files = len([
        n for n in os.listdir(args.ckpt_dir)
        if ckpt._SHARD_RE.search(n)
        and n.startswith(os.path.basename(sharded_path))
    ])

    return {
        "metric": "ckpt_io",
        "preset": args.preset,
        "platform": jax.devices()[0].platform,
        "n_devices": ndev,
        "state_bytes": int(state_bytes),
        "allgather_save_s": round(allgather_s, 4),
        "sharded_save_s": round(sharded_s, 4),
        "sharded_async_block_s": round(async_block_s, 4),
        "sharded_restore_s": round(restore_s, 4),
        "restore_bit_identical": bool(identical),
        "shard_files": shard_files,
        "speedup_vs_allgather": round(allgather_s / max(sharded_s, 1e-9), 3),
        "manifest_bytes": int(manifest_bytes),
        "status": "ok" if identical else "failed",
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gptj-1b3")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--layers", type=int, default=None,
                    help="override layer count (CPU smoke at real d_model)")
    ap.add_argument("--platform", choices=["default", "cpu"], default="default")
    ap.add_argument("--stream", type=int, default=1)
    ap.add_argument("--remat", type=int, default=1)
    ap.add_argument("--ckpt", type=int, default=1,
                    help="also run the checkpoint-I/O phase (ckpt_io row)")
    ap.add_argument("--ckpt-only", action="store_true",
                    help="skip the offload training phase (e.g. on hosts "
                         "whose jax lacks the pinned_host memory API) and "
                         "emit only the ckpt_io row")
    ap.add_argument("--ckpt-dir", default="/tmp/saturn_billion_ckpts/io_bench")
    args = ap.parse_args()

    if args.platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        if args.ckpt:
            # the ckpt phase needs a real mesh to shard over; the offload
            # phase still pins itself to devices[:1]
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss
    from saturn_tpu.parallel.offload import HostOffload
    from saturn_tpu.utils.timing import device_hbm_bytes

    overrides = {"seq_len": args.seq}
    if args.layers is not None:
        overrides["n_layers"] = args.layers

    def get_model(**kw):
        return build_gpt2(args.preset, **{**overrides, **kw})

    spec = get_model()
    shapes = jax.eval_shape(spec.init_fn, jax.random.PRNGKey(0))
    n_params = sum(
        int(np.prod(s.shape)) for s in jax.tree_util.tree_leaves(shapes)
    )
    print(f"{args.preset}: {n_params/1e9:.2f}B params, "
          f"b{args.batch}x{args.seq}, layers={spec.config.n_layers}",
          file=sys.stderr)

    if args.ckpt_only:
        _emit_ckpt_row(args, shapes)
        return

    task = Task(
        get_model=get_model,
        get_dataloader=lambda: make_lm_dataset(
            context_length=args.seq, batch_size=args.batch,
            vocab_size=spec.config.vocab_size,
            n_tokens=args.seq * args.batch * 4,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-4, batch_count=args.steps),
        save_dir="/tmp/saturn_billion_ckpts",
    )

    off = HostOffload()
    devices = jax.devices()[:1]
    config = {"stream": bool(args.stream), "remat": bool(args.remat)}
    bundle = off.build(task, devices, config)
    state = bundle.init()
    batch = jax.device_put(task.get_dataset().batch(0), bundle.batch_sharding)
    # warmup / compile
    state, loss = bundle.step(state, batch)
    loss0 = float(jax.device_get(loss))

    t0 = timeit.default_timer()
    for i in range(args.steps):
        b = jax.device_put(task.get_dataset().batch(i % 3 + 1),
                           bundle.batch_sharding)
        state, loss = bundle.step(state, b)
    lossN = float(jax.device_get(loss))
    dt = (timeit.default_timer() - t0) / args.steps

    dev = jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)() or {}
    out = {
        "metric": "billion_scale_offload",
        "preset": args.preset,
        "params_b": round(n_params / 1e9, 3),
        "batch": args.batch,
        "seq": args.seq,
        "config": config,
        "samples_per_s": round(args.batch / dt, 3),
        "tokens_per_s": round(args.batch * args.seq / dt, 1),
        "step_s": round(dt, 3),
        "loss_first": round(loss0, 4),
        "loss_last": round(lossN, 4),
        "hbm_limit_gib": round(device_hbm_bytes(dev) / 2**30, 2),
        "hbm_peak_gib": round(stats.get("peak_bytes_in_use", 0) / 2**30, 2),
        "platform": dev.platform,
    }
    print(json.dumps(out))

    if args.ckpt:
        _emit_ckpt_row(args, shapes)


def _emit_ckpt_row(args, shapes) -> None:
    """Run the ckpt-I/O phase, self-validate against the schema (and the
    recorded row's regression bar, if any), and print the JSON row."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import bench_guard

    row = _ckpt_phase(args, shapes)
    ref = bench_guard.latest_ckpt_record()
    problems = bench_guard.validate_ckpt_row(
        row, reference=ref[1] if ref else None
    )
    if problems:
        for p in problems:
            print(f"ckpt_io row invalid: {p}", file=sys.stderr)
        print(json.dumps(row))
        sys.exit(1)
    print(json.dumps(row))


if __name__ == "__main__":
    main()
