"""Billion-parameter single-chip capability row (VERDICT r3 item 4).

The reference demonstrates its spilled executor on GPT-J-6B
(``examples/wikitext103/WikiText103.py:62-71``, ``Spilled.py:23-28``); the
saturn_tpu analog is ``parallel/offload.py`` (pinned_host params + per-layer
scan streaming). This script instantiates a GPT-J-class >=1B preset under
the offload executor on ONE chip and records the BASELINE.md capability
row: parameter count, samples/s, achieved tokens/s, and the XLA-analyzed
vs measured HBM high-water.

Each config runs in this process directly (run one config per invocation —
``peak_bytes_in_use`` is a process-lifetime high-water mark).

Run on TPU:
  PYTHONPATH=/root/repo:$PYTHONPATH python benchmarks/billion_scale.py \
      [--preset gptj-1b3] [--batch 4] [--seq 1024] [--steps 3]
CPU smoke (tiny shapes, mechanism only):
  python benchmarks/billion_scale.py --preset gptj-6b --layers 2 \
      --batch 1 --seq 128 --steps 1 --platform cpu
"""

from __future__ import annotations

import argparse
import json
import sys
import timeit

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gptj-1b3")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--layers", type=int, default=None,
                    help="override layer count (CPU smoke at real d_model)")
    ap.add_argument("--platform", choices=["default", "cpu"], default="default")
    ap.add_argument("--stream", type=int, default=1)
    ap.add_argument("--remat", type=int, default=1)
    args = ap.parse_args()

    if args.platform == "cpu":
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss
    from saturn_tpu.parallel.offload import HostOffload
    from saturn_tpu.utils.timing import device_hbm_bytes

    overrides = {"seq_len": args.seq}
    if args.layers is not None:
        overrides["n_layers"] = args.layers

    def get_model(**kw):
        return build_gpt2(args.preset, **{**overrides, **kw})

    spec = get_model()
    shapes = jax.eval_shape(spec.init_fn, jax.random.PRNGKey(0))
    n_params = sum(
        int(np.prod(s.shape)) for s in jax.tree_util.tree_leaves(shapes)
    )
    print(f"{args.preset}: {n_params/1e9:.2f}B params, "
          f"b{args.batch}x{args.seq}, layers={spec.config.n_layers}",
          file=sys.stderr)

    task = Task(
        get_model=get_model,
        get_dataloader=lambda: make_lm_dataset(
            context_length=args.seq, batch_size=args.batch,
            vocab_size=spec.config.vocab_size,
            n_tokens=args.seq * args.batch * 4,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-4, batch_count=args.steps),
        save_dir="/tmp/saturn_billion_ckpts",
    )

    off = HostOffload()
    devices = jax.devices()[:1]
    config = {"stream": bool(args.stream), "remat": bool(args.remat)}
    bundle = off.build(task, devices, config)
    state = bundle.init()
    batch = jax.device_put(task.get_dataset().batch(0), bundle.batch_sharding)
    # warmup / compile
    state, loss = bundle.step(state, batch)
    loss0 = float(jax.device_get(loss))

    t0 = timeit.default_timer()
    for i in range(args.steps):
        b = jax.device_put(task.get_dataset().batch(i % 3 + 1),
                           bundle.batch_sharding)
        state, loss = bundle.step(state, b)
    lossN = float(jax.device_get(loss))
    dt = (timeit.default_timer() - t0) / args.steps

    dev = jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)() or {}
    out = {
        "metric": "billion_scale_offload",
        "preset": args.preset,
        "params_b": round(n_params / 1e9, 3),
        "batch": args.batch,
        "seq": args.seq,
        "config": config,
        "samples_per_s": round(args.batch / dt, 3),
        "tokens_per_s": round(args.batch * args.seq / dt, 1),
        "step_s": round(dt, 3),
        "loss_first": round(loss0, 4),
        "loss_last": round(lossN, 4),
        "hbm_limit_gib": round(device_hbm_bytes(dev) / 2**30, 2),
        "hbm_peak_gib": round(stats.get("peak_bytes_in_use", 0) / 2**30, 2),
        "platform": dev.platform,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
