"""Tokenizer throughput at WikiText scale: native C++ vs Python fallback.

VERDICT r3 item 6: the reference tokenizes real WikiText-103 (50k vocab,
100M+ tokens) through torchtext's native machinery; saturn_tpu's equivalent
is ``native/tokenize.cpp`` behind ``data/lm_dataset.word_tokenize_file``.
This benchmark proves the path at reference scale on a locally generated
corpus (zero-egress image — ``data/corpus_gen.py``):

1. generate/reuse a ~120 MB corpus with >64k word types;
2. build a 50304-entry vocab + encode with the NATIVE tokenizer (cold
   cache), timed;
3. same with the pure-Python fallback, timed;
4. assert both produce the identical id stream and vocab size (the cache
   poisoning guard — the two paths must be byte-identical semantics);
5. print one JSON line with MB/s for both, the speedup, and scale stats.

Run: ``python benchmarks/tokenizer_bench.py [--size-mb 120]``
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from saturn_tpu.data.corpus_gen import generate_corpus  # noqa: E402
from saturn_tpu.data import lm_dataset  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=float, default=120.0)
    ap.add_argument("--corpus", default="/tmp/saturn_wikitext_scale.txt")
    ap.add_argument("--max-vocab", type=int, default=50304)
    ap.add_argument("--skip-python", action="store_true",
                    help="only time the native path")
    args = ap.parse_args()

    info = generate_corpus(args.corpus, args.size_mb)
    size = os.path.getsize(args.corpus)
    mb = size / 1e6
    print(f"corpus: {args.corpus} ({mb:.1f} MB, gen info {info})",
          file=sys.stderr)

    cache_dir = "/tmp/saturn_tok_bench_cache"
    shutil.rmtree(cache_dir, ignore_errors=True)  # cold: time the real work

    from saturn_tpu import native

    has_native = native.load("tokenize") is not None
    t0 = time.perf_counter()
    ids_native, vocab_native = lm_dataset.word_tokenize_file(
        args.corpus, max_vocab=args.max_vocab, cache_dir=cache_dir
    )
    t_native = time.perf_counter() - t0

    # cache hit must be near-free (the .npz is the product the trainer loads)
    t0 = time.perf_counter()
    ids2, _ = lm_dataset.word_tokenize_file(
        args.corpus, max_vocab=args.max_vocab, cache_dir=cache_dir
    )
    t_cache = time.perf_counter() - t0
    assert len(ids2) == len(ids_native)

    out = {
        "metric": "wikitext_scale_tokenizer",
        "corpus_mb": round(mb, 1),
        "n_tokens": int(len(ids_native)),
        "vocab_size": int(vocab_native),
        "native_used": bool(has_native),
        "native_s": round(t_native, 2),
        "native_mb_s": round(mb / t_native, 1),
        "cache_hit_s": round(t_cache, 3),
    }

    if not args.skip_python:
        with open(args.corpus, "rb") as f:
            data = f.read()
        t0 = time.perf_counter()
        ids_py, vocab_py = lm_dataset._word_tokenize_python(
            data, args.max_vocab
        )
        t_py = time.perf_counter() - t0
        assert vocab_py == vocab_native, (vocab_py, vocab_native)
        assert np.array_equal(ids_py, ids_native), \
            "native and Python id streams diverge — cache poisoning hazard"
        out["python_s"] = round(t_py, 2)
        out["python_mb_s"] = round(mb / t_py, 1)
        out["speedup"] = round(t_py / t_native, 2)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
