"""Pipeline schedule overhead benchmark (VERDICT r1 item 8).

Times one pipelined train step against the dp baseline on the same device
count, at a medium-model scale where the embedding table and vocab head are
big enough to expose schedule overheads. Runs on whatever backend is up
(8-virtual-CPU mesh in CI; the real chip when the tunnel is alive).

Run: ``python benchmarks/pipeline_step.py [--preset gpt2-medium] [--seq 512]``
"""

from __future__ import annotations

import argparse
import os


def main():
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2-medium")
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--cpu", action="store_true", help="force the CPU backend")
    args = ap.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss
    from saturn_tpu.parallel.dp import DataParallel
    from saturn_tpu.parallel.pp import Pipeline
    from saturn_tpu.utils.timing import time_train_step

    devices = jax.devices()
    n = 1 << (len(devices).bit_length() - 1)
    devices = devices[:n]
    print(f"backend={devices[0].platform} devices={n} preset={args.preset} "
          f"seq={args.seq} batch={args.batch}")

    task = Task(
        get_model=lambda **kw: build_gpt2(args.preset, seq_len=args.seq, **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=args.seq, batch_size=args.batch,
            n_tokens=args.seq * args.batch * 4,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=4),
        save_dir="/tmp/pp_bench_ckpts",
    )

    results = {}
    configs = [("dp", DataParallel(), {"remat": False})]
    if n >= 2:
        configs += [
            ("pp s2", Pipeline(), {"stages": 2, "microbatches": 4, "remat": False}),
        ]
    if n >= 4:
        configs += [
            ("pp s4", Pipeline(), {"stages": 4, "microbatches": 8, "remat": False}),
        ]
    for label, tech, cfg in configs:
        bundle = tech.build(task, devices, cfg)
        state = bundle.init()
        batch = jax.device_put(task.get_dataset().batch(0), bundle.batch_sharding)
        dt = time_train_step(bundle.compiled, state, batch, n_timed=5, n_warmup=2)
        tput = args.batch * args.seq / dt
        results[label] = dt
        print(f"{label:8s} {dt*1e3:9.1f} ms/step  {tput:10.0f} tok/s  cfg={cfg}")

    if "dp" in results:
        for k, v in results.items():
            if k != "dp":
                print(f"{k} vs dp: {results['dp']/v:.2f}x")


if __name__ == "__main__":
    main()
