"""Online service under Poisson + diurnal-burst arrival traces.

Two experiments, toward ROADMAP item 2 ("service scale-out to real
traffic"):

1. **Cache phases (in-process)** — a seeded Poisson stream of jobs to a
   running ``SaturnService``, twice against the same persistent profile
   cache: **cold** (every arrival pays its profiling sweep) vs **warm**
   (cache lookup, zero trials). Emits the ``online_admission_latency`` row.

2. **Gateway phase (over the wire)** — hundreds of jobs driven through the
   network gateway under a Poisson base rate modulated by diurnal bursts
   (periodic windows at a multiplied rate, the arrival shape a serving
   front door actually sees). The gateway's inflight window is deliberately
   small, so bursts overrun it and the shed path is exercised for real.
   Reports client-observed admission latency p50/p99 and the shed rate.

Prints one JSON line per experiment (the gateway row last — it is the
headline); the gateway row self-validates against
``bench_guard.ONLINE_ROW_REQUIRED`` before printing:

    {"metric": "online_admission_latency", "cold_s": ..., "warm_s": ...}
    {"metric": "online_arrivals", "n_jobs": ..., "admission_p50_s": ...,
     "admission_p99_s": ..., "shed_rate": ..., "status": "ok", ...}

Run: ``python benchmarks/online_arrivals.py`` (``--gateway-only`` skips the
cache phases).
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

from saturn_tpu import library as lib
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.service import (
    GatewayClient,
    GatewayError,
    GatewayServer,
    SaturnService,
    ServiceClient,
)
from saturn_tpu.service.gateway import protocol
from saturn_tpu.twin.arrivals import BURST_EVERY, BURST_LEN, arrival_stream
from saturn_tpu.utils.metrics import read_events

N_JOBS = 6
ARRIVAL_RATE_HZ = 5.0     # mean inter-arrival 200 ms
TRIAL_COST_S = 0.02       # stand-in for compile time per profiling trial
PER_BATCH_S = 0.004
SEED = 7

# Gateway-phase traffic shape: a Poisson base rate with periodic diurnal
# bursts (every cycle, a burst window arrives at burst_rate instead). The
# burst cycle constants (BURST_EVERY/BURST_LEN) and the generator itself
# live in saturn_tpu.twin.arrivals — one seeded stream shared with the twin
# simulator, so bench and twin traces can't drift. The inflight window is
# sized so bursts overrun it — shed behavior is the point, not an accident.
N_ONLINE = 200
BASE_RATE_HZ = 12.0
BURST_RATE_HZ = 80.0
GATEWAY_WINDOW = 12       # gateway max_inflight (solver size stays bounded)
ONLINE_BATCHES = 2        # tiny jobs: the wire, not the mesh, is measured


class FakeDev:
    pass


class BenchTech(BaseTechnique):
    """Profiles with a fixed sleep (the 'compile'), executes by sleeping."""

    name = "bench-online"

    def execute(self, task, devices, tid, override_batch_count=None):
        time.sleep(PER_BATCH_S * (override_batch_count or 1))

    def search(self, task, devices, tid):
        time.sleep(TRIAL_COST_S)
        return {}, PER_BATCH_S


class FakeTask:
    """Duck-typed Task: profilable (no pre-filled strategies), cacheable
    (stable degraded fingerprint + a distinguishing ``family`` hint)."""

    def __init__(self, name, family, total_batches=40):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = 1000
        self.hints = {"family": family}
        self.chip_range = None
        self.strategies = {}
        self.selected_strategy = None

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length


def run_phase(phase: str, cache_dir: str, topo: SliceTopology) -> dict:
    rng = random.Random(SEED)  # same trace both phases
    mpath = tempfile.mktemp(suffix=".jsonl")
    svc = SaturnService(
        topology=topo, interval=0.2, metrics_path=mpath,
        technique_names=["bench-online"], profile_cache=cache_dir,
        poll_s=0.02,
    ).start()
    client = ServiceClient(svc)
    try:
        t0 = time.monotonic()
        ids = []
        for i in range(N_JOBS):
            time.sleep(rng.expovariate(ARRIVAL_RATE_HZ))
            ids.append(client.submit(
                FakeTask(f"{phase}-job{i}", family=i),
                priority=float(rng.randint(0, 2)),
            ))
        for jid in ids:
            out = client.wait(jid, timeout=120)
            if out["state"] != "DONE":
                raise SystemExit(f"benchmark job did not finish: {out}")
        makespan = time.monotonic() - t0
        svc.stop(timeout=30)
        admits = [e for e in read_events(mpath, kind="job_admitted")
                  if e["decision"] == "admit"]
        if len(admits) != N_JOBS:
            raise SystemExit(f"expected {N_JOBS} admissions, got {admits}")
        return {
            "mean_admission_s": sum(e["latency_s"] for e in admits) / len(admits),
            "trials": sum(e["trials_run"] for e in admits),
            "makespan_s": makespan,
        }
    finally:
        if os.path.exists(mpath):
            os.unlink(mpath)


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _online_provider(tech):
    """Gateway task rebuild: payload -> pre-profiled task (strategies filled,
    so admission is the wire + queue, not a profiling sweep)."""

    def provide(payload):
        t = FakeTask(payload["task"], family=0,
                     total_batches=payload["remaining_batches"])
        sizes = (payload.get("spec") or {}).get("sizes", [4, 8])
        t.strategies = {
            g: Strategy(tech, g, {}, PER_BATCH_S * t.total_batches,
                        PER_BATCH_S)
            for g in sizes
        }
        return t

    return provide


def run_gateway_phase(topo: SliceTopology, *,
                      n_jobs: int = N_ONLINE,
                      window: int = GATEWAY_WINDOW,
                      base_rate_hz: float = BASE_RATE_HZ,
                      burst_rate_hz: float = BURST_RATE_HZ,
                      interval: float = 0.2,
                      batches: int = ONLINE_BATCHES,
                      metrics_path: str = None,
                      drain: bool = True,
                      settle_s: float = 0.0,
                      session_window: int = 16,
                      seed: int = SEED,
                      durability_dir: str = None) -> dict:
    """Drive ``n_jobs`` jobs through the gateway under Poisson + bursts.

    Clients run with ``max_attempts=1`` on purpose: a shed is *counted*, not
    retried away — the row measures what the front door refused, and retry
    loops would hide exactly the behavior under test.

    ``benchmarks/solver_scaling.py`` reuses this with the solver-depth
    shape: ``window=n_jobs`` (nothing shed — queue depth is the point),
    long ``batches`` so arrivals outlive the run, ``drain=False`` (reach
    full depth and measure re-solves, don't wait out a multi-hour
    makespan), and ``metrics_path`` to capture the ``solver_tier`` events.

    ``durability_dir`` turns on the service's write-ahead journal: the run
    leaves a replayable trace behind, which is how the twin's fidelity
    check gets its ground truth (``saturn_tpu.twin.trace.load_trace``).
    """
    tech = BenchTech()
    svc = SaturnService(
        topology=topo, interval=interval, poll_s=0.02,
        task_provider=_online_provider(tech), health_guardian=False,
        metrics_path=metrics_path, durability_dir=durability_dir,
    ).start()
    gw = GatewayServer(svc, max_inflight=window,
                       max_inflight_per_session=session_window)
    gw.start()
    trace = arrival_stream(n_jobs, base_rate_hz=base_rate_hz,
                           burst_rate_hz=burst_rate_hz, seed=seed)
    latencies, accepted, shed = [], [], 0
    t0 = time.monotonic()
    try:
        with GatewayClient(*gw.address, session="bench-online",
                           seed=seed, timeout_s=30.0,
                           max_attempts=1) as client:
            for arr in trace:
                i = arr.index
                time.sleep(arr.gap_s)
                t_submit = time.monotonic()
                try:
                    jid = client.submit(
                        name=f"online-{i}", total_batches=batches,
                        priority=arr.priority,
                        spec={"sizes": [4, 8]},
                    )
                except GatewayError as e:
                    if e.code not in (protocol.GW_RETRY_AFTER,
                                      protocol.GW_UNAVAILABLE):
                        raise
                    shed += 1
                    continue
                latencies.append(time.monotonic() - t_submit)
                accepted.append(jid)
            if drain:
                for jid in accepted:
                    out = client.wait(jid, timeout=300)
                    if out["state"] != "DONE":
                        raise SystemExit(f"gateway bench job not DONE: {out}")
            elif settle_s > 0:
                time.sleep(settle_s)  # a few more re-solves at full depth
        makespan = time.monotonic() - t0
    finally:
        gw.shutdown(timeout=10, reason="bench-complete")
        # No-drain runs leave thousands of long jobs live on purpose —
        # draining them would wait out the plan's full makespan.
        svc.stop(abort=not drain, timeout=60)
    latencies.sort()
    return {
        "metric": "online_arrivals",
        "n_jobs": n_jobs,
        "accepted": len(accepted),
        "shed": shed,
        "shed_rate": round(shed / n_jobs, 4),
        "admission_p50_s": round(_percentile(latencies, 0.50), 6),
        "admission_p99_s": round(_percentile(latencies, 0.99), 6),
        "makespan_s": round(makespan, 3),
        "base_rate_hz": base_rate_hz,
        "burst_rate_hz": burst_rate_hz,
        "gateway_window": window,
        "seed": seed,
        "status": "ok",
    }


def main() -> None:
    gateway_only = "--gateway-only" in sys.argv[1:]
    lib.register("bench-online", BenchTech)
    topo = SliceTopology([FakeDev() for _ in range(8)])

    if not gateway_only:
        cache_dir = tempfile.mkdtemp(prefix="saturn_bench_pcache_")
        try:
            cold = run_phase("cold", cache_dir, topo)
            warm = run_phase("warm", cache_dir, topo)
        finally:
            shutil.rmtree(cache_dir, ignore_errors=True)

        print(json.dumps({
            "metric": "online_admission_latency",
            "cold_s": round(cold["mean_admission_s"], 6),
            "warm_s": round(warm["mean_admission_s"], 6),
            "speedup": round(
                cold["mean_admission_s"] / max(warm["mean_admission_s"], 1e-9),
                2,
            ),
            "cold_trials": cold["trials"],
            "warm_trials": warm["trials"],
            "makespan_cold_s": round(cold["makespan_s"], 6),
            "makespan_warm_s": round(warm["makespan_s"], 6),
            "n_jobs": N_JOBS,
            "unit": "s",
        }))

    row = run_gateway_phase(topo)
    import bench_guard
    problems = bench_guard.validate_online_row(row)
    if problems:
        raise SystemExit(f"online row failed self-validation: {problems}")
    print(json.dumps(row))


if __name__ == "__main__":
    main()
