"""Online service under a Poisson arrival trace: admission latency + makespan.

Submits a seeded Poisson stream of jobs (exponential inter-arrival times,
mixed priorities) to a running ``SaturnService`` on the 8 virtual CPU
devices, twice against the same persistent profile cache directory:

- **cold**: empty cache — every arrival pays its profiling sweep (the fake
  technique sleeps per trial to stand in for XLA compile time),
- **warm**: same task fingerprints again — every arrival resolves from the
  cache with zero trials, so admission latency collapses to the lookup.

Prints ONE JSON line like ``bench.py``:

    {"metric": "online_admission_latency", "cold_s": ..., "warm_s": ...,
     "speedup": ..., "makespan_cold_s": ..., "makespan_warm_s": ...,
     "warm_trials": 0, "n_jobs": ...}

Run: ``python benchmarks/online_arrivals.py``.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

from saturn_tpu import library as lib
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.service import SaturnService, ServiceClient
from saturn_tpu.utils.metrics import read_events

N_JOBS = 6
ARRIVAL_RATE_HZ = 5.0     # mean inter-arrival 200 ms
TRIAL_COST_S = 0.02       # stand-in for compile time per profiling trial
PER_BATCH_S = 0.004
SEED = 7


class FakeDev:
    pass


class BenchTech(BaseTechnique):
    """Profiles with a fixed sleep (the 'compile'), executes by sleeping."""

    name = "bench-online"

    def execute(self, task, devices, tid, override_batch_count=None):
        time.sleep(PER_BATCH_S * (override_batch_count or 1))

    def search(self, task, devices, tid):
        time.sleep(TRIAL_COST_S)
        return {}, PER_BATCH_S


class FakeTask:
    """Duck-typed Task: profilable (no pre-filled strategies), cacheable
    (stable degraded fingerprint + a distinguishing ``family`` hint)."""

    def __init__(self, name, family, total_batches=40):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = 1000
        self.hints = {"family": family}
        self.chip_range = None
        self.strategies = {}
        self.selected_strategy = None

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length


def run_phase(phase: str, cache_dir: str, topo: SliceTopology) -> dict:
    rng = random.Random(SEED)  # same trace both phases
    mpath = tempfile.mktemp(suffix=".jsonl")
    svc = SaturnService(
        topology=topo, interval=0.2, metrics_path=mpath,
        technique_names=["bench-online"], profile_cache=cache_dir,
        poll_s=0.02,
    ).start()
    client = ServiceClient(svc)
    try:
        t0 = time.monotonic()
        ids = []
        for i in range(N_JOBS):
            time.sleep(rng.expovariate(ARRIVAL_RATE_HZ))
            ids.append(client.submit(
                FakeTask(f"{phase}-job{i}", family=i),
                priority=float(rng.randint(0, 2)),
            ))
        for jid in ids:
            out = client.wait(jid, timeout=120)
            if out["state"] != "DONE":
                raise SystemExit(f"benchmark job did not finish: {out}")
        makespan = time.monotonic() - t0
        svc.stop(timeout=30)
        admits = [e for e in read_events(mpath, kind="job_admitted")
                  if e["decision"] == "admit"]
        if len(admits) != N_JOBS:
            raise SystemExit(f"expected {N_JOBS} admissions, got {admits}")
        return {
            "mean_admission_s": sum(e["latency_s"] for e in admits) / len(admits),
            "trials": sum(e["trials_run"] for e in admits),
            "makespan_s": makespan,
        }
    finally:
        if os.path.exists(mpath):
            os.unlink(mpath)


def main() -> None:
    lib.register("bench-online", BenchTech)
    topo = SliceTopology([FakeDev() for _ in range(8)])
    cache_dir = tempfile.mkdtemp(prefix="saturn_bench_pcache_")
    try:
        cold = run_phase("cold", cache_dir, topo)
        warm = run_phase("warm", cache_dir, topo)
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)

    print(json.dumps({
        "metric": "online_admission_latency",
        "cold_s": round(cold["mean_admission_s"], 6),
        "warm_s": round(warm["mean_admission_s"], 6),
        "speedup": round(
            cold["mean_admission_s"] / max(warm["mean_admission_s"], 1e-9), 2
        ),
        "cold_trials": cold["trials"],
        "warm_trials": warm["trials"],
        "makespan_cold_s": round(cold["makespan_s"], 6),
        "makespan_warm_s": round(warm["makespan_s"], 6),
        "n_jobs": N_JOBS,
        "unit": "s",
    }))


if __name__ == "__main__":
    main()
