"""Cross-job co-scheduling microbenchmark: sequential vs interleaved pair.

Round 11's tentpole claim, measured end-to-end through the engine: an
offload-style job (host-staging-bound — its per-batch cost is dominated by a
GIL-releasing host wait, emulating pinned-host transfers / PCIe staging) and
a compute-bound neighbor share ONE device block. Sequentially (the
pre-round-11 plan: same block, ordering edge) the pair takes
``t_host + t_compute``; co-scheduled (same block, co-schedule edge) the
group launcher interleaves their windows so the neighbor's device compute
fills the offload job's staging bubbles and the pair takes
``~max(t_host, t_compute)``.

Prints ONE JSON line like ``bench.py``:

    {"metric": "coschedule_pair_tokens_per_sec", "value": <interleaved>,
     "workload": "coschedule_pair", "sequential_tokens_per_sec": ...,
     "pair_speedup": ..., ...}

``workload`` makes the row shape-distinct for ``bench_guard.py``: a
coschedule record never gates a ``bench.py`` record or vice versa.

Hardware-free by construction (CPU forced before jax imports) and sized for
a ONE-core CI host: the win comes from overlapping a ``time.sleep`` staging
phase (which releases the GIL) with the neighbor's XLA compute, not from
parallel cores — the same overlap a real TPU host gets between PCIe staging
and device windows. Run: ``python benchmarks/coschedule.py``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import timeit

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

from saturn_tpu import HParams, Task
from saturn_tpu.core.mesh import Block, SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.data.lm_dataset import make_lm_dataset
from saturn_tpu.executor import engine
from saturn_tpu.models.gpt2 import build_gpt2
from saturn_tpu.models.loss import pretraining_loss
from saturn_tpu.parallel.dp import DataParallel
from saturn_tpu.solver.milp import Assignment, Plan

SEQ_LEN = 16
BATCH_SIZE = 1
N_COMPUTE = 48          # compute-bound job's batches per arm
N_OFFLOAD = 12          # offload job: few batches, each staging-dominated
STAGE_DELAY_S = 0.1     # offload job's per-batch host wait (releases GIL)
WINDOW = 8


class StagedDataset:
    """Wraps a dataset with a per-batch host wait: the offload job's
    pinned-host staging phase. ``time.sleep`` releases the GIL, so a
    co-scheduled neighbor's XLA compute can run under it on one core."""

    def __init__(self, inner, delay_s: float):
        self._inner = inner
        self._delay = delay_s
        self.batch_size = inner.batch_size

    def __len__(self):
        return len(self._inner)

    def example_batch(self):
        return self._inner.example_batch()

    def batch(self, i):
        time.sleep(self._delay)
        return self._inner.batch(i)


def make_task(save_dir: str, name: str, batch_count: int,
              stage_delay_s: float = 0.0) -> Task:
    def loader():
        ds = make_lm_dataset(
            context_length=SEQ_LEN, batch_size=BATCH_SIZE, vocab_size=256,
            n_tokens=SEQ_LEN * BATCH_SIZE * 32,
        )
        return StagedDataset(ds, stage_delay_s) if stage_delay_s else ds

    return Task(
        get_model=lambda **kw: build_gpt2("test-tiny", seq_len=SEQ_LEN, **kw),
        get_dataloader=loader,
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=batch_count),
        chip_range=[1],
        name=name,
        save_dir=save_dir,
    )


def make_pair(tmp: str, tag: str):
    offload = make_task(
        os.path.join(tmp, tag, "offload"), "co-offload", N_OFFLOAD,
        stage_delay_s=STAGE_DELAY_S,
    )
    compute = make_task(
        os.path.join(tmp, tag, "compute"), "co-compute", N_COMPUTE
    )
    for t in (offload, compute):
        t.strategies = {
            1: Strategy(executor=DataParallel(), apportionment=1, params={},
                        runtime=1.0, per_batch_time=0.01)
        }
    return offload, compute


def run_arm(tmp: str, tag: str, coscheduled: bool) -> float:
    """Wall time for the pair under one plan shape (fresh tasks each arm)."""
    offload, compute = make_pair(tmp, tag)
    if coscheduled:
        deps = {"co-offload": [], "co-compute": []}
        groups = [["co-offload", "co-compute"]]
    else:
        # the pre-round-11 plan for a shared block: an ordering edge
        deps = {"co-offload": [], "co-compute": ["co-offload"]}
        groups = []
    plan = Plan(
        assignments={
            "co-offload": Assignment(1, Block(0, 1), 0.0, 1.0),
            "co-compute": Assignment(1, Block(0, 1), 0.0 if coscheduled else 1.0, 1.0),
        },
        makespan=2.0,
        dependencies=deps,
        coschedule=groups,
    )
    topo = SliceTopology(jax.devices())
    batches = {"co-offload": N_OFFLOAD, "co-compute": N_COMPUTE}
    # warm both programs outside the timed region (compile tax is not the
    # thing under test; execute() AOT-compiles, but arm 1 would otherwise
    # pay it while arm 2 reuses nothing — separate technique instances)
    for t in (offload, compute):
        tech = t.strategies[1].executor
        bundle = tech.build(t, topo.block_devices(Block(0, 1)), {})
        bundle.fused_compiled(WINDOW)
        _ = bundle.compiled
    t0 = timeit.default_timer()
    errors = engine.execute(
        [offload, compute], batches, 100.0, plan, topo,
    )
    dt = timeit.default_timer() - t0
    if errors:
        raise RuntimeError(f"benchmark interval failed: {errors}")
    return dt


def main() -> None:
    os.environ.setdefault("SATURN_TPU_MAX_WINDOW", str(WINDOW))
    with tempfile.TemporaryDirectory() as tmp:
        t_seq = run_arm(tmp, "seq", coscheduled=False)
        t_int = run_arm(tmp, "int", coscheduled=True)
    total_tokens = (N_OFFLOAD + N_COMPUTE) * BATCH_SIZE * SEQ_LEN
    out = {
        "metric": "coschedule_pair_tokens_per_sec",
        "value": round(total_tokens / t_int, 1),
        "workload": "coschedule_pair",
        "platform": jax.devices()[0].platform,
        "batch_size": BATCH_SIZE,
        "seq_len": SEQ_LEN,
        "n_batches": {"offload": N_OFFLOAD, "compute": N_COMPUTE},
        "stage_delay_s": STAGE_DELAY_S,
        "window": WINDOW,
        "sequential_tokens_per_sec": round(total_tokens / t_seq, 1),
        "sequential_s": round(t_seq, 3),
        "interleaved_s": round(t_int, 3),
        "pair_speedup": round(t_seq / t_int, 3),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
