"""Crash-recovery latency vs journal length.

Measures the restart-critical path of the durability layer
(``saturn_tpu.durability``) as the write-ahead journal grows:

- **recover**: scan every segment, CRC + sequence-verify each record, and
  quarantine the torn tail (one is planted per run — the realistic restart
  has a crashed writer's partial append at the end);
- **replay**: fold the verified records into the service's recovery state
  (job registry + realized-iteration ledger + last committed plan).

Journals are synthesized with the real ``Journal`` writer (same segment
rotation, same group-commit batching) over a representative record mix:
submissions, lifecycle edges, per-interval task_progress and plan commits
for a rotating population of jobs.

Prints ONE JSON line like ``bench.py``:

    {"metric": "crash_recovery_latency", "points": [
        {"records": 1000, "segments": ..., "recover_s": ..., "replay_s": ...,
         "total_s": ...}, ...],
     "throughput_rec_per_s": ..., "unit": "s"}

Run: ``python benchmarks/crash_recovery.py``.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import timeit

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["JAX_PLATFORMS"] = "cpu"

from saturn_tpu.durability import Journal, recover, replay_service_state

JOURNAL_LENGTHS = (1_000, 10_000, 50_000)
JOBS = 16               # rotating live-job population
COMMIT_EVERY = 32       # records per group commit (an interval's batch)
SEGMENT_MAX = 512 * 1024


def synthesize(root: str, n_records: int) -> None:
    """Write ~n_records of a realistic service-journal mix, ending in a
    torn trailing record (the crashed writer's un-fsync'd append)."""
    j = Journal(root, segment_max_bytes=SEGMENT_MAX, sync=False)
    for i in range(JOBS):
        j.append("job_submitted", job=f"j{i + 1:04d}-model-{i}",
                 task=f"model-{i}", priority=float(i % 3),
                 max_retries=1, total_batches=10_000,
                 spec={"sizes": [2, 4]})
    written = JOBS
    interval = 0
    while written < n_records:
        for i in range(JOBS):
            if written >= n_records:
                break
            j.append("task_progress", task=f"model-{i}",
                     job=f"j{i + 1:04d}-model-{i}", batches=40)
            written += 1
            if written % COMMIT_EVERY == 0:
                j.commit()
        j.append("plan_commit", interval=interval, makespan=123.4,
                 plan={"assignments": {f"model-{i}": {"start": 0.0,
                                                      "apportionment": 4,
                                                      "block": i % 2}
                                       for i in range(JOBS)}})
        written += 1
        interval += 1
    j.close()
    # plant the torn tail recovery always faces after a real crash
    segs = sorted(n for n in os.listdir(root) if n.endswith(".jsonl"))
    with open(os.path.join(root, segs[-1]), "ab") as f:
        f.write(b'{"crc":"00000000","data":{"task":"model-0","ba')


def bench_one(n_records: int) -> dict:
    root = tempfile.mkdtemp(prefix="saturn_bench_wal_")
    try:
        synthesize(root, n_records)
        t0 = timeit.default_timer()
        report = recover(root)
        t1 = timeit.default_timer()
        state = replay_service_state(root)
        t2 = timeit.default_timer()
        if not report["quarantined"]:
            raise SystemExit("planted torn tail was not quarantined")
        if len(state.jobs) != JOBS:
            raise SystemExit(
                f"replay folded {len(state.jobs)} jobs, expected {JOBS}"
            )
        return {
            "records": report["records"],
            "segments": report["segments"],
            "recover_s": round(t1 - t0, 6),
            "replay_s": round(t2 - t1, 6),
            "total_s": round(t2 - t0, 6),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def main() -> None:
    points = [bench_one(n) for n in JOURNAL_LENGTHS]
    biggest = points[-1]
    print(json.dumps({
        "metric": "crash_recovery_latency",
        "points": points,
        "throughput_rec_per_s": round(
            biggest["records"] / max(biggest["total_s"], 1e-9)
        ),
        "unit": "s",
    }))


if __name__ == "__main__":
    main()
