"""Elastic recovery latency: detect -> replan -> resume, hardware-free.

Runs a 3-task batch on the 8 virtual CPU devices, injects a 4-device slice
preemption mid-interval, and measures how long the fleet takes to get work
running again on the surviving mesh:

- **detect**: the ``topology_change`` event (the orchestrator's pre-interval
  poll observing the loss),
- **replan**: the ``recovery`` event's ``replan_latency_s`` (topology diff +
  strategy synthesis + solver re-run),
- **resume**: the first technique launch after recovery.

Prints ONE JSON line like ``bench.py``:

    {"metric": "elastic_recovery_latency", "value": <detect->resume seconds>,
     "unit": "s", "replan_s": ..., "policy": "pause-resolve-resume", ...}

Run: ``python benchmarks/elastic_recovery.py``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.executor import orchestrate
from saturn_tpu.resilience import (
    FaultEvent,
    FaultInjector,
    FaultKind,
    FleetHealthMonitor,
)
from saturn_tpu.utils.metrics import read_events


class FakeDev:
    pass


class TimestampingTech(BaseTechnique):
    """Sleeps per batch, records the wall-clock time of every launch."""

    name = "bench-fake"

    def __init__(self, per_batch=0.005):
        self.per_batch = per_batch
        self.launches = []
        self.lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        with self.lock:
            self.launches.append(time.time())
        time.sleep(self.per_batch * (override_batch_count or 1))

    def search(self, task, devices, tid):
        return {}, self.per_batch


class FakeTask:
    def __init__(self, name, total_batches, sizes, tech, pbt):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = 1000
        self.hints = {}
        self.strategies = {
            g: Strategy(tech, g, {}, pbt * total_batches, pbt) for g in sizes
        }
        self.selected_strategy = None

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length


def main() -> None:
    policy = os.environ.get("SATURN_TPU_RECOVERY_POLICY", "pause-resolve-resume")
    topo = SliceTopology([FakeDev() for _ in range(8)])
    monitor = FleetHealthMonitor.for_topology(topo)
    tech = TimestampingTech(per_batch=0.005)
    tasks = [FakeTask(f"job{i}", 80, [2, 4], tech, pbt=0.005) for i in range(3)]
    injector = FaultInjector(schedule=[
        FaultEvent(1, FaultKind.SLICE_PREEMPTION, devices=(4, 5, 6, 7),
                   after_s=0.05),
    ])
    mpath = tempfile.mktemp(suffix=".jsonl")
    try:
        out = orchestrate(
            tasks, interval=0.2, topology=topo, fault_injector=injector,
            health_monitor=monitor, failure_policy="retry",
            recovery_policy=policy, metrics_path=mpath,
        )
        if sorted(out["completed"]) != ["job0", "job1", "job2"]:
            raise SystemExit(f"benchmark run lost work: {out}")
        detect_ts = read_events(mpath, kind="topology_change")[0]["ts"]
        recovery = read_events(mpath, kind="recovery")[0]
        with tech.lock:
            resume_ts = min(t for t in tech.launches if t > recovery["ts"])
    finally:
        if os.path.exists(mpath):
            os.unlink(mpath)

    print(json.dumps({
        "metric": "elastic_recovery_latency",
        "value": round(resume_ts - detect_ts, 6),
        "unit": "s",
        "replan_s": round(recovery["replan_latency_s"], 6),
        "policy": policy,
        "surviving_capacity": recovery["capacity"],
        "n_tasks": recovery["n_tasks"],
    }))


if __name__ == "__main__":
    main()
