"""Anytime solver scaling: deadline-bounded re-solves at 500-10k queued jobs.

Two phases, one row (validated against ``bench_guard.SOLVER_ROW_REQUIRED``):

1. **Depth phase** — jobs stream through the real network gateway into a
   running ``SaturnService`` (``online_arrivals.run_gateway_phase`` with the
   solver-depth shape: ``window = n_jobs`` so nothing is shed — queue depth
   is the point — and ``drain=False``: jobs are long on purpose, the run
   reaches full depth, records a settle window of re-solves, and stops
   without waiting out a multi-hour makespan). Every interval re-solve goes
   through ``solver/anytime.py``; its ``solver_tier`` events give the
   per-tier wall-time distribution and the deadline-miss count (**must be
   zero** — the row fails self-validation otherwise).

2. **Quality phase** — subsampled instances small enough for the exact
   MILP (<= ``QUALITY_INSTANCE_N`` tasks, under ``milp_task_limit``):
   ``anytime_solve`` under the depth phase's deadline vs ``milp.solve``
   with a generous budget. ``quality_delta_pct`` is the mean makespan
   excess; the row schema caps it at 10%.

Run: ``python benchmarks/solver_scaling.py`` (quick mode: 500 jobs,
CPU-safe, < 60 s — the ``solver``-marked smoke test runs this) or
``--full`` for the 5k and 10k sweep the acceptance bar quotes. One JSON
row per scale point.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import bench_guard
import online_arrivals
from online_arrivals import FakeDev, _percentile, run_gateway_phase

from saturn_tpu import library as lib
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.solver import anytime, milp
from saturn_tpu.utils.metrics import read_events

SEED = 11
QUICK_JOBS = 500
FULL_JOBS = (5000, 10000)
INTERVAL_S = 1.0          # service interval; deadline = interval/2 = 0.5 s
DEEP_INTERVAL_S = 2.0     # >5k queued jobs: the interval budget scales with
                          # depth (a 10k-deep queue re-planned every second
                          # buys nothing — jobs run for hours)
ARRIVAL_HZ = 400.0        # jobs arrive far faster than they finish...
LONG_BATCHES = 2000       # ...and are long, so the queue reaches full depth
SETTLE_S = 4.0            # extra intervals of re-solves at full depth
QUALITY_SAMPLES = 6       # subsampled exact-vs-anytime instances
QUALITY_INSTANCE_N = 8    # small enough for the exact MILP to finish
QUALITY_EXACT_S = 5.0     # exact-MILP budget; its incumbent is the reference


class _QTask:
    """Solver-facing duck type for the quality phase (numbers only)."""

    def __init__(self, name, runtimes):
        self.name = name
        self.strategies = {
            g: Strategy(object(), g, {}, rt, 0.1)
            for g, rt in runtimes.items()
        }

    def feasible_strategies(self):
        return self.strategies


def quality_delta_pct(deadline: float, seed: int) -> float:
    """Mean makespan excess of the anytime ladder over the exact MILP on
    random instances the exact solver can actually finish."""
    rng = random.Random(seed)
    topo = SliceTopology([FakeDev() for _ in range(8)])
    deltas = []
    for k in range(QUALITY_SAMPLES):
        tasks = []
        for i in range(QUALITY_INSTANCE_N):
            base = rng.uniform(2.0, 40.0)
            tasks.append(_QTask(f"q{k}-{i}", {
                2: base,
                4: base * rng.uniform(0.55, 0.8),
                8: base * rng.uniform(0.35, 0.6),
            }))
        exact = milp.solve(tasks, topo, time_limit=QUALITY_EXACT_S)
        approx, _ = anytime.anytime_solve(tasks, topo, deadline, seed=seed + k)
        if exact.makespan > 1e-9:
            deltas.append(
                100.0 * (approx.makespan - exact.makespan) / exact.makespan)
    return max(0.0, sum(deltas) / max(len(deltas), 1))


def run_scale_point(n_jobs: int, mode: str) -> dict:
    topo = SliceTopology([FakeDev() for _ in range(8)])
    mpath = tempfile.mktemp(suffix=".jsonl", prefix="solver_scaling_")
    interval = DEEP_INTERVAL_S if n_jobs > 5000 else INTERVAL_S
    try:
        gw_row = run_gateway_phase(
            topo,
            n_jobs=n_jobs,
            window=n_jobs,            # queue depth, not shedding, is measured
            session_window=n_jobs,
            base_rate_hz=ARRIVAL_HZ,
            burst_rate_hz=ARRIVAL_HZ * 1.5,
            interval=interval,
            batches=LONG_BATCHES,
            metrics_path=mpath,
            drain=False,
            settle_s=SETTLE_S,
            seed=SEED,
        )
        events = read_events(mpath, kind="solver_tier")
    finally:
        if os.path.exists(mpath):
            os.unlink(mpath)
    if gw_row["shed"]:
        raise SystemExit(
            f"{gw_row['shed']} job(s) shed with window == n_jobs — the "
            "depth phase lost arrivals and the row would under-measure")
    if not events:
        raise SystemExit("no solver_tier events: the anytime front-end is "
                         "not wired into the service re-solve")
    walls = sorted(float(e["wall_s"]) for e in events)
    deadline = float(events[-1]["deadline_s"])
    misses = sum(1 for e in events
                 if float(e["wall_s"]) > float(e["deadline_s"]))
    tier_counts: dict = {}
    for e in events:
        name = e.get("tier_name", str(e.get("tier")))
        tier_counts[name] = tier_counts.get(name, 0) + 1
    row = {
        "metric": "solver_scaling",
        "mode": mode,
        "n_jobs": n_jobs,
        "deadline_s": round(deadline, 6),
        "resolves": len(events),
        "deadline_misses": misses,
        "tier_counts": tier_counts,
        "solve_p50_s": round(_percentile(walls, 0.50), 6),
        "solve_p99_s": round(_percentile(walls, 0.99), 6),
        "admission_p50_s": gw_row["admission_p50_s"],
        "admission_p99_s": gw_row["admission_p99_s"],
        "quality_delta_pct": round(quality_delta_pct(deadline, SEED), 3),
        "quality_samples": QUALITY_SAMPLES,
        "seed": SEED,
        "status": "ok",
    }
    problems = bench_guard.validate_solver_row(row)
    if problems:
        row["status"] = "invalid"
        print(json.dumps(row))
        raise SystemExit(f"solver row failed self-validation: {problems}")
    return row


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--full", action="store_true",
                    help="run the 5k and 10k sweep (quick: 500 jobs)")
    ap.add_argument("--jobs", type=int, default=0,
                    help="override the scale point (single run)")
    args = ap.parse_args()

    lib.register("bench-online", online_arrivals.BenchTech)
    if args.jobs:
        points, mode = [args.jobs], "custom"
    elif args.full:
        points, mode = list(FULL_JOBS), "full"
    else:
        points, mode = [QUICK_JOBS], "quick"
    for n in points:
        t0 = time.monotonic()
        row = run_scale_point(n, mode)
        row["bench_wall_s"] = round(time.monotonic() - t0, 1)
        print(json.dumps(row))


if __name__ == "__main__":
    main()
