"""Cold-sweep static-prune benchmark: what memlens buys before lowering.

Two tasks on the 8-virtual-device CPU fixture — the tiny GPT-2 and a ~30x
larger variant — swept under a synthetic per-device HBM capacity chosen
(geometric mean of the two memlens-predicted peaks) so the small task fits
and the large one deterministically does not:

- **before**: ``SATURN_TPU_MEMLENS_PRUNE=0`` — the infeasible grid point
  lowers, compiles, and is rejected by XLA memory analysis
  (``_fits_memory``), paying the full compile tax to learn "no";
- **after**: pruning on — the same point is refused statically
  (``trial_pruned`` reason ``memlens_static``) and never lowers.

Each phase sweeps a FRESH profile-cache directory and fresh task objects, so
the delta is pruning, not cache warmth. The row also counts contradictions:
a ``_fits_memory`` compile-time rejection of a grid point whose memlens
prediction sat comfortably under the headroom margin would mean the static
model blessed a point XLA refused — the acceptance bar is zero.

Prints ONE JSON line (schema ``bench_guard.SWEEP_PRUNE_ROW_REQUIRED``; this
script refuses to print a row that fails the validator):

    {"metric": "sweep_static_prune", "pruned_before_lowering": ...,
     "rejected_after_lowering": ..., "saved_s": ..., "contradictions": 0,
     ...}

Run: ``python benchmarks/sweep_static_prune.py``.
"""

from __future__ import annotations

import json
import math
import os
import shutil
import sys
import tempfile
import timeit

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import bench_guard
import saturn_tpu
from saturn_tpu import HParams, Task, library
from saturn_tpu.analysis.memlens import passes as ml_passes
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.data.lm_dataset import make_lm_dataset
from saturn_tpu.models.gpt2 import build_gpt2
from saturn_tpu.models.loss import pretraining_loss
from saturn_tpu.parallel import BUILTIN_TECHNIQUES

SIZE = 4

#: The large task's model overrides: same vocab/seq as test-tiny so the
#: dataloader is shared, ~30x the parameter bytes so its peak clears any
#: capacity the small task fits under with room on both sides.
BIG = dict(d_model=256, n_layers=4)


def make_task(save_dir: str, name: str, big: bool) -> Task:
    overrides = dict(BIG) if big else {}
    return Task(
        get_model=lambda **kw: build_gpt2("test-tiny", **{**overrides, **kw}),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256, n_tokens=64 * 8 * 8
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=8),
        chip_range=[SIZE],
        name=name,
        save_dir=save_dir,
    )


def predicted_peak(task: Task, topo: SliceTopology) -> int:
    """Memlens static peak for the task's dp point (untimed setup phase)."""
    tech = BUILTIN_TECHNIQUES["dp"]()
    devices = topo.blocks(SIZE)[0].devices_of(topo.devices)
    config = tech.candidate_configs(task, SIZE)[0]
    prof = ml_passes.predict_profile(tech, task, devices, config)
    assert prof is not None, f"memlens could not trace {task.name}"
    return prof.peak_bytes


def run_sweep(root: str, topo: SliceTopology, tag: str) -> tuple:
    """One timed sweep over fresh tasks against a fresh cache; returns
    (seconds, metrics JSONL records)."""
    work = os.path.join(root, tag)
    metrics_path = os.path.join(work, "metrics.jsonl")
    tasks = [
        make_task(work, f"{tag}-fits", big=False),
        make_task(work, f"{tag}-oom", big=True),
    ]
    t0 = timeit.default_timer()
    saturn_tpu.search(
        tasks, technique_names=["dp"], topology=topo,
        profile_cache=os.path.join(work, "profiles"),
        metrics_path=metrics_path,
    )
    dt = timeit.default_timer() - t0
    records = []
    with open(metrics_path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return dt, records


def main() -> None:
    library.register_default_library()
    topo = SliceTopology(jax.devices())
    root = tempfile.mkdtemp(prefix="saturn_sweep_prune_")
    try:
        # Untimed: pick the capacity from the two static predictions. The
        # geometric mean sits ~sqrt(30x) from each peak — far outside both
        # the x1.15 prune margin and the x0.92 compile headroom, so the
        # verdicts are insensitive to the static model's calibration ratio.
        p_fits = predicted_peak(make_task(os.path.join(root, "p0"), "p-fits",
                                          big=False), topo)
        p_oom = predicted_peak(make_task(os.path.join(root, "p1"), "p-oom",
                                         big=True), topo)
        capacity = int(math.sqrt(float(p_fits) * float(p_oom)))
        os.environ[ml_passes.ENV_CAPACITY] = str(capacity)

        os.environ["SATURN_TPU_MEMLENS_PRUNE"] = "0"
        before_s, before_ev = run_sweep(root, topo, "before")
        os.environ["SATURN_TPU_MEMLENS_PRUNE"] = "1"
        after_s, after_ev = run_sweep(root, topo, "after")
    finally:
        os.environ.pop(ml_passes.ENV_CAPACITY, None)
        os.environ.pop("SATURN_TPU_MEMLENS_PRUNE", None)
        shutil.rmtree(root, ignore_errors=True)

    rejected = sum(
        1 for r in before_ev
        if r.get("kind") == "trial" and r.get("memory_infeasible")
    )
    pruned = sum(
        1 for r in after_ev
        if r.get("kind") == "trial_pruned" and r.get("reason") == "memlens_static"
    )
    # A compile-time memory rejection of a point memlens placed comfortably
    # under the headroom margin contradicts the static verdict. The -oom
    # rejections in the before phase are the measured waste, not
    # contradictions: memlens predicted those OOM too.
    peak_of = {"fits": p_fits, "oom": p_oom}
    contradictions = sum(
        1 for r in before_ev + after_ev
        if r.get("kind") == "trial" and r.get("memory_infeasible")
        and peak_of[str(r.get("task", "")).rsplit("-", 1)[-1]]
        <= ml_passes.HEADROOM_MARGIN * capacity
    )

    row = {
        "metric": "sweep_static_prune",
        "grid_points": 2,
        "pruned_before_lowering": pruned,
        "rejected_after_lowering": rejected,
        "contradictions": contradictions,
        "before_s": round(before_s, 3),
        "after_s": round(after_s, 3),
        "saved_s": round(before_s - after_s, 3),
        "capacity_bytes": capacity,
        "status": "ok",
    }
    problems = bench_guard.validate_sweep_prune_row(row)
    if problems:
        print(json.dumps({"metric": "sweep_static_prune", "status": "invalid",
                          "problems": problems, "row": row}))
        sys.exit(1)
    print(json.dumps(row))


if __name__ == "__main__":
    main()
