"""Async step pipeline microbenchmark: per-step vs fused vs fused+prefetch.

Measures the three dispatch shapes of ``SPMDTechnique.execute`` on a CPU
fixture (tiny GPT-2, single device, dp):

- ``per_step``: the pre-round-10 hot loop — synchronous host staging
  (numpy slice + device_put) alternating with one jitted step per batch;
- ``fused``: K-step ``lax.scan`` windows (one dispatch + one loss readback
  per window), staging still synchronous;
- ``fused_prefetch``: fused windows with staging moved to the
  ``DevicePrefetcher`` background thread — what execute() now runs.

Prints ONE JSON line like ``bench.py``:

    {"metric": "step_pipeline_tokens_per_sec", "value": <fused_prefetch>,
     "per_step": ..., "fused": ..., "speedup_vs_per_step": ..., ...}

Hardware-free by construction (CPU forced before jax imports). The shape is
deliberately small (batch 1 x seq 16, single device, K=16): the CI host has
ONE core, so there is no second core for the prefetch thread to overlap on
and the measurable win is dispatch amortization — which scales with the
dispatch:compute ratio, hence a small step. On real TPUs both terms grow:
per-step dispatch is host Python against microsecond device steps, and the
prefetch overlap hides real PCIe transfer time. Run:
``python benchmarks/step_pipeline.py``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import timeit

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from saturn_tpu import HParams, Task
from saturn_tpu.data.lm_dataset import make_lm_dataset
from saturn_tpu.data.prefetch import DevicePrefetcher
from saturn_tpu.models.gpt2 import build_gpt2
from saturn_tpu.models.loss import pretraining_loss
from saturn_tpu.parallel.dp import DataParallel

N_BATCHES = 256
WINDOW = 16
BATCH_SIZE = 1
SEQ_LEN = 16


def make_task(save_dir: str) -> Task:
    return Task(
        get_model=lambda **kw: build_gpt2("test-tiny", seq_len=SEQ_LEN, **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=SEQ_LEN, batch_size=BATCH_SIZE, vocab_size=256,
            n_tokens=SEQ_LEN * BATCH_SIZE * 32,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=N_BATCHES),
        chip_range=[1],
        name="pipeline-bench",
        save_dir=save_dir,
    )


def run_per_step(bundle, ds, n: int) -> float:
    state = bundle.init()
    loss = None
    t0 = timeit.default_timer()
    for i in range(n):
        batch = jax.device_put(ds.batch(i), bundle.batch_sharding)
        state, loss = bundle.compiled(state, batch)
    float(np.asarray(jax.device_get(loss)))
    return timeit.default_timer() - t0


def run_fused(bundle, ds, n: int, k: int, prefetch: bool) -> float:
    fused = bundle.fused_compiled(k)
    sharding = bundle.stacked_sharding()
    n_windows = n // k

    def stage(w: int) -> object:
        host = np.stack([np.asarray(ds.batch(w * k + j)) for j in range(k)])
        return jax.device_put(host, sharding)

    state = bundle.init()
    loss = None
    t0 = timeit.default_timer()
    if prefetch:
        pf = DevicePrefetcher(n_windows, stage, depth=2)
        try:
            for window in pf:
                state, loss = fused(state, window)
        finally:
            pf.close()
    else:
        for w in range(n_windows):
            state, loss = fused(state, stage(w))
    float(np.asarray(jax.device_get(loss)).reshape(-1)[-1])
    return timeit.default_timer() - t0


def main() -> None:
    with tempfile.TemporaryDirectory() as tmp:
        task = make_task(tmp)
        tech = DataParallel()
        devices = jax.devices()[:1]
        bundle = tech.build(task, devices, {})
        ds = task.get_dataset()

        # Compile + warm every program outside the timed regions, then run
        # each mode twice and keep the faster pass (CPU timer noise).
        run_per_step(bundle, ds, 2)
        run_fused(bundle, ds, 2 * WINDOW, WINDOW, prefetch=False)

        tokens = N_BATCHES * BATCH_SIZE * SEQ_LEN
        results = {}
        for name, fn in (
            ("per_step", lambda: run_per_step(bundle, ds, N_BATCHES)),
            ("fused", lambda: run_fused(bundle, ds, N_BATCHES, WINDOW, False)),
            ("fused_prefetch",
             lambda: run_fused(bundle, ds, N_BATCHES, WINDOW, True)),
        ):
            dt = min(fn(), fn())
            results[name] = tokens / dt

    out = {
        "metric": "step_pipeline_tokens_per_sec",
        "value": round(results["fused_prefetch"], 1),
        "unit": "tokens/s",
        "per_step": round(results["per_step"], 1),
        "fused": round(results["fused"], 1),
        "fused_prefetch": round(results["fused_prefetch"], 1),
        "speedup_vs_per_step": round(
            results["fused_prefetch"] / results["per_step"], 3
        ),
        "window": WINDOW,
        "n_batches": N_BATCHES,
        "batch_size": BATCH_SIZE,
        "seq_len": SEQ_LEN,
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
