"""Comm/compute overlap benchmark: serial vs overlapped, same shape.

Times the three overlapped lowerings this repo carries against their serial
twins on identical shapes, and proves the swap is free: each pair runs a
short SGD trajectory and the per-step losses must agree **bitwise** (the
overlap knobs reorder communication, never arithmetic):

  zero3          ``ops/collective_matmul.zero3_loss_and_grads`` with
                 ``prefetch`` off (layer k's gather on the critical path,
                 the GSPMD-like serial lowering) vs on (layer k+1's hops
                 ride under layer k's compute).
  pipeline_1f1b  ``ops/pipeline.staged_pipeline_loss_and_grads`` with
                 ``overlap`` off vs on (next tick's stage hop launched
                 before this tick's compute).
  ring           ``ops/ring.ring_attention`` with ``overlap`` off vs on
                 (kv block s+1's ppermute issued before folding block s).

Per pair the row reports min-of-reps step time, achieved FLOP/s and MFU
against a nominal peak (``SATURN_TPU_BENCH_PEAK_FLOPS``, default 1e12 —
the *ratio* is the signal; on CPU the absolute MFU is nominal-relative).
The headline is the pair with the best overlapped/serial speedup.

Overlap is a *scheduling* win: it needs hardware that can run a DMA and
compute concurrently. On a single-core CI host XLA executes every thunk
serially, so the measured overlapped time is bounded below by serial and
the double-buffer's extra copies show up as a small tax — the row records
``host_cores`` so readers (and the guard) can tell a serialized host from
a real regression. The ``priced`` section is the deterministic witness:
it traces the fsdp overlap grid point through shardflow and prices the
ledger serial vs overlapped with the active per-op-class factors — the
same repricing admission and the solver apply — which is strictly below
serial on every host. ``bench_guard.validate_overlap_row`` gates on all
of it: trajectories bitwise equal, measured overlapped time within noise
tolerance of serial (and strictly faster where the host can overlap),
MFU non-decreasing within the same tolerance, priced speedup > 1.

Run: ``python benchmarks/comm_overlap.py [--json] [--reps 10]``
"""

from __future__ import annotations

import argparse
import json
import os
import timeit


def _envf(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _time_min(fn, args, reps: int, warmup: int = 2) -> float:
    """Min-of-reps seconds for ``fn(*args)`` whose first output is a scalar
    loss (host-read to sync the device queue, as utils/timing does)."""
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.device_get(out[0])
    best = float("inf")
    for _ in range(reps):
        t0 = timeit.default_timer()
        out = fn(*args)
        jax.device_get(out[0])
        best = min(best, timeit.default_timer() - t0)
    return best


def _trajectory(fn, params, tokens, steps: int, lr: float = 0.1):
    """Per-step losses of a short SGD loop — the bit-identity witness."""
    import jax

    losses = []
    for _ in range(steps):
        loss, grads = fn(params, tokens)
        losses.append(float(jax.device_get(loss)))
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return losses


def _toy(L, DM, V, B, T, seed=0):
    import jax
    import jax.numpy as jnp

    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(seed), 4)
    params = {
        "emb": jax.random.normal(k1, (V, DM)) * 0.02,
        "blocks": {
            "w": jax.random.normal(k2, (L, DM, DM)) * 0.1,
            "b": jnp.zeros((L, DM)),
        },
        "head": jax.random.normal(k3, (DM, V)) * 0.02,
    }
    tokens = jax.random.randint(k4, (B, T), 0, V)
    fns = dict(
        embed_fn=lambda other, tok: other["emb"][tok],
        block_fn=lambda lp, h: jnp.tanh(h @ lp["w"] + lp["b"]),
        head_fn=lambda other, h: h @ other["head"],
        loss_fn=lambda logits, tok: -jnp.mean(
            jnp.take_along_axis(
                jax.nn.log_softmax(logits, axis=-1), tok[..., None], axis=-1
            )
        ),
    )
    # fwd+bwd dense-matmul flops: 3x the forward 2mnk per block matmul
    # plus the head projection (embedding lookup is a gather, not counted).
    flops = 6.0 * B * T * DM * DM * L + 6.0 * B * T * DM * V
    return params, tokens, fns, flops


def bench_zero3(reps, steps):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from saturn_tpu.ops.collective_matmul import zero3_loss_and_grads

    L, DM, V, B, T = 8, 256, 512, 32, 64
    params, tokens, fns, flops = _toy(L, DM, V, B, T)
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))

    def make(prefetch):
        return jax.jit(lambda p, t: zero3_loss_and_grads(
            p, t, mesh=mesh, block_key="blocks", shard_axis="data",
            prefetch=prefetch, min_size=1, **fns))

    return _run_pair(make(False), make(True), params, tokens,
                     reps, steps, flops)


def bench_pipeline(reps, steps):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from saturn_tpu.ops.pipeline import staged_pipeline_loss_and_grads

    L, DM, V, B, T = 8, 256, 512, 32, 64
    params, tokens, fns, flops = _toy(L, DM, V, B, T)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "stage"))

    def make(overlap):
        return jax.jit(lambda p, t: staged_pipeline_loss_and_grads(
            p, t, mesh=mesh, block_key="blocks", n_microbatches=8,
            schedule="1f1b", overlap=overlap, **fns))

    return _run_pair(make(False), make(True), params, tokens,
                     reps, steps, flops)


def bench_ring(reps, steps):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from saturn_tpu.ops.ring import ring_attention
    from saturn_tpu.ops.shmap_compat import shard_map

    B, H, T, D, S = 4, 8, 1024, 64, 8
    mesh = Mesh(np.array(jax.devices()[:S]).reshape(1, S), ("data", "seq"))
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(kq, (B, H, T, D))
    k = jax.random.normal(kk, (B, H, T, D))
    v = jax.random.normal(kv, (B, H, T, D))

    def make(overlap):
        def attn(qq, kk_, vv):
            return ring_attention(
                qq, kk_, vv, axis_name="seq", axis_size=S, overlap=overlap
            )

        sm = shard_map(
            attn, mesh=mesh,
            in_specs=(P(None, None, "seq", None),) * 3,
            out_specs=P(None, None, "seq", None),
        )

        def loss_and_grads(qq, rest):
            kk_, vv = rest

            def L(x):
                return jnp.mean(sm(x, kk_, vv) ** 2)

            return jax.value_and_grad(L)(qq)

        return jax.jit(loss_and_grads)

    # causal attention fwd+bwd: ~3x fwd; fwd = 2 matmuls of 2*B*H*T^2*D / 2
    flops = 3.0 * 2.0 * 2.0 * B * H * T * T * D / 2.0
    return _run_pair(make(False), make(True), q, (k, v), reps, steps, flops)


def _run_pair(serial_fn, overlap_fn, params, tokens, reps, steps, flops):
    serial_tr = _trajectory(serial_fn, params, tokens, steps)
    overlap_tr = _trajectory(overlap_fn, params, tokens, steps)
    bit_identical = serial_tr == overlap_tr
    t_serial = _time_min(serial_fn, (params, tokens), reps)
    t_overlap = _time_min(overlap_fn, (params, tokens), reps)
    peak = _envf("SATURN_TPU_BENCH_PEAK_FLOPS", 1e12)
    return {
        "serial_ms": round(t_serial * 1e3, 3),
        "overlapped_ms": round(t_overlap * 1e3, 3),
        "speedup": round(t_serial / t_overlap, 4),
        "tflops_serial": round(flops / t_serial / 1e12, 4),
        "tflops_overlapped": round(flops / t_overlap / 1e12, 4),
        "mfu_serial": round(flops / t_serial / peak, 4),
        "mfu_overlapped": round(flops / t_overlap / peak, 4),
        "bit_identical": bit_identical,
        "loss_trajectory": [round(x, 8) for x in serial_tr],
    }


def priced_pair() -> dict:
    """Serial vs overlapped **static pricing** of one real executor program.

    Traces the fsdp overlap grid point through shardflow (the same
    ``trace_step`` -> ``interpret`` -> ``estimate_step_seconds`` path
    admission and the solver run) and prices the ledger both ways. Unlike
    the measured pairs this delta is deterministic everywhere: the
    per-op-class overlap factors discount the gather wire time, so the
    overlapped estimate is strictly below serial whenever the program
    communicates at all — the repricing the calibrated factors feed.
    """
    import jax

    from saturn_tpu import HParams, Task
    from saturn_tpu.analysis.shardflow.interp import interpret
    from saturn_tpu.analysis.shardflow.prior import estimate_step_seconds
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss
    from saturn_tpu.parallel.fsdp import FSDP

    seq, batch = 64, 8
    task = Task(
        get_model=lambda **kw: build_gpt2("test-tiny", seq_len=seq, **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=seq, batch_size=batch, n_tokens=seq * batch * 2,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=2),
        save_dir="/tmp/comm_overlap_bench",
    )
    devices = jax.devices()[:8]
    traced = FSDP().trace_step(
        task, devices, {"remat": False, "offload": False, "overlap": True}
    )
    ledger = interpret(traced)
    serial_s = estimate_step_seconds(ledger, len(devices), overlap=False)
    over_s = estimate_step_seconds(ledger, len(devices), overlap=True)
    return {
        "serial_ms": round(serial_s * 1e3, 6),
        "overlapped_ms": round(over_s * 1e3, 6),
        "speedup": round(serial_s / over_s, 4),
    }


def run(reps: int = 10, steps: int = 3) -> dict:
    import jax

    pairs = {
        "zero3": bench_zero3(reps, steps),
        "pipeline_1f1b": bench_pipeline(reps, steps),
        "ring": bench_ring(reps, steps),
    }
    headline = max(pairs, key=lambda n: pairs[n]["speedup"])
    hp = pairs[headline]
    return {
        "metric": "comm_overlap",
        "platform": jax.devices()[0].platform,
        "devices": len(jax.devices()),
        "host_cores": os.cpu_count() or 1,
        "pairs": pairs,
        "headline": headline,
        "serial_ms": hp["serial_ms"],
        "overlapped_ms": hp["overlapped_ms"],
        "speedup": hp["speedup"],
        "mfu_serial": hp["mfu_serial"],
        "mfu_overlapped": hp["mfu_overlapped"],
        "bit_identical": all(p["bit_identical"] for p in pairs.values()),
        "priced": priced_pair(),
    }


def main():
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--steps", type=int, default=3,
                    help="SGD steps in the bit-identity trajectory")
    ap.add_argument("--json", action="store_true",
                    help="print only the JSON row")
    args = ap.parse_args()

    row = run(reps=args.reps, steps=args.steps)
    if not args.json:
        for name, p in row["pairs"].items():
            print(f"{name:14s} serial {p['serial_ms']:9.2f} ms  "
                  f"overlapped {p['overlapped_ms']:9.2f} ms  "
                  f"speedup {p['speedup']:.3f}x  "
                  f"bit_identical={p['bit_identical']}")
        print(f"headline: {row['headline']} {row['speedup']:.3f}x")
    print(json.dumps(row))
    return 0 if row["bit_identical"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
