"""Chaos campaign benchmark: seeded fault sweeps + sentinel overhead.

Runs the PR-8 acceptance campaign end to end on the CPU mesh:

- three seeded campaigns (``resilience.chaos.run_campaign``), each drawing
  one fault per health class — NaN loss, loss spike, persistent batch
  poisoning, dispatch stall — against two tiny GPT-2 jobs; the first seed
  additionally arms a simulated SIGKILL at the ``post-rollback`` journal
  barrier and restarts through it;
- a fault-free baseline of the same jobs for the makespan-inflation ratio;
- per campaign, a fault-free REFERENCE run with the campaign's final
  quarantine pre-applied: ``compare_checkpoints`` then proves every job's
  published checkpoint is byte-identical to training the same surviving
  batch sequence without any faults (faults land in interval 0, so the
  rollback target is the initial state and the comparison is exact);
- the sentinel's hot-path cost: the fused dispatch loop from
  ``benchmarks/step_pipeline.py`` timed with the end-of-interval loss fold
  + report readback versus the bare last-loss readback it replaced.

Prints ONE JSON line (schema: ``bench_guard.CHAOS_ROW_REQUIRED``, and this
script refuses to print a row that fails ``bench_guard.validate_chaos_row``):

    {"metric": "chaos_campaign", "seeds": [...], "fault_classes": [...],
     "jobs": 6, "jobs_lost": 0, "restarts": 1, "quarantined_batches": 3,
     "makespan_inflation": 2.4, "trajectory_bit_identical": true,
     "sentinel_overhead_pct": 0.3, "platform": "cpu", "status": "ok"}

``status`` is "ok" only when zero jobs were lost, every checkpoint matched
its reference byte-for-byte, and the sentinel overhead stayed <= 2%.
Run: ``python benchmarks/chaos_campaign.py`` (not part of tier-1).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import timeit

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import saturn_tpu
from saturn_tpu import HParams, Task, library
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.data.lm_dataset import make_lm_dataset
from saturn_tpu.health import SentinelConfig, sentinel
from saturn_tpu.models.gpt2 import build_gpt2
from saturn_tpu.models.loss import pretraining_loss
from saturn_tpu.parallel.dp import DataParallel
from saturn_tpu.resilience.chaos import (
    CampaignSpec,
    HEALTH_FAULT_CLASSES,
    compare_checkpoints,
    run_campaign,
)

import bench_guard

SEEDS = (11, 23, 47)
SEQ_LEN = 16
BATCH_SIZE = 2
N_BATCHES = 8          # == epoch length: quarantine comparison stays exact
TASK_NAMES = ("chaos-a", "chaos-b")


def make_template(save_dir: str, name: str) -> Task:
    return Task(
        get_model=lambda **kw: build_gpt2("test-tiny", seq_len=SEQ_LEN, **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=SEQ_LEN, batch_size=BATCH_SIZE, vocab_size=256,
            n_tokens=SEQ_LEN * BATCH_SIZE * N_BATCHES,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=N_BATCHES),
        chip_range=[2],
        name=name,
        save_dir=save_dir,
    )


def clone_tasks(templates, save_dir: str):
    """Fresh per-run task list sharing the templates' profiled strategies.
    Keeps the journal-stable names; only the checkpoint directory moves."""
    os.makedirs(save_dir, exist_ok=True)
    out = []
    for t in templates:
        c = t.clone(name=t.name)
        c.save_dir = save_dir
        out.append(c)
    return out


def run_plain(templates, save_dir: str, topo) -> float:
    """One fault-free orchestration of the job set; returns wall seconds."""
    tasks = clone_tasks(templates, save_dir)
    t0 = timeit.default_timer()
    saturn_tpu.orchestrate(
        tasks, interval=30.0, topology=topo, solver_time_limit=2.0
    )
    return timeit.default_timer() - t0


def sentinel_overhead_pct(tmp: str) -> float:
    """Fused-dispatch loop (the per-step benchmark path) with the sentinel's
    end-of-interval fold + report readback vs the bare last-loss readback.
    The fold is ONE jitted scan over the interval's loss vector — the single
    host transfer the interval already paid now moves 6 floats instead of 1."""
    n, k = 256, 16
    task = make_template(os.path.join(tmp, "overhead"), "overhead-probe")
    tech = DataParallel()
    bundle = tech.build(task, jax.devices()[:1], {})
    ds = task.get_dataset()
    fused = bundle.fused_compiled(k)
    sharding = bundle.stacked_sharding()
    cfg = SentinelConfig(enabled=True)

    def stage(w: int):
        host = np.stack(
            [np.asarray(ds.batch((w * k + j) % N_BATCHES)) for j in range(k)]
        )
        return jax.device_put(host, sharding)

    windows = [stage(w) for w in range(n // k)]

    def run(with_sentinel: bool) -> float:
        import jax.numpy as jnp

        state = bundle.init()
        losses = []
        t0 = timeit.default_timer()
        for w in windows:
            state, loss = fused(state, w)
            if with_sentinel:
                losses.append(loss.reshape(-1))
        if with_sentinel:
            rep = sentinel.fold(
                jnp.asarray(sentinel.carry_init()), jnp.concatenate(losses), cfg
            )
            float(np.asarray(jax.device_get(rep))[sentinel.REP_LAST_LOSS])
        else:
            float(np.asarray(jax.device_get(loss)).reshape(-1)[-1])
        return timeit.default_timer() - t0

    run(False)  # compile + warm both programs outside the timed passes
    run(True)
    t_off = min(run(False) for _ in range(3))
    t_on = min(run(True) for _ in range(3))
    return (t_on - t_off) / t_off * 100.0


def main() -> None:
    topo = SliceTopology(jax.devices())
    library.register_default_library()
    # Spike detection is workload policy (off by default); the campaign
    # injects 1e9 spikes, so turn the EWMA screen on for every run here.
    sentinel.set_config(SentinelConfig(enabled=True, spike_factor=8.0,
                                       warmup_steps=2))

    with tempfile.TemporaryDirectory() as tmp:
        templates = [
            make_template(os.path.join(tmp, "templates"), n)
            for n in TASK_NAMES
        ]
        saturn_tpu.search(templates, technique_names=["dp"], topology=topo)

        run_plain(templates, os.path.join(tmp, "warmup"), topo)  # compile
        baseline_s = run_plain(templates, os.path.join(tmp, "baseline"), topo)

        restarts = jobs_lost = quarantined_total = 0
        mismatches = []
        campaign_times = []
        for i, seed in enumerate(SEEDS):
            spec = CampaignSpec(seed=seed, kill_during_rollback=(i == 0),
                                poison_range=N_BATCHES, stall_s=0.25)
            save = os.path.join(tmp, f"camp{seed}", "ckpts")
            t0 = timeit.default_timer()
            result = run_campaign(
                lambda: clone_tasks(templates, save),
                spec,
                os.path.join(tmp, f"camp{seed}", "wal"),
                interval=30.0, topology=topo, solver_time_limit=2.0,
            )
            campaign_times.append(timeit.default_timer() - t0)
            restarts += result.restarts
            jobs_lost += len(result.failed)
            jobs_lost += sum(
                1 for n in TASK_NAMES
                if n not in result.completed and n not in result.failed
            )
            quarantined_total += sum(
                len(v) for v in result.quarantined.values()
            )

            # Reference: same jobs, no faults, the campaign's final
            # quarantine pre-applied — the surviving-batch trajectory the
            # faulted run must have reproduced bit-for-bit.
            ref_save = os.path.join(tmp, f"camp{seed}", "ref")
            ref_tasks = clone_tasks(templates, ref_save)
            for t in ref_tasks:
                t.quarantine_batches(result.quarantined.get(t.name, []))
            saturn_tpu.orchestrate(
                ref_tasks, interval=30.0, topology=topo, solver_time_limit=2.0
            )
            mismatches.extend(
                f"seed {seed}: {m}"
                for m in compare_checkpoints(save, ref_save,
                                             names=list(TASK_NAMES))
            )

        overhead = sentinel_overhead_pct(tmp)

    bit_identical = not mismatches
    row = {
        "metric": "chaos_campaign",
        "seeds": list(SEEDS),
        "fault_classes": [str(c) for c in HEALTH_FAULT_CLASSES],
        "jobs": len(SEEDS) * len(TASK_NAMES),
        "jobs_lost": jobs_lost,
        "restarts": restarts,
        "quarantined_batches": quarantined_total,
        "makespan_inflation": round(
            (sum(campaign_times) / len(campaign_times)) / baseline_s, 3
        ),
        "trajectory_bit_identical": bit_identical,
        "sentinel_overhead_pct": round(overhead, 3),
        "platform": jax.devices()[0].platform,
        "status": (
            "ok"
            if jobs_lost == 0 and bit_identical and overhead <= 2.0
            else "degraded"
        ),
    }
    if mismatches:
        row["mismatches"] = mismatches[:8]
    problems = bench_guard.validate_chaos_row(row)
    if problems:
        raise SystemExit(f"chaos row failed its own schema: {problems}")
    print(json.dumps(row))


if __name__ == "__main__":
    main()
