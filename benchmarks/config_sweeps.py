"""Staged-config sweeps #2-#4 (BASELINE.md "Targets", VERDICT r2 item 2).

One driver for the three staged configs between the single-job bench (#1,
bench.py) and the 16-job flagship (#5, examples/lm_sweep/driver.py):

- **#2** 4-job GPT-2-small LR sweep, DP executor only — meant for the real
  chip, where single-chip blocks make the makespan honest (tasks time-share
  nothing; the reference anchor is the 6-task LR×batch sweep of
  ``/root/reference/examples/wikitext103/WikiText103.py:62-71``).
- **#3** 8-job GPT-2-medium/large sweep, FSDP + pipeline executors.
- **#4** 12-job heterogeneous batch (three model families × sizes) with the
  offload executor in the mix (reference anchor: Spilled,
  ``/root/reference/saturn/library.py`` default registry).

Each run routes ``search`` + ``orchestrate`` through a metrics JSONL and
prints the rows BASELINE.md records: profiling wall, SPASE plan makespan,
realized orchestration wall, per-interval planned-vs-elapsed error, and
per-job samples/sec.

On the 8-device CPU mesh (``--platform cpu``) configs #3/#4 run at reduced
shapes — the host can't push gpt2-medium FLOPs; the run proves the
*mechanism* (solver, gang launch, executor schedules), while the real-chip
rows for medium/large capability come from ``memory_contract.py`` and
``bench.py``. Record shapes with the row; never compare across shapes.

Run: ``python benchmarks/config_sweeps.py --config 2            # real chip``
     ``python benchmarks/config_sweeps.py --config 3 --platform cpu``
"""

from __future__ import annotations

import argparse
import json
import os
import time


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--config", type=int, required=True, choices=[2, 3, 4])
    p.add_argument("--platform", choices=["default", "cpu"], default="default")
    p.add_argument("--interval", type=float, default=None,
                   help="scheduling interval seconds (default per config)")
    p.add_argument("--batch-count", type=int, default=None,
                   help="batches per task (default per config/platform)")
    p.add_argument("--metrics", default=None,
                   help="metrics JSONL path (default /tmp/configN_metrics.jsonl)")
    p.add_argument("--save-dir", default="/tmp/saturn_config_ckpts")
    return p.parse_args()


def build_tasks(config: int, cpu: bool, batch_count: int):
    """Task list + technique/chip restrictions for a staged config."""
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2, config_for
    from saturn_tpu.models.loss import pretraining_loss

    def lm_task(preset, bs, lr, name, seq=None, chip_range=None, **model_kw):
        ctx = seq or config_for(preset).seq_len
        vocab = config_for(preset).vocab_size
        return Task(
            get_model=lambda **kw: build_gpt2(
                preset, seq_len=ctx, **model_kw, **kw
            ),
            get_dataloader=lambda: make_lm_dataset(
                context_length=ctx, batch_size=bs, vocab_size=vocab,
                n_tokens=ctx * bs * max(batch_count, 8),
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=lr, batch_count=batch_count),
            chip_range=chip_range,
            name=name,
        )

    if config == 2:
        # 4 jobs = one searched base + 3 lr clones; DP only, 1-chip blocks.
        preset = "test-tiny" if cpu else "gpt2-small"
        seq = 64 if cpu else 512
        base = lm_task(preset, 8, 1e-3, f"c2-{preset}-lr0.001", seq=seq,
                       chip_range=[1])
        lrs = [3e-4, 1e-4, 3e-3]
        return [base], lrs, ["dp"], None

    if config == 3:
        # 8 jobs: 2 sizes × 2 batch sizes searched, ×2 lrs cloned;
        # FSDP + pipeline only, multi-chip blocks.
        if cpu:
            sizes = [("test-tiny", dict(seq=64)),
                     ("gptj-test-tiny", dict(seq=64))]
            batches = [4, 8]
        else:
            sizes = [("gpt2-medium", {}), ("gpt2-large", {})]
            batches = [4, 8]
        tasks = []
        for preset, kw in sizes:
            for bs in batches:
                tasks.append(lm_task(
                    preset, bs, 1e-3, f"c3-{preset}-bs{bs}-lr0.001",
                    chip_range=[2, 4], **kw,
                ))
        return tasks, [3e-4], ["fsdp", "pp"], None

    # config 4: 12 heterogeneous jobs, offload in the technique mix.
    if cpu:
        fams = [("test-tiny", dict(seq=64)),
                ("gptj-test-tiny", dict(seq=64)),
                ("moe-test-tiny", dict(seq=64))]
        batches = [2, 4]
    else:
        fams = [("gpt2-small", {}), ("gpt2-medium", {}),
                ("gpt2-small-moe8", {})]
        batches = [4, 8]
    tasks = []
    for preset, kw in fams:
        for bs in batches:
            tasks.append(lm_task(
                preset, bs, 1e-3, f"c4-{preset}-bs{bs}-lr0.001",
                chip_range=[1, 2, 4], **kw,
            ))
    return tasks, [3e-4], ["dp", "fsdp", "offload"], None


def summarize(metrics_path: str, search_wall: float, orch_wall: float,
              n_tasks: int):
    events = []
    with open(metrics_path) as f:
        for line in f:
            events.append(json.loads(line))
    solves = [e for e in events if e["kind"] == "solve"]
    intervals = [e for e in events if e["kind"] == "interval"]
    per_task = {}
    for e in events:
        if e["kind"] == "task_interval":
            per_task.setdefault(e["task"], []).append(e)
    completed = {e["task"] for e in events if e["kind"] == "task_completed"}

    print("\n== summary ==")
    print(f"tasks: {n_tasks} ({len(completed)} completed)")
    print(f"search wall: {search_wall:.1f}s  orchestration wall: {orch_wall:.1f}s")
    if solves:
        print(f"planned makespan (first solve): {solves[0]['makespan_s']:.1f}s "
              f"over {solves[0]['n_tasks']} tasks")
    for i, e in enumerate(intervals):
        err = e["elapsed_s"] / e["planned_s"] - 1 if e["planned_s"] else 0
        print(f"interval {i}: planned {e['planned_s']:.0f}s "
              f"elapsed {e['elapsed_s']:.1f}s ({err:+.0%}) "
              f"tasks={e['n_tasks']} failed={e['failed']}")
    print("\n| task | technique | samples/s (last) | per-batch s |")
    print("|---|---|---|---|")
    for name in sorted(per_task):
        last = per_task[name][-1]
        print(f"| {name} | {last['technique']} | {last['samples_per_sec']} "
              f"| {last['per_batch_s']:.3f} |")


def main():
    args = parse_args()
    cpu = args.platform == "cpu"
    if cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
            + " --xla_cpu_collective_call_terminate_timeout_seconds=600"
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
        # compile cache is opt-in: cross-context entries execute wrong code
        # (tests/conftest.py has the post-mortem)
        if os.environ.get("SATURN_TPU_COMPILE_CACHE"):
            jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import jax

    import saturn_tpu
    from saturn_tpu import library

    library.register_default_library()
    batch_count = args.batch_count or (4 if cpu else 64)
    interval = args.interval or (30.0 if cpu else 60.0)
    metrics_path = args.metrics or f"/tmp/config{args.config}_metrics.jsonl"
    if os.path.exists(metrics_path):
        os.remove(metrics_path)

    base_tasks, clone_lrs, technique_names, _ = build_tasks(
        args.config, cpu, batch_count
    )
    os.makedirs(args.save_dir, exist_ok=True)
    for t in base_tasks:
        t.save_dir = args.save_dir

    print(f"config #{args.config} on {jax.devices()[0].platform} "
          f"({len(jax.devices())} devices), batch_count={batch_count}, "
          f"interval={interval}s, techniques={technique_names}")

    t0 = time.time()
    saturn_tpu.search(
        base_tasks, technique_names=technique_names, log=True,
        metrics_path=metrics_path,
    )
    search_wall = time.time() - t0

    tasks = list(base_tasks)
    for task in base_tasks:
        for lr in clone_lrs:
            tasks.append(task.clone(
                name=task.name.rsplit("-lr", 1)[0] + f"-lr{lr:g}", lr=lr
            ))
    for t in tasks:
        t.save_dir = args.save_dir

    t0 = time.time()
    saturn_tpu.orchestrate(
        tasks, log=True, interval=interval, metrics_path=metrics_path
    )
    orch_wall = time.time() - t0

    summarize(metrics_path, search_wall, orch_wall, len(tasks))


if __name__ == "__main__":
    main()
