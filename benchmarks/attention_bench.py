"""Flash-vs-dense attention measurement (VERDICT r1 item 2/3).

Times one jitted train step of GPT-2-small with `attention="dense"` vs
`attention="flash"` (the Pallas kernel, `ops/flash.py`) on the current
backend, at several sequence lengths. On TPU this decides the default; on
CPU the flash path runs in interpret mode and is only a correctness check,
so the script refuses unless --force-cpu.

Run: ``python benchmarks/attention_bench.py [--preset gpt2-small]``
Prints a markdown table for BASELINE.md.
"""

from __future__ import annotations

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2-small")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seqs", type=int, nargs="+", default=[512, 1024, 2048])
    ap.add_argument("--force-cpu", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    if jax.default_backend() != "tpu" and not args.force_cpu:
        raise SystemExit(
            "refusing to 'benchmark' Pallas interpret mode on "
            f"{jax.default_backend()}; pass --force-cpu to run anyway"
        )

    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss
    from saturn_tpu.utils.timing import time_train_step

    print(f"backend={jax.default_backend()} preset={args.preset} batch={args.batch}\n")
    print("| seq | dense ms/step | flash ms/step | flash speedup |")
    print("|---|---|---|---|", flush=True)
    for seq in args.seqs:
        row = {}
        for attn in ("dense", "flash"):
            spec = build_gpt2(args.preset, seq_len=seq, attention=attn)
            ds = make_lm_dataset(
                context_length=seq, batch_size=args.batch,
                vocab_size=spec.config.vocab_size,
                n_tokens=seq * args.batch * 4,
            )
            tx = optax.adamw(3e-4)

            def init_state():
                p = spec.init_fn(jax.random.PRNGKey(0))
                return {"params": p, "opt": tx.init(p)}

            def step(state, batch):
                def loss_of(p):
                    return pretraining_loss(spec.apply_fn(p, batch), batch)

                loss, g = jax.value_and_grad(loss_of)(state["params"])
                up, opt = tx.update(g, state["opt"], state["params"])
                return {"params": optax.apply_updates(state["params"], up),
                        "opt": opt}, loss

            jstep = jax.jit(step, donate_argnums=(0,))
            try:
                state = jax.jit(init_state)()
                batch = jnp.asarray(ds.batch(0))
                row[attn] = time_train_step(
                    jstep, state, batch, n_timed=10, n_warmup=3
                )
                del state
            except Exception as e:
                # a config exceeding HBM is a RESULT (dense materializes the
                # (T,T) scores and dies first at long seq) — record, move on
                row[attn] = None
                print(f"  [{attn} seq={seq}: {type(e).__name__}: "
                      f"{str(e)[:100]}]", flush=True)
        d, f = row.get("dense"), row.get("flash")
        d_s = f"{d*1e3:.1f}" if d else "OOM"
        f_s = f"{f*1e3:.1f}" if f else "OOM"
        sp = f"{d/f:.2f}x" if d and f else ("flash only" if f else "—")
        print(f"| {seq} | {d_s} | {f_s} | {sp} |", flush=True)


if __name__ == "__main__":
    main()
