"""Lint session: ruff + mypy with the repo's tiered strictness.

The analyzer package (``saturn_tpu/analysis/``) is held to the strict
configuration in ``pyproject.toml`` — it is the gate every plan-adoption
site trusts, so it gets the strongest static guarantees in the tree; the
rest of the repo runs the permissive baseline.

Neither tool is baked into the CI image, so this session *skips* (exit 0,
with a notice) when one is missing rather than failing the build — the
same gate-on-absence rule as the hypothesis-optional differential test.

Run: ``python tools/lint.py`` — exit 1 only on real findings.
"""

from __future__ import annotations

import ast
import glob
import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Packages where a silently swallowed exception eats a training fault the
#: guardian was supposed to see — the recovery path itself must never lose
#: an error.
SWALLOW_ROOTS = ("saturn_tpu/executor", "saturn_tpu/health")

#: A handler that calls one of these (method or bare name) is observing the
#: failure, not swallowing it: logging, metrics, or an error-ledger write.
_OBSERVERS = frozenset({
    "debug", "info", "warning", "error", "exception", "critical",
    "log", "event", "append", "record", "put", "add",
})


def _observes(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Yield, ast.Continue,
                             ast.Break)):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if name in _OBSERVERS:
                return True
        # ``except Exception as e`` whose body reads ``e`` is capturing the
        # failure into state someone inspects later, not dropping it.
        if (handler.name and isinstance(node, ast.Name)
                and node.id == handler.name):
            return True
    return False


def _swallow_findings(roots=SWALLOW_ROOTS) -> list:
    """Flag ``except Exception:`` / bare ``except:`` handlers in the
    executor and health packages whose body neither re-raises, diverts
    control flow, nor records the failure (log/metric/ledger). Returns
    ``{"path", "line", "message"}`` dicts; empty means clean."""
    findings = []
    for root in roots:
        for path in sorted(glob.glob(os.path.join(REPO, root, "**", "*.py"),
                                     recursive=True)):
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                broad = node.type is None or (
                    isinstance(node.type, ast.Name)
                    and node.type.id in ("Exception", "BaseException")
                )
                if broad and not _observes(node):
                    findings.append({
                        "path": os.path.relpath(path, REPO),
                        "line": node.lineno,
                        "message": "broad except swallows the error "
                                   "silently — re-raise, log, or record it",
                    })
    return findings


#: Calls that would reintroduce a full-tree gather/materialization funnel
#: into the sharded checkpoint writer. Round 19 removed the last sanctioned
#: ones; any new use in utils/checkpoint.py is a format regression.
_CKPT_FORBIDDEN_CALLS = frozenset({"process_allgather", "device_get"})


def _ckpt_format_findings(
    path: str = "saturn_tpu/utils/checkpoint.py",
) -> list:
    """The checkpoint-format gate: the sharded writer must stay zero-gather.
    Flags any call to ``process_allgather`` or ``jax.device_get`` of a whole
    tree/leaf inside ``utils/checkpoint.py`` — per-shard ``shard.data``
    copies are the only sanctioned device→host traffic there."""
    findings = []
    full = os.path.join(REPO, path)
    with open(full) as f:
        tree = ast.parse(f.read(), filename=full)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name not in _CKPT_FORBIDDEN_CALLS:
            continue
        if name == "device_get":
            # the per-shard copy (device_get of shard.data) is the sharded
            # format's one legitimate transfer; a device_get of anything
            # else in this file is a full-leaf materialization
            arg = node.args[0] if node.args else None
            if (isinstance(arg, ast.Attribute) and arg.attr == "data"):
                continue
        findings.append({
            "path": path,
            "line": node.lineno,
            "message": f"{name}() in the checkpoint writer reintroduces a "
                       "full-tree gather funnel — the sharded manifest "
                       "format writes per-shard local copies only",
        })
    return findings


def _have(tool: str) -> bool:
    return importlib.util.find_spec(tool) is not None


def _run(argv: list) -> int:
    r = subprocess.run(argv, cwd=REPO)
    return r.returncode


def main() -> int:
    results = {}
    failed = False

    # The memlens gate below traces techniques at a probe sub-mesh size;
    # the virtual-device flag must land before anything imports jax.
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if _have("ruff"):
        rc = _run([sys.executable, "-m", "ruff", "check", "saturn_tpu",
                   "tests", "tools", "benchmarks"])
        results["ruff"] = "ok" if rc == 0 else f"failed rc={rc}"
        failed |= rc != 0
    else:
        results["ruff"] = "skipped (not installed; pip install -e '.[lint]')"

    if _have("mypy"):
        # Strictness tiers live in pyproject [tool.mypy]; scoping the run to
        # the analyzer keeps the permissive baseline from drowning signal.
        rc = _run([sys.executable, "-m", "mypy", "saturn_tpu/analysis"])
        results["mypy"] = "ok" if rc == 0 else f"failed rc={rc}"
        failed |= rc != 0
    else:
        results["mypy"] = "skipped (not installed; pip install -e '.[lint]')"

    # Always available: the repo's own static passes over its own hot path.
    # A lint session that can't even self-host the analyzer is not a lint
    # session, so these run regardless of which external tools exist.
    sys.path.insert(0, REPO)
    from saturn_tpu.analysis import jax_lint
    from saturn_tpu.parallel.spmd_base import SPMDTechnique

    diags = jax_lint.lint_host_syncs(SPMDTechnique.interval_dispatches)
    diags += jax_lint.lint_donation(
        SPMDTechnique.interval_dispatches,
        {"fused_fn": (0, 1), "single_fn": (0, 1)},
    )
    results["saturn-lint"] = (
        "ok" if not diags else [d.to_json() for d in diags]
    )
    failed |= bool(diags)

    swallows = _swallow_findings()
    results["swallowed-exceptions"] = "ok" if not swallows else swallows
    failed |= bool(swallows)

    # checkpoint-format: the sharded writer must never regress to a gather
    # funnel (process_allgather / full-leaf device_get in checkpoint.py).
    ckpt_regressions = _ckpt_format_findings()
    results["ckpt-format"] = "ok" if not ckpt_regressions else ckpt_regressions
    failed |= bool(ckpt_regressions)

    # saturn-tsan: the concurrency pass over the thread-bearing packages.
    # Gates on unsanctioned SAT-C findings (errors); sanctioned cases are
    # info-severity and pass.
    from saturn_tpu.analysis.concurrency import static_pass

    tsan_report = static_pass.run(static_pass.default_paths(REPO)).report
    results["saturn-tsan"] = (
        "ok" if tsan_report.ok
        else [d.to_json() for d in tsan_report.errors]
    )
    failed |= not tsan_report.ok

    # saturn-shardflow: the source half of the sharding pass (SAT-X002
    # gather-to-replicated funnels) over the technique and kernel packages.
    # AST-only — no jax, no devices — so it gates in any environment; the
    # full jaxpr trace audit is ``python -m saturn_tpu.analysis shardflow``.
    from saturn_tpu.analysis.diagnostics import AnalysisReport
    from saturn_tpu.analysis.shardflow import passes as sf_passes

    sf_report = AnalysisReport(subject="shardflow-sources")
    sf_passes.scan_sources(sf_passes.default_source_paths(REPO), sf_report)
    results["saturn-shardflow"] = (
        "ok" if sf_report.ok
        else [d.to_json() for d in sf_report.errors]
    )
    failed |= not sf_report.ok

    # saturn-memlens: the peak-liveness audit over every in-tree
    # technique's traced step. Gates on unsanctioned SAT-M001/M003 errors
    # (predicted OOM / missed donation); without a known HBM capacity only
    # M003 can fire, which is exactly the source invariant — in-tree step
    # functions must donate their state. An environment whose jax cannot
    # trace at all skips, per the gate-on-absence rule.
    from saturn_tpu.analysis.memlens import passes as ml_passes

    try:
        ml_report, _ = ml_passes.audit_intree(size=4)
    except Exception as e:
        results["saturn-memlens"] = f"skipped ({type(e).__name__}: {e})"
    else:
        results["saturn-memlens"] = (
            "ok" if ml_report.ok
            else [d.to_json() for d in ml_report.errors]
        )
        failed |= not ml_report.ok

    print(json.dumps({"metric": "lint", "results": results,
                      "status": "failed" if failed else "ok"}))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
