"""Multi-host orchestration driver — run the SAME script on every host.

TPU pod form (arguments autodetected):
    python examples/multihost/driver.py

CPU fixture form (what CI exercises, 2 processes x 2 devices):
    python examples/multihost/driver.py --processes 2 --process-id 0 \
        --coordinator 127.0.0.1:9555 --platform cpu &
    python examples/multihost/driver.py --processes 2 --process-id 1 \
        --coordinator 127.0.0.1:9555 --platform cpu

The reference could not express this at all — its solver pinned every job
to one node (``saturn/solver/milp.py:134-137``) because the data plane was
per-job single-node NCCL. Here one JAX runtime spans the hosts and blocks
of at most one slice stay on ICI while slice-multiple blocks cross DCN on
the data axis (``core/mesh.py``).
"""

from __future__ import annotations

import argparse


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--platform", choices=["default", "cpu"], default="default")
    ap.add_argument("--devices-per-process", type=int, default=2,
                    help="cpu fixture only: virtual devices per process")
    ap.add_argument("--batch-count", type=int, default=4)
    ap.add_argument("--save-dir", default="/tmp/saturn_multihost_ckpts")
    args = ap.parse_args()

    if args.platform == "cpu":
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices_per_process}"
            + " --xla_cpu_collective_call_terminate_timeout_seconds=600"
        )
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax  # noqa: F811

    from saturn_tpu import HParams, Task, orchestrate
    from saturn_tpu.core import distributed
    from saturn_tpu.core.strategy import Strategy
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss
    from saturn_tpu.parallel.dp import DataParallel
    from saturn_tpu.parallel.fsdp import FSDP

    distributed.initialize(
        coordinator_address=args.coordinator,
        num_processes=args.processes,
        process_id=args.process_id,
    )
    topo = distributed.global_topology()
    n = topo.capacity
    print(f"rank {distributed.process_index()}/{distributed.process_count()}"
          f": {n} usable devices, slice_size {topo.slice_size}")

    dp, fsdp = DataParallel(), FSDP()

    def mk(name, tech, app):
        t = Task(
            get_model=lambda **kw: build_gpt2("test-tiny", **kw),
            get_dataloader=lambda: make_lm_dataset(
                context_length=64, batch_size=2 * n, vocab_size=256,
                n_tokens=64 * 2 * n * 8,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=1e-3, batch_count=args.batch_count),
            name=name,
            save_dir=args.save_dir,
        )
        # Identical preset strategies on every rank (the multihost
        # contract); to profile instead, search on the coordinator and
        # broadcast with distributed.sync_task_state(tasks).
        t.strategies[app] = Strategy(tech, app, {"remat": False}, 1.0, 0.5)
        return t

    tasks = [
        mk("mh-dp-cross", dp, n),                 # spans every slice (DCN)
        mk("mh-fsdp-half", fsdp, max(n // 2, 1)),  # fits one slice (ICI)
    ]
    res = orchestrate(tasks, interval=120.0, topology=topo, log=True,
                      solver_time_limit=5.0)
    print(f"rank {distributed.process_index()}: {res}")


if __name__ == "__main__":
    main()
