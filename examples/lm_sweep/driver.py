"""End-to-end HPO sweep driver — the canonical saturn_tpu usage.

Parity target: ``examples/wikitext103/WikiText103.py:35-106`` in the
reference. Same shape of flow:

1. register parallelism techniques into the library,
2. build a Task sweep varying batch size,
3. ``search`` — profile every (task × sub-mesh size × technique),
4. clone searched tasks across learning rates WITHOUT re-profiling
   (``WikiText103.py:87-99``: lr doesn't change step time),
5. ``orchestrate`` — solve the SPASE MILP and gang-execute to completion.

Runs on whatever ``jax.devices()`` offers: the real TPU chip, or an 8-device
virtual CPU mesh with ``--platform cpu`` (the multi-node-without-a-cluster
test mode, SURVEY.md §4).

Examples:
    python driver.py --preset test-tiny --platform cpu --batch-count 8
    python driver.py --preset gpt2-small --lrs 1e-4 3e-4 --batch-sizes 8 16
"""

from __future__ import annotations

import argparse
import os
import time

_BUNDLED_CORPUS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "data", "corpus.txt"
)


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="test-tiny",
                   help="model preset (test-tiny, gpt2-small, gptj-test-tiny, ...)")
    p.add_argument("--context-length", type=int, default=None,
                   help="sequence length (default: preset's)")
    p.add_argument("--batch-sizes", type=int, nargs="+", default=[8],
                   help="one task per batch size (reference varied 16/8)")
    p.add_argument("--lrs", type=float, nargs="+", default=[1e-3, 1e-4],
                   help="lr variants cloned from each searched task")
    p.add_argument("--batch-count", type=int, default=16,
                   help="batches per task (reference verification used 100)")
    p.add_argument("--interval", type=float, default=60.0,
                   help="scheduling interval seconds (reference default 1000)")
    p.add_argument("--techniques", nargs="+", default=None,
                   help="library names to profile (default: all registered)")
    p.add_argument("--chip-range", type=int, nargs="+", default=None,
                   help="sub-mesh sizes to profile (default: all powers of two)")
    p.add_argument("--corpus", default=_BUNDLED_CORPUS,
                   help="local text file to tokenize; 'synthetic' for the "
                        "deterministic Zipf stream (default: the bundled "
                        "examples/data/corpus.txt)")
    p.add_argument("--tokenizer", choices=["word", "byte"], default="word",
                   help="corpus tokenizer (native word vocab, or raw bytes)")
    p.add_argument("--save-dir", default="saturn_sweep_ckpts")
    p.add_argument("--platform", choices=["default", "cpu"], default="default",
                   help="cpu = 8 virtual XLA host devices (no TPU needed)")
    return p.parse_args()


def main():
    args = parse_args()
    if args.platform == "cpu":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
        # compile cache is opt-in: cross-context entries execute wrong code
        # (tests/conftest.py has the post-mortem)
        if os.environ.get("SATURN_TPU_COMPILE_CACHE"):
            jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import saturn_tpu
    from saturn_tpu import HParams, Task, library
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2, config_for
    from saturn_tpu.models.loss import pretraining_loss

    # 1) register techniques (reference ``WikiText103.py:49-54`` registered
    #    its UDP classes; the built-in default library covers dp/fsdp/tp/
    #    pipeline/spilled/ring).
    names = library.register_default_library()
    print(f"registered techniques: {names}")

    ctx = args.context_length or config_for(args.preset).seq_len
    vocab = config_for(args.preset).vocab_size
    corpus = None if args.corpus in ("synthetic", "none") else args.corpus
    if corpus and not os.path.exists(corpus):
        raise SystemExit(f"corpus file not found: {corpus}")
    print(f"corpus: {corpus or 'synthetic'} (tokenizer={args.tokenizer})")

    # 2) one task per batch size (reference ``WikiText103.py:62-71``).
    base_tasks = []
    for bs in args.batch_sizes:
        task = Task(
            get_model=lambda **kw: build_gpt2(args.preset, seq_len=ctx, **kw),
            get_dataloader=lambda bs=bs: make_lm_dataset(
                context_length=ctx, batch_size=bs, vocab_size=vocab,
                n_tokens=ctx * bs * max(args.batch_count, 16),
                corpus_path=corpus, tokenizer=args.tokenizer,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=args.lrs[0], batch_count=args.batch_count),
            chip_range=args.chip_range,
            name=f"{args.preset}-bs{bs}-lr{args.lrs[0]:g}",
            save_dir=args.save_dir,
        )
        base_tasks.append(task)

    # 3) profile (reference ``WikiText103.py:75``).
    t0 = time.time()
    saturn_tpu.search(base_tasks, technique_names=args.techniques, log=True)
    print(f"search took {time.time() - t0:.1f}s")

    # 4) lr variants reuse the profile (reference ``WikiText103.py:87-99``).
    tasks = list(base_tasks)
    for task in base_tasks:
        for lr in args.lrs[1:]:
            tasks.append(task.clone(name=task.name.rsplit("-lr", 1)[0] + f"-lr{lr:g}", lr=lr))

    for t in tasks:
        feas = {g: f"{s.runtime:.1f}s/{type(s.executor).name}"
                for g, s in t.feasible_strategies().items()}
        print(f"  {t.name}: {feas}")

    # 5) solve + execute (reference ``WikiText103.py:102``).
    t0 = time.time()
    saturn_tpu.orchestrate(tasks, log=True, interval=args.interval)
    print(f"orchestration took {time.time() - t0:.1f}s for {len(tasks)} tasks")

    from saturn_tpu.utils import checkpoint as ckpt_mod

    for t in tasks:
        step = int(ckpt_mod.load_arrays(t.ckpt_path)["step"])
        print(f"  {t.name}: trained steps={step} remaining={t.total_batches}")


if __name__ == "__main__":
    main()
