"""Install-verification E2E smoke test (no TPU required).

Parity target: ``examples/wikitext103/simple-verification.py:33-111`` — a
``unittest.TestCase`` that registers techniques, builds one task restricted to
specific apportionment sizes, runs the real ``search`` then ``orchestrate``,
and asserts the job finished. Runs on 8 virtual CPU devices, so it exercises
real multi-device pjit programs (SURVEY.md §4's "multi-node without a
cluster" mode).

Run:  python examples/lm_sweep/verify.py
"""

from __future__ import annotations

import os
import unittest

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")


class VerifyInstall(unittest.TestCase):
    """End-to-end: register → Task(chip_range=[4, 8]) → search → orchestrate
    (reference ``simple-verification.py:59-73`` used gpu_range=[4, 8])."""

    def setUp(self):
        from saturn_tpu import HParams, Task, library
        from saturn_tpu.data.lm_dataset import make_lm_dataset
        from saturn_tpu.models.gpt2 import build_gpt2
        from saturn_tpu.models.loss import pretraining_loss

        library.register_default_library()
        self.task = Task(
            get_model=lambda **kw: build_gpt2("test-tiny", **kw),
            get_dataloader=lambda: make_lm_dataset(
                context_length=64, batch_size=8, vocab_size=256,
                n_tokens=64 * 8 * 16,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=1e-3, batch_count=12),
            chip_range=[4, 8],
            name="verify-task",
            save_dir="/tmp/saturn_verify_ckpts",
        )
        self.task.clear_ckpt()

    def test_search_and_orchestrate(self):
        import numpy as np

        import saturn_tpu

        saturn_tpu.search([self.task], technique_names=["dp", "fsdp"], log=True)
        feasible = self.task.feasible_strategies()
        self.assertTrue(feasible, "no feasible strategy found")
        self.assertTrue(set(feasible) <= {4, 8}, f"chip_range ignored: {set(feasible)}")

        saturn_tpu.orchestrate([self.task], log=True, interval=30.0)
        self.assertEqual(self.task.total_batches, 0)
        self.assertTrue(self.task.has_ckpt())
        from saturn_tpu.utils import checkpoint as ckpt_mod
        self.assertEqual(int(ckpt_mod.load_arrays(self.task.ckpt_path)["step"]), 12)


if __name__ == "__main__":
    unittest.main()
