"""Differential oracle: the static plan verifier accepts exactly the
plans the dynamic launch guard accepts.

Since the engine's ``_check_disjoint`` now *delegates* to the analyzer, a
test comparing the two directly would be a tautology.  The oracle here is
an independent brute-force reimplementation of the launch invariants with
deliberately different algorithms — set-merge fixpoint instead of
union-find, Kahn's toposort instead of DFS cycle detection, Floyd-Warshall
closure instead of memoized reachability — so a bug in the shared
implementation shows up as a disagreement, not as agreement-with-itself.

A subsample additionally runs ``engine.execute`` end-to-end with fake
techniques, asserting raise/no-raise matches the static verdict (the
pre-refactor ground truth).

Uses hypothesis when the image carries it; otherwise a seeded
``random.Random`` sweep of the same generator (the floor of 1000 plans is
met either way — the suite must not depend on an uninstalled package).
"""

import random
import threading

import pytest

from saturn_tpu.analysis import plan_verifier
from saturn_tpu.core.mesh import Block, SliceTopology
from saturn_tpu.solver.milp import Assignment, Plan

pytestmark = pytest.mark.analysis

CAPACITY = 8
N_PLANS = 1200          # differential floor is 1000; a margin on top
N_ENGINE_SUBSAMPLE = 60


# ---------------------------------------------------------------------------
# plan generator
# ---------------------------------------------------------------------------

def gen_plan(rng: random.Random):
    """A random plan over a capacity-8 buddy topology: aligned pow2 blocks
    (legal and overlapping alike), random dependency edges (sometimes the
    solver's own consistent edges, sometimes arbitrary garbage), and
    occasional co-schedule groups."""
    n = rng.randint(2, 6)
    names = [f"t{i}" for i in range(n)]
    assignments = {}
    for name in names:
        size = rng.choice([1, 2, 4, 8])
        offset = rng.randrange(0, CAPACITY, size) if size < CAPACITY else 0
        start = float(rng.randint(0, 3))
        runtime = float(rng.randint(1, 4))
        assignments[name] = Assignment(size, Block(offset, size), start, runtime)

    coschedule = []
    if rng.random() < 0.35:
        pool = names[:]
        rng.shuffle(pool)
        g = rng.randint(2, min(3, len(pool)))
        coschedule.append(pool[:g])
        if len(pool) - g >= 2 and rng.random() < 0.3:
            coschedule.append(pool[g:g + 2])

    mode = rng.random()
    plan = Plan(
        assignments=assignments,
        makespan=max(a.start + a.runtime for a in assignments.values()),
        dependencies={},
        coschedule=coschedule,
    )
    if mode < 0.4:
        # the solver's own serialization edges (mostly-accepting population)
        plan.compute_dependencies()
    else:
        # arbitrary edges, including backward and cyclic ones
        deps = {name: [] for name in names}
        for name in names:
            for other in names:
                if other != name and rng.random() < 0.25:
                    deps[name].append(other)
        plan.dependencies = deps
    return names, plan


# ---------------------------------------------------------------------------
# brute-force oracle (independent algorithms)
# ---------------------------------------------------------------------------

def oracle_accepts(names, plan) -> bool:
    running = set(names)

    # group condensation: set-merge to a fixpoint (no union-find)
    groups = [set(g) & running for g in (plan.coschedule or [])]
    groups = [g for g in groups if g]
    changed = True
    while changed:
        changed = False
        for i in range(len(groups)):
            for j in range(i + 1, len(groups)):
                if groups[i] & groups[j]:
                    groups[i] |= groups.pop(j)
                    changed = True
                    break
            if changed:
                break
    group_of = {}
    for gid, g in enumerate(groups):
        for m in g:
            group_of[m] = gid
    for i, name in enumerate(sorted(running)):
        group_of.setdefault(name, len(groups) + i)

    # groupmate dependency
    for name in running:
        for d in plan.dependencies.get(name, ()):
            if d in running and d != name and group_of[d] == group_of[name]:
                return False

    # condensed edges + Kahn's toposort for cycles
    nodes = sorted(set(group_of[n] for n in running))
    edges = set()
    for name in running:
        for d in plan.dependencies.get(name, ()):
            if d in running and group_of[d] != group_of[name]:
                edges.add((group_of[name], group_of[d]))
    indeg = {u: 0 for u in nodes}
    for u, v in edges:
        indeg[v] += 1
    queue = [u for u in nodes if indeg[u] == 0]
    seen = 0
    while queue:
        u = queue.pop()
        seen += 1
        for (a, b) in edges:
            if a == u:
                indeg[b] -= 1
                if indeg[b] == 0:
                    queue.append(b)
    if seen != len(nodes):
        return False  # cycle

    # Floyd-Warshall transitive closure over condensed nodes
    reach = {u: {v: (u, v) in edges for v in nodes} for u in nodes}
    for k in nodes:
        for i in nodes:
            if reach[i][k]:
                for j in nodes:
                    if reach[k][j]:
                        reach[i][j] = True

    # pairwise overlap race (manual interval arithmetic, not Block.overlaps)
    named = sorted(running)
    for i, n1 in enumerate(named):
        a1 = plan.assignments.get(n1)
        if a1 is None:
            continue
        for n2 in named[i + 1:]:
            a2 = plan.assignments.get(n2)
            if a2 is None:
                continue
            lo = max(a1.block.offset, a2.block.offset)
            hi = min(a1.block.offset + a1.block.size,
                     a2.block.offset + a2.block.size)
            if hi <= lo:
                continue
            g1, g2 = group_of[n1], group_of[n2]
            if g1 == g2:
                continue
            if not reach[g1][g2] and not reach[g2][g1]:
                return False  # race
    return True


def static_accepts(names, plan) -> bool:
    return not plan_verifier.launch_diagnostics(names, plan)


# ---------------------------------------------------------------------------
# differential sweep
# ---------------------------------------------------------------------------

def test_static_verifier_matches_oracle_on_1000_plans():
    rng = random.Random(0x5A7A)
    accepted = rejected = 0
    for i in range(N_PLANS):
        names, plan = gen_plan(rng)
        want = oracle_accepts(names, plan)
        got = static_accepts(names, plan)
        assert got == want, (
            f"case {i}: oracle {'accepts' if want else 'rejects'} but "
            f"verifier {'accepts' if got else 'rejects'}: "
            f"deps={plan.dependencies} coschedule={plan.coschedule} "
            f"blocks={{n: (a.block.offset, a.block.size) for n, a in plan.assignments.items()}}"
        )
        accepted += want
        rejected += not want
    # the generator must exercise both verdicts heavily, or the test is void
    assert accepted >= 200 and rejected >= 200, (accepted, rejected)


try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    @given(st.integers(min_value=0, max_value=2**63 - 1))
    @settings(max_examples=200, deadline=None)
    def test_static_verifier_matches_oracle_hypothesis(seed):
        names, plan = gen_plan(random.Random(seed))
        assert static_accepts(names, plan) == oracle_accepts(names, plan)
except ImportError:
    pass  # seeded sweep above already covers the 1000-plan floor


# ---------------------------------------------------------------------------
# dynamic-guard agreement (engine.execute end-to-end on a subsample)
# ---------------------------------------------------------------------------

class FakeDev:
    pass


def topo8():
    return SliceTopology([FakeDev() for _ in range(8)])


def _fake_tasks(plan):
    from saturn_tpu.core.strategy import Strategy
    from saturn_tpu.core.technique import BaseTechnique

    class Tech(BaseTechnique):
        name = "fake"

        def __init__(self):
            self.calls = []
            self.lock = threading.Lock()

        def execute(self, task, devices, tid, override_batch_count=None):
            with self.lock:
                self.calls.append(task.name)

        def search(self, task, devices, tid):
            return {}, 0.001

    class FakeTask:
        def __init__(self, name, size):
            self.name = name
            self.total_batches = 1
            self.current_batch = 0
            self.epoch_length = 1000
            self.tech = Tech()
            self.strategies = {size: Strategy(self.tech, size, {}, 0.001, 0.001)}
            self.selected_strategy = None

        def select_strategy(self, g):
            self.selected_strategy = self.strategies[g]

        def reconfigure(self, n):
            self.current_batch = (self.current_batch + n) % self.epoch_length

    return [FakeTask(name, a.apportionment)
            for name, a in plan.assignments.items()]


def test_dynamic_guard_agrees_on_subsample():
    """engine.execute (the pre-refactor ground truth, running the real
    launcher threads) raises exactly when the static verifier rejects."""
    from saturn_tpu.executor import engine

    rng = random.Random(0xD1FF)
    ran = 0
    while ran < N_ENGINE_SUBSAMPLE:
        names, plan = gen_plan(rng)
        if plan.coschedule:
            # group launch needs real technique support; the static/dynamic
            # coschedule agreement is pinned by tests/test_coschedule.py
            continue
        ran += 1
        tasks = _fake_tasks(plan)
        batches = {n: 1 for n in names}
        accepts = static_accepts(names, plan)
        if accepts:
            engine.execute(tasks, batches, 10.0, plan, topo8())
            assert all(t.tech.calls for t in tasks)
        else:
            with pytest.raises(RuntimeError):
                engine.execute(tasks, batches, 10.0, plan, topo8())
            assert not any(t.tech.calls for t in tasks)
