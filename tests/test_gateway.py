"""Network gateway: wire protocol, idempotent retries, netchaos acceptance.

Everything runs against the real service loop on the 8 virtual CPU devices
from conftest, with real TCP sockets on loopback. The acceptance campaign at
the bottom is the ISSUE's scenario: seeds × wire-fault classes (connection
drops, duplicated/reordered frames, partial writes, mid-ACK kills) plus a
gateway kill-and-restart against the same journal, asserting **zero lost
jobs, zero duplicate admissions**, and surviving jobs' journaled
trajectories identical to an in-process run of the same mix.
"""

import threading
import time

import pytest

from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.durability.recovery import replay_service_state
from saturn_tpu.resilience.crash import CrashInjector
from saturn_tpu.resilience.netchaos import (
    NET_FAULT_CLASSES,
    NetChaosProxy,
    NetChaosSpec,
    single_fault_spec,
)
from saturn_tpu.service import (
    GatewayClient,
    GatewayError,
    GatewayServer,
    SaturnService,
    ServiceClient,
)
from saturn_tpu.service.gateway import protocol

pytestmark = pytest.mark.gateway


class FakeDev:
    pass


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


class RecordingTech(BaseTechnique):
    """Sleeps per batch; records (task, block-size) launches."""

    name = "gw-fake"

    def __init__(self, per_batch=0.001):
        self.per_batch = per_batch
        self.calls = []
        self.lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        with self.lock:
            self.calls.append((task.name, len(devices)))
        time.sleep(self.per_batch * (override_batch_count or 1))

    def search(self, task, devices, tid):
        return {}, self.per_batch


class FakeTask:
    """Duck-typed pre-profiled task (admission skips the trial sweep)."""

    def __init__(self, name, total_batches, sizes, tech, pbt=0.001):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = 1000
        self.hints = {}
        self.chip_range = None
        self.strategies = {
            g: Strategy(tech, g, {}, pbt * total_batches, pbt) for g in sizes
        }
        self.selected_strategy = None

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length


def _provider(tech):
    """The one task-rebuild contract serving both wire submits and crash
    recovery: payload -> fresh FakeTask."""

    def provide(payload):
        return FakeTask(
            payload["task"], payload["remaining_batches"],
            payload["spec"]["sizes"], tech, pbt=0.004,
        )

    return provide


def _service(tech, wal=None, barrier=None, start=True, **kw):
    svc = SaturnService(
        topology=topo(8), interval=0.2, poll_s=0.02,
        durability_dir=wal, task_provider=_provider(tech),
        crash_barrier=barrier, health_guardian=False, **kw,
    )
    return svc.start() if start else svc


SPEC = {"sizes": [4, 8]}


# ----------------------------------------------------------------- protocol
class TestProtocol:
    def test_frame_roundtrip(self):
        frame = {"op": "submit", "rid": "s:1", "job": {"name": "a"}}
        assert protocol.decode_frame(protocol.encode_frame(frame)) == frame

    def test_decode_rejects_garbage_and_non_objects(self):
        for raw in (b"not json\n", b"[1,2]\n", b"\xff\xfe\n"):
            with pytest.raises(GatewayError) as ei:
                protocol.decode_frame(raw)
            assert ei.value.code == protocol.GW_BADFRAME

    def test_oversized_frame_refused_both_ways(self):
        big = {"op": "submit", "blob": "x" * protocol.MAX_FRAME_BYTES}
        with pytest.raises(GatewayError):
            protocol.encode_frame(big)
        with pytest.raises(GatewayError):
            protocol.decode_frame(b"x" * (protocol.MAX_FRAME_BYTES + 1))

    def test_error_codes_are_closed(self):
        with pytest.raises(ValueError):
            GatewayError("GW_NOT_A_CODE")

    def test_error_round_trips_losslessly(self):
        for code in protocol.ERROR_CODES:
            e = GatewayError(code, "why it failed", retry_after_s=(
                0.25 if code == protocol.GW_RETRY_AFTER else None))
            back = GatewayError.from_wire(e.to_wire())
            assert back.code == e.code
            assert back.message == e.message
            assert back.retriable == e.retriable
            assert back.retry_after_s == e.retry_after_s

    def test_from_wire_tolerates_malformed_payloads(self):
        for payload in (None, "boom", {"code": "GW_NOPE", "message": "m"}):
            e = GatewayError.from_wire(payload)
            assert e.code == protocol.GW_INTERNAL

    def test_retriable_defaults_follow_the_code_class(self):
        assert GatewayError(protocol.GW_RETRY_AFTER).retriable
        assert GatewayError(protocol.GW_DRAINING).retriable
        assert not GatewayError(protocol.GW_DUPLICATE_NAME).retriable
        assert not GatewayError(protocol.GW_INTERNAL).retriable

    def test_classify_maps_service_exceptions_to_typed_codes(self):
        dup = ValueError("task name 'a' is already live as j0001-a")
        assert (protocol.classify_exception(dup).code
                == protocol.GW_DUPLICATE_NAME)
        assert (protocol.classify_exception(KeyError("unknown job id")).code
                == protocol.GW_UNKNOWN_JOB)
        assert (protocol.classify_exception(ValueError("bad field")).code
                == protocol.GW_BADREQUEST)
        internal = protocol.classify_exception(RuntimeError("boom"))
        assert internal.code == protocol.GW_INTERNAL
        assert "RuntimeError" in internal.message


# ------------------------------------------------------------ basic surface
class TestGatewaySurface:
    def test_submit_wait_status_cancel_over_the_wire(self, tmp_path):
        tech = RecordingTech()
        svc = _service(tech, wal=str(tmp_path / "wal"))
        gw = GatewayServer(svc).start()
        try:
            with GatewayClient(*gw.address, seed=1) as c:
                jid = c.submit(name="wire-a", total_batches=5, spec=SPEC)
                snap = c.status(jid)
                assert snap["job_id"] == jid and snap["task"] == "wire-a"
                done = c.wait(jid, timeout=60)
                assert done["state"] == "DONE"
                # cancel an already-terminal job -> False, like ServiceClient
                assert c.cancel(jid) is False
                assert c.ping()["pong"] is True
        finally:
            gw.shutdown(reason="test")
            svc.stop(abort=True, timeout=30)

    def test_duplicate_live_name_is_a_typed_wire_error(self, tmp_path):
        tech = RecordingTech()
        svc = _service(tech, wal=str(tmp_path / "wal"))
        gw = GatewayServer(svc).start()
        try:
            with GatewayClient(*gw.address, seed=2) as c:
                c.submit(name="dup-name", total_batches=50, spec=SPEC)
                with pytest.raises(GatewayError) as ei:
                    c.submit(name="dup-name", total_batches=5, spec=SPEC)
                assert ei.value.code == protocol.GW_DUPLICATE_NAME
                assert not ei.value.retriable
        finally:
            gw.shutdown(reason="test")
            svc.stop(abort=True, timeout=30)

    def test_unknown_job_and_bad_op_errors(self, tmp_path):
        tech = RecordingTech()
        svc = _service(tech, wal=str(tmp_path / "wal"))
        gw = GatewayServer(svc).start()
        try:
            with GatewayClient(*gw.address, seed=3) as c:
                with pytest.raises(GatewayError) as ei:
                    c.status("j9999-nope")
                assert ei.value.code == protocol.GW_UNKNOWN_JOB
                with pytest.raises(GatewayError) as ei:
                    c._call({"op": "frobnicate"})
                assert ei.value.code == protocol.GW_BADREQUEST
        finally:
            gw.shutdown(reason="test")
            svc.stop(abort=True, timeout=30)

    def test_same_dedup_key_returns_original_job_id(self, tmp_path):
        tech = RecordingTech()
        svc = _service(tech, wal=str(tmp_path / "wal"))
        gw = GatewayServer(svc).start()
        try:
            with GatewayClient(*gw.address, seed=4) as c:
                key = "retry:me:1"
                a = c.submit(name="idem", total_batches=5, spec=SPEC,
                             dedup_key=key)
                b = c.submit(name="idem", total_batches=5, spec=SPEC,
                             dedup_key=key)
                assert a == b
                assert gw.stats()["dedup_hits"] == 1
                assert c.wait(a, timeout=60)["state"] == "DONE"
        finally:
            gw.shutdown(reason="test")
            svc.stop(abort=True, timeout=30)


# ---------------------------------------------------- deadlines/backpressure
class TestAdmissionControls:
    def test_expired_request_deadline_sheds_before_admission(self, tmp_path):
        tech = RecordingTech()
        svc = _service(tech, wal=str(tmp_path / "wal"))
        gw = GatewayServer(svc).start()
        try:
            with GatewayClient(*gw.address, seed=5) as c:
                with pytest.raises(GatewayError) as ei:
                    c.submit(name="late", total_batches=5, spec=SPEC,
                             request_deadline_s=0.0)
                assert ei.value.code == protocol.GW_DEADLINE_EXPIRED
                assert gw.stats()["sheds"] == {"deadline_expired": 1}
                # the shed left no job behind
                assert all(r.name != "late" for r in svc.queue.jobs())
        finally:
            gw.shutdown(reason="test")
            svc.stop(abort=True, timeout=30)

    def test_global_window_backpressure_returns_retry_after(self, tmp_path):
        tech = RecordingTech()
        svc = _service(tech, wal=str(tmp_path / "wal"))
        gw = GatewayServer(svc, max_inflight=1).start()
        try:
            with GatewayClient(*gw.address, seed=6, max_attempts=1) as c:
                c.submit(name="bp-a", total_batches=2000, spec=SPEC)
                with pytest.raises(GatewayError) as ei:
                    c.submit(name="bp-b", total_batches=5, spec=SPEC)
                # one transport attempt: the raw verdict, not the retry loop
                e = ei.value
                assert e.code in (protocol.GW_RETRY_AFTER,
                                  protocol.GW_UNAVAILABLE)
                if e.code == protocol.GW_RETRY_AFTER:
                    assert e.retriable and e.retry_after_s > 0
        finally:
            gw.shutdown(reason="test")
            svc.stop(abort=True, timeout=30)

    def test_retry_after_clears_once_the_window_frees(self, tmp_path):
        tech = RecordingTech()
        svc = _service(tech, wal=str(tmp_path / "wal"))
        gw = GatewayServer(svc, max_inflight=1, retry_after_s=0.1).start()
        try:
            with GatewayClient(*gw.address, seed=7, max_attempts=30,
                               backoff_base_s=0.05) as c:
                a = c.submit(name="win-a", total_batches=3, spec=SPEC)
                # retries through GW_RETRY_AFTER until win-a completes
                b = c.submit(name="win-b", total_batches=3, spec=SPEC)
                assert c.wait(a, timeout=60)["state"] == "DONE"
                assert c.wait(b, timeout=60)["state"] == "DONE"
        finally:
            gw.shutdown(reason="test")
            svc.stop(abort=True, timeout=30)

    def test_pressure_shed_signal_shrinks_the_window(self, tmp_path):
        tech = RecordingTech()
        svc = _service(tech, wal=str(tmp_path / "wal"))
        gw = GatewayServer(svc, max_inflight=8,
                           pressure_window_factor=0.25).start()
        try:
            # Fake the service's deadline-pressure shedder having just fired:
            # effective window = max(1, 8*0.25) = 2.
            svc.last_pressure_shed = time.monotonic()
            with GatewayClient(*gw.address, seed=8, max_attempts=1) as c:
                c.submit(name="pw-a", total_batches=2000, spec=SPEC)
                c.submit(name="pw-b", total_batches=2000, spec=SPEC)
                with pytest.raises(GatewayError) as ei:
                    c.submit(name="pw-c", total_batches=5, spec=SPEC)
                assert ei.value.code in (protocol.GW_RETRY_AFTER,
                                         protocol.GW_UNAVAILABLE)
                assert "pressure-shrunk" in ei.value.message or \
                    ei.value.code == protocol.GW_UNAVAILABLE
        finally:
            gw.shutdown(reason="test")
            svc.stop(abort=True, timeout=30)

    def test_per_session_window(self, tmp_path):
        tech = RecordingTech()
        svc = _service(tech, wal=str(tmp_path / "wal"))
        gw = GatewayServer(svc, max_inflight=64,
                           max_inflight_per_session=1).start()
        try:
            with GatewayClient(*gw.address, seed=9, max_attempts=1) as c:
                c.submit(name="sw-a", total_batches=2000, spec=SPEC)
                with pytest.raises(GatewayError):
                    c.submit(name="sw-b", total_batches=5, spec=SPEC)
            # a different session still fits the global window
            with GatewayClient(*gw.address, seed=10, max_attempts=1) as c2:
                c2.submit(name="sw-c", total_batches=5, spec=SPEC)
        finally:
            gw.shutdown(reason="test")
            svc.stop(abort=True, timeout=30)


# -------------------------------------------------------------------- drain
class TestDrain:
    def test_drain_refuses_submits_flushes_and_journals_marker(self, tmp_path):
        wal = str(tmp_path / "wal")
        tech = RecordingTech()
        svc = _service(tech, wal=wal)
        gw = GatewayServer(svc).start()
        with GatewayClient(*gw.address, seed=11) as c:
            jid = c.submit(name="drain-a", total_batches=5, spec=SPEC)
            assert gw.shutdown(timeout=10.0, reason="SIGTERM") is True
            # live connection: in-flight work still answers, new work refused
            with pytest.raises(GatewayError) as ei:
                c.submit(name="drain-b", total_batches=5, spec=SPEC)
            assert ei.value.code in (protocol.GW_DRAINING,
                                     protocol.GW_UNAVAILABLE)
        svc.stop(abort=True, timeout=30)
        # durable handoff marker, with the ledger
        from saturn_tpu.durability import journal as jmod

        drains = [r for r in jmod.replay(wal) if r["kind"] == "gateway_drain"]
        assert len(drains) == 1
        d = drains[0]["data"]
        assert d["reason"] == "SIGTERM" and d["clean"] is True
        assert d["dedup_entries"] == 1
        assert jid  # the admitted job survived in the journal too
        state = replay_service_state(wal)
        assert jid in state.jobs
        # Operator view: the analysis CLI reads the same ledger back.
        import json

        from saturn_tpu.analysis import cli as acli

        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = acli.main(["--json", "gateway", wal])
        assert rc == 0
        view = json.loads(buf.getvalue())
        assert view["submitted"] == 1
        assert view["dedup_entries"] == 1
        assert view["last_drain_clean"] is True
        assert view["drains"][0]["reason"] == "SIGTERM"

    def test_wait_drained_blocks_until_marker_journaled(self, tmp_path):
        """The SIGTERM pattern: shutdown on a separate thread, the host
        waits on wait_drained() — by the time it returns, the durable
        marker must already be in the journal."""
        wal = str(tmp_path / "wal")
        tech = RecordingTech()
        svc = _service(tech, wal=wal)
        gw = GatewayServer(svc).start()
        assert gw.wait_drained(timeout=0.05) is False  # not draining yet
        t = threading.Thread(
            target=gw.shutdown, kwargs={"reason": "SIGTERM"}, daemon=True
        )
        t.start()
        assert gw.wait_drained(timeout=10.0) is True
        from saturn_tpu.durability import journal as jmod

        kinds = [r["kind"] for r in jmod.replay(wal)]
        assert "gateway_drain" in kinds
        t.join(timeout=5.0)
        svc.stop(abort=True, timeout=30)

    def test_new_connections_refused_while_draining(self, tmp_path):
        tech = RecordingTech()
        svc = _service(tech, wal=str(tmp_path / "wal"))
        gw = GatewayServer(svc).start()
        gw.shutdown(timeout=5.0, reason="test")
        with pytest.raises(GatewayError) as ei:
            GatewayClient(*gw.address, seed=12, max_attempts=2,
                          timeout_s=1.0, backoff_base_s=0.01).ping()
        assert ei.value.code == protocol.GW_UNAVAILABLE
        svc.stop(abort=True, timeout=30)


# -------------------------------------------------------------- kill-replay
@pytest.mark.crash
class TestKillReplay:
    def test_ack_cut_by_gateway_kill_recovers_idempotently(self, tmp_path):
        """The canonical lost-ACK crash: the submit's journal commit lands,
        the crash harness kills the gateway before the ACK frame is written,
        and the client's retry AGAINST THE NEXT INCARNATION (same journal)
        gets the original job id — exactly-once across restarts."""
        wal = str(tmp_path / "wal")
        tech = RecordingTech()
        # No service loop: the submit path needs only queue+journal, and an
        # idle loop would race the injector for barrier crossings.
        inj = CrashInjector("post-commit", hit=1, armed=False)
        svc = _service(tech, wal=wal, barrier=inj.barrier, start=False)
        gw = GatewayServer(svc).start()
        key = "kill:me:1"
        inj.arm()
        with pytest.raises(GatewayError) as ei:
            GatewayClient(*gw.address, session="killer", seed=13,
                          max_attempts=2, timeout_s=2.0,
                          backoff_base_s=0.01).submit(
                name="kill-a", total_batches=4, spec=SPEC, dedup_key=key)
        assert ei.value.code == protocol.GW_UNAVAILABLE
        assert inj.fired.is_set() and gw.killed
        # The admission was durable before the kill point...
        state = replay_service_state(wal)
        assert state.dedup.get(key) is not None
        original = state.dedup[key]
        # ...so the next incarnation answers the retry with the original id.
        tech2 = RecordingTech()
        svc2 = _service(tech2, wal=wal)
        gw2 = GatewayServer(svc2).start()
        try:
            with GatewayClient(*gw2.address, session="killer", seed=13) as c:
                jid = c.submit(name="kill-a", total_batches=4, spec=SPEC,
                               dedup_key=key)
                assert jid == original
                assert c.wait(jid, timeout=60)["state"] == "DONE"
            # exactly one admission for the key across both incarnations
            final = replay_service_state(wal)
            submitted = [j for j in final.jobs.values()
                         if j.dedup_key == key]
            assert len(submitted) == 1 and submitted[0].job_id == original
        finally:
            gw2.shutdown(reason="test")
            svc2.stop(abort=True, timeout=30)


# ------------------------------------------------------- netchaos acceptance
def _trajectory(wal):
    """A run's journaled outcome, in comparison form: per job name, the
    final lifecycle state and the durably realized batches. Two runs of the
    same mix must produce identical trajectories — same jobs, same
    verdicts, same amount of work, no phantom admissions."""
    state = replay_service_state(wal)
    out = {}
    for j in state.jobs.values():
        assert j.task not in out, f"duplicate admission for {j.task}"
        out[j.task] = (j.state, j.realized, j.total_batches)
    return out


def _job_mix(n=6):
    return [(f"mix-{i}", 3 + (i % 3)) for i in range(n)]


def _run_in_process(wal, mix):
    """Reference run: the same job mix through the in-process client."""
    tech = RecordingTech()
    svc = _service(tech, wal=wal)
    try:
        client = ServiceClient(svc)
        ids = [client.submit(FakeTask(name, total, SPEC["sizes"], tech,
                                      pbt=0.004),
                             spec={"sizes": SPEC["sizes"]})
               for name, total in mix]
        for jid in ids:
            assert client.wait(jid, timeout=60)["state"] == "DONE"
    finally:
        svc.stop(timeout=60)
    return _trajectory(wal)


def _run_through_chaos(wal, mix, spec):
    """Same mix, but over TCP through the seeded chaos proxy."""
    tech = RecordingTech()
    svc = _service(tech, wal=wal)
    gw = GatewayServer(svc).start()
    try:
        with NetChaosProxy(*gw.address, spec) as px:
            with GatewayClient(*px.address, seed=spec.seed,
                               timeout_s=5.0, max_attempts=10) as c:
                ids = [c.submit(name=name, total_batches=total, spec=SPEC)
                       for name, total in mix]
                for jid in ids:
                    assert c.wait(jid, timeout=90)["state"] == "DONE", jid
            stats = px.stats
    finally:
        gw.shutdown(reason="campaign")
        svc.stop(timeout=60)
    return _trajectory(wal), stats


@pytest.mark.slow
@pytest.mark.parametrize("seed", [11, 23, 47])
def test_netchaos_campaign_zero_lost_zero_duplicated(seed, tmp_path):
    """Seeds × fault classes: every class injected at least somewhere across
    the sweep, and for every (seed, class) cell the chaos run's trajectory
    equals the clean in-process reference — zero lost jobs, zero duplicate
    admissions, same realized work."""
    mix = _job_mix()
    reference = _run_in_process(str(tmp_path / "ref"), mix)
    assert all(st == "DONE" and r >= t for st, r, t in reference.values())
    injected_anywhere = {}
    for fc in ("drop", "dup", "partial", "kill_ack"):
        wal = str(tmp_path / f"chaos-{fc}")
        spec = single_fault_spec(seed=seed, fault_class=fc,
                                 max_faults_per_conn=2)
        trajectory, stats = _run_through_chaos(wal, mix, spec)
        assert trajectory == reference, (
            f"{fc}: chaos trajectory diverged from the in-process reference"
        )
        for k, v in stats.injected.items():
            injected_anywhere[k] = injected_anywhere.get(k, 0) + v
    assert set(injected_anywhere) == {"drop", "dup", "partial", "kill_ack"}, (
        f"campaign never injected some classes: {injected_anywhere}"
    )


@pytest.mark.slow
def test_netchaos_mixed_faults_with_gateway_kill_and_restart(tmp_path):
    """The full acceptance scenario: mixed wire faults AND a gateway kill
    mid-campaign, restart against the same journal, campaign completes with
    zero lost and zero duplicated jobs."""
    mix = _job_mix(5)
    reference = _run_in_process(str(tmp_path / "ref"), mix)
    wal = str(tmp_path / "chaos")
    spec = NetChaosSpec(seed=31, fault_rate=0.3, max_faults_per_conn=2)

    tech = RecordingTech()
    inj = CrashInjector("post-commit", hit=2, armed=True)
    svc = _service(tech, wal=wal, barrier=inj.barrier, start=False)
    gw = GatewayServer(svc).start()
    survivors = {}
    with NetChaosProxy(*gw.address, spec) as px:
        c = GatewayClient(*px.address, session="camp", seed=spec.seed,
                          timeout_s=3.0, max_attempts=3,
                          backoff_base_s=0.02)
        keys = {name: f"camp:{name}" for name, _ in mix}
        for name, total in mix:
            try:
                survivors[name] = c.submit(
                    name=name, total_batches=total, spec=SPEC,
                    dedup_key=keys[name])
            except GatewayError:
                pass  # lost to the kill window — retried after restart
        c.close()
    assert inj.fired.is_set() and gw.killed  # the kill landed mid-campaign

    # Restart: same journal, fresh service+gateway; the client retries every
    # submit with its original dedup key, then drives all jobs to DONE.
    tech2 = RecordingTech()
    svc2 = _service(tech2, wal=wal)
    gw2 = GatewayServer(svc2).start()
    try:
        with NetChaosProxy(*gw2.address, spec) as px2:
            with GatewayClient(*px2.address, session="camp", seed=spec.seed,
                               timeout_s=5.0, max_attempts=10) as c2:
                ids = {}
                for name, total in mix:
                    ids[name] = c2.submit(name=name, total_batches=total,
                                          spec=SPEC, dedup_key=keys[name])
                for name, jid in sorted(ids.items()):
                    # idempotency across the kill: pre-kill admissions keep
                    # their ids through the retry
                    if name in survivors:
                        assert jid == survivors[name], name
                    assert c2.wait(jid, timeout=90)["state"] == "DONE", name
    finally:
        gw2.shutdown(reason="campaign")
        svc2.stop(timeout=60)

    trajectory = _trajectory(wal)   # asserts zero duplicate admissions
    assert trajectory == reference  # zero lost, same realized work


# ----------------------------------------------------------- session resume
def test_session_resume_after_reconnect(tmp_path):
    tech = RecordingTech()
    svc = _service(tech, wal=str(tmp_path / "wal"))
    gw = GatewayServer(svc, max_inflight_per_session=1).start()
    try:
        c = GatewayClient(*gw.address, session="resume-me", seed=14,
                          max_attempts=1)
        c.submit(name="rs-a", total_batches=2000, spec=SPEC)
        c.close()
        # a NEW connection with the SAME session id inherits the window
        c2 = GatewayClient(*gw.address, session="resume-me", seed=15,
                           max_attempts=1)
        with pytest.raises(GatewayError):
            c2.submit(name="rs-b", total_batches=5, spec=SPEC)
        c2.close()
        assert gw.stats()["sessions"] == 1
    finally:
        gw.shutdown(reason="test")
        svc.stop(abort=True, timeout=30)
