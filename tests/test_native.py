"""Native (C++) components: SPASE scheduler and corpus tokenizer.

These run without hardware; the toolchain (g++) is in-image, so the native
path is expected to build. Fallback behavior is tested by monkeypatching the
loader, not by uninstalling the compiler.
"""

import os

import numpy as np
import pytest

from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.solver import milp, native_sched


class FakeTask:
    def __init__(self, name, strategies):
        self.name = name
        self.strategies = strategies

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}


def mk_task(name, table):
    """table: {size: runtime}"""
    return FakeTask(
        name,
        {g: Strategy(object(), g, {}, rt, per_batch_time=rt) for g, rt in table.items()},
    )


def topo8():
    return SliceTopology(devices=list(range(8)))


def check_plan_valid(plan, capacity=8):
    items = list(plan.assignments.values())
    for i, a in enumerate(items):
        assert a.start >= -1e-9
        assert a.block.end <= capacity
        for b in items[i + 1 :]:
            if a.block.overlaps(b.block):
                assert (
                    a.start + a.runtime <= b.start + 1e-6
                    or b.start + b.runtime <= a.start + 1e-6
                ), "overlapping tasks share devices"


class TestNativeScheduler:
    def test_available(self):
        assert native_sched.available(), "libspase failed to build"

    def test_small_instance_valid_and_tight(self):
        # 4 tasks that perfectly pack 8 devices in parallel -> makespan 10.
        tasks = [mk_task(f"t{i}", {2: 10.0, 4: 6.0}) for i in range(4)]
        plan = native_sched.solve_native(tasks, topo8(), time_limit=0.5)
        assert plan is not None
        check_plan_valid(plan)
        # optimum: all four run 2-chip in parallel -> makespan 10 (the greedy
        # constructor's myopic 4-chip pick gives 13; option-pinning moves in
        # the local search must find the parallel packing).
        assert plan.makespan <= 10.0 + 1e-6
        assert set(plan.assignments) == {f"t{i}" for i in range(4)}

    def test_never_worse_than_python_greedy(self):
        rng = np.random.default_rng(7)
        for trial in range(5):
            tasks = []
            for i in range(8):
                sizes = [1, 2, 4]
                tasks.append(
                    mk_task(
                        f"t{trial}_{i}",
                        {s: float(rng.uniform(1, 20)) for s in sizes},
                    )
                )
            # ordering_slack=0 to match greedy_plan's unpadded packing
            nat = native_sched.solve_native(
                tasks, topo8(), time_limit=0.3, ordering_slack=0.0
            )
            gre = milp.greedy_plan(tasks, topo8())
            assert nat is not None
            check_plan_valid(nat)
            assert nat.makespan <= gre.makespan + 1e-6

    def test_constructor_equivalence_with_python(self):
        """Property test (VERDICT r2 weak #6): with the local search disabled
        (time_limit=0) the native path is exactly the LPT constructor, which
        must agree with ``greedy_plan`` — both are the shared DeviceTimeline
        earliest-free-slot rule, same order, same min-finish option choice —
        on makespan AND per-task (option, start), across random instances and
        slack values."""
        rng = np.random.default_rng(42)
        for trial in range(10):
            slack = float(rng.choice([0.0, 0.5, 1.0, 3.0]))
            n = int(rng.integers(2, 12))
            tasks = []
            for i in range(n):
                sizes = [int(s) for s in rng.choice([1, 2, 4, 8], size=rng.integers(1, 4), replace=False)]
                tasks.append(
                    mk_task(
                        f"e{trial}_{i}",
                        {s: float(np.round(rng.uniform(1, 30), 3)) for s in sizes},
                    )
                )
            nat = native_sched.solve_native(
                tasks, topo8(), time_limit=0.0, ordering_slack=slack
            )
            gre = milp.greedy_plan(tasks, topo8(), ordering_slack=slack)
            assert nat is not None
            assert nat.makespan == pytest.approx(gre.makespan, abs=1e-9)
            for name, ga in gre.assignments.items():
                na = nat.assignments[name]
                assert (na.apportionment, na.block.offset) == (
                    ga.apportionment,
                    ga.block.offset,
                ), f"{name}: option diverged under slack={slack}"
                assert na.start == pytest.approx(ga.start, abs=1e-9)

    def test_large_batch_routes_to_native(self):
        tasks = [mk_task(f"t{i}", {1: 5.0, 2: 3.0}) for i in range(16)]
        plan = milp.solve(tasks, topo8(), time_limit=2.0)
        check_plan_valid(plan)
        assert len(plan.assignments) == 16
        # 16 tasks on 8 devices, each >= 3s of 2-chip work (or 5s 1-chip):
        # lower bound on makespan is total_work/8 = 16*5/8 = 10 for 1-chip
        # or 16*6/8 = 12 for 2-chip; just require a sane, finite result.
        assert 0 < plan.makespan < 200

    def test_capacity_error_names_task_large_batch(self):
        """A task profiled only above capacity must raise the clear ValueError
        on the native large-batch path too, not an opaque greedy crash."""
        tasks = [mk_task(f"t{i}", {1: 5.0}) for i in range(13)]
        tasks.append(mk_task("too-big", {16: 5.0}))
        with pytest.raises(ValueError, match="too-big"):
            milp.solve(tasks, topo8(), time_limit=1.0)

    def test_fallback_when_native_missing(self, monkeypatch):
        monkeypatch.setattr(native_sched, "_FN", False)
        assert native_sched.solve_native([], topo8()) is None
        tasks = [mk_task(f"t{i}", {1: 5.0}) for i in range(14)]
        plan = milp.solve(tasks, topo8(), time_limit=1.0)  # > milp_task_limit
        check_plan_valid(plan)
        assert len(plan.assignments) == 14
        monkeypatch.setattr(native_sched, "_FN", None)  # reset lazy cache


SAMPLE = """The quick brown fox jumps over the lazy dog.
The dog, surprisingly, did not mind; the fox did it again!
"""


class TestNativeTokenizer:
    def test_native_matches_python(self, tmp_path):
        from saturn_tpu.data.lm_dataset import _word_tokenize_python, word_tokenize_file

        p = tmp_path / "corpus.txt"
        p.write_text(SAMPLE * 3)
        ids, vocab = word_tokenize_file(str(p), max_vocab=64, cache_dir=str(tmp_path / "c1"))
        py_ids, py_vocab = _word_tokenize_python((SAMPLE * 3).encode(), 64)
        assert vocab == py_vocab
        np.testing.assert_array_equal(ids, py_ids)
        assert ids.dtype == np.int32
        # 'the' is the most frequent token -> id 2 (after pad/unk)
        assert ids[0] == 2

    def test_non_ascii_parity(self, tmp_path):
        """Multi-byte UTF-8 must tokenize identically on both paths (bytes
        split into single-byte tokens; ASCII-only lowercasing)."""
        from saturn_tpu.data.lm_dataset import _word_tokenize_python, word_tokenize_file

        text = "Café déjà-vu naïve Straße — twice! Café déjà-vu.\n" * 4
        p = tmp_path / "utf8.txt"
        p.write_text(text, encoding="utf-8")
        ids, vocab = word_tokenize_file(str(p), max_vocab=128, cache_dir=str(tmp_path / "cx"))
        py_ids, py_vocab = _word_tokenize_python(text.encode("utf-8"), 128)
        assert vocab == py_vocab
        np.testing.assert_array_equal(ids, py_ids)

    def test_unk_capping(self, tmp_path):
        from saturn_tpu.data.lm_dataset import word_tokenize_file

        p = tmp_path / "corpus.txt"
        p.write_text(SAMPLE)
        ids, vocab = word_tokenize_file(str(p), max_vocab=5, cache_dir=str(tmp_path / "c2"))
        assert vocab == 5
        assert (ids == 1).any()  # rare tokens mapped to <unk>
        assert ids.max() <= 4

    def test_cache_hit(self, tmp_path):
        from saturn_tpu.data.lm_dataset import word_tokenize_file

        p = tmp_path / "corpus.txt"
        p.write_text(SAMPLE)
        cache = str(tmp_path / "c3")
        a, va = word_tokenize_file(str(p), max_vocab=64, cache_dir=cache)
        b, vb = word_tokenize_file(str(p), max_vocab=64, cache_dir=cache)
        np.testing.assert_array_equal(a, b)
        assert va == vb


class TestInterleavedEncodeCache:
    """ADVICE r4: the native encode cache is keyed per (path, max_vocab) —
    interleaved count/fill call pairs for different corpora (or vocab caps)
    must each hit their own cached build and return correct streams."""

    def test_interleaved_corpora_and_vocab_caps(self, tmp_path):
        import ctypes

        from saturn_tpu import native

        lib = native.load("tokenize")
        if lib is None:
            pytest.skip("native tokenize unavailable")
        fn = lib.word_tokenize_file
        fn.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_long,
            ctypes.POINTER(ctypes.c_int),
        ]
        fn.restype = ctypes.c_long

        pa = tmp_path / "a.txt"
        pb = tmp_path / "b.txt"
        pa.write_text("alpha beta gamma alpha beta alpha\n" * 50)
        pb.write_text("delta epsilon delta zeta eta theta iota\n" * 50)

        def count(p, mv):
            return fn(str(p).encode(), mv, None, None, 0, None)

        def fill(p, mv, n):
            ids = np.empty(n, dtype=np.int32)
            vs = ctypes.c_int()
            got = fn(
                str(p).encode(), mv, None,
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                n, ctypes.byref(vs),
            )
            assert got == n
            return ids, vs.value

        # Interleave: count(a), count(b), count(a@small-vocab), then fill
        # all three — every pair must resolve from its own cache entry.
        na = count(pa, 64)
        nb = count(pb, 64)
        na_small = count(pa, 4)
        assert na == na_small == 50 * 6 and nb == 50 * 7
        ids_b, vs_b = fill(pb, 64, nb)
        ids_a, vs_a = fill(pa, 64, na)
        ids_a4, vs_a4 = fill(pa, 4, na_small)
        assert vs_a == 5 and vs_b == 8  # distinct words + pad/unk
        assert vs_a4 == 4
        assert (ids_a4 == 1).any()  # capped vocab -> <unk> pressure
        assert ids_a.max() < vs_a and ids_b.max() < vs_b
        # id streams differ between the corpora (cache didn't cross wires)
        assert len(ids_a) != len(ids_b) or (ids_a[: len(ids_b)] != ids_b).any()


class TestCorpusGen:
    """WikiText-scale corpus synthesis (data/corpus_gen.py) — small sizes
    here; benchmarks/tokenizer_bench.py runs the 100MB+ flow."""

    def test_generates_requested_size_and_type_count(self, tmp_path):
        from saturn_tpu.data.corpus_gen import generate_corpus

        out = str(tmp_path / "corpus.txt")
        info = generate_corpus(out, size_mb=1.0, n_extra_types=5000)
        size = os.path.getsize(out)
        assert 0.9e6 <= size <= 1.3e6
        assert info["bytes"] == size and info["types"] > 5000

    def test_deterministic_and_idempotent(self, tmp_path):
        from saturn_tpu.data.corpus_gen import generate_corpus

        a, b = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")
        generate_corpus(a, size_mb=0.2, n_extra_types=500, seed=7)
        generate_corpus(b, size_mb=0.2, n_extra_types=500, seed=7)
        with open(a) as fa, open(b) as fb:
            assert fa.read() == fb.read()
        # second call on an existing big-enough file skips regeneration and
        # reports the sidecar's true counts (ADVICE r4: not None)
        info = generate_corpus(a, size_mb=0.2, n_extra_types=500, seed=7)
        assert info.get("reused") and info["tokens"] > 0 and info["types"] > 0

    def test_param_change_regenerates(self, tmp_path):
        """ADVICE r4: a same-size corpus written with different generation
        parameters must not be silently reused."""
        from saturn_tpu.data.corpus_gen import generate_corpus

        out = str(tmp_path / "a.txt")
        generate_corpus(out, size_mb=0.2, n_extra_types=500, seed=7)
        with open(out) as f:
            body_seed7 = f.read()
        info = generate_corpus(out, size_mb=0.2, n_extra_types=500, seed=8)
        assert not info.get("reused")
        with open(out) as f:
            assert f.read() != body_seed7
        # missing sidecar (pre-existing file of unknown provenance) -> rebuild
        os.remove(out + ".meta.json")
        info = generate_corpus(out, size_mb=0.2, n_extra_types=500, seed=8)
        assert not info.get("reused") and info["tokens"] > 0

    def test_feeds_word_vocab_with_unk_pressure(self, tmp_path):
        """Generated text drives a capped vocab build end to end: more
        types than the cap -> real <unk>s, ids within range."""
        from saturn_tpu.data.corpus_gen import generate_corpus
        from saturn_tpu.data.lm_dataset import word_tokenize_file

        out = str(tmp_path / "corpus.txt")
        generate_corpus(out, size_mb=0.5, n_extra_types=3000)
        ids, vocab = word_tokenize_file(
            out, max_vocab=1024, cache_dir=str(tmp_path / "cache")
        )
        assert vocab == 1024
        assert (ids == 1).any()          # <unk> pressure exists
        assert 0 < ids.max() < 1024
        assert len(ids) > 50_000

    def test_dataset_integration(self, tmp_path):
        from saturn_tpu.data.lm_dataset import make_lm_dataset

        p = tmp_path / "corpus.txt"
        p.write_text(SAMPLE * 40)
        ds = make_lm_dataset(
            context_length=16, batch_size=4, vocab_size=128,
            corpus_path=str(p), tokenizer="word",
        )
        b = ds.batch(0)
        assert b.shape == (4, 16) and b.dtype == np.int32


class TestLocaleRobustness:
    def test_parity_under_utf8_ctype_locale(self, tmp_path):
        """ADVICE r1: classification must be ASCII-range, not std::ctype —
        a non-C LC_CTYPE must not change how bytes >= 0x80 tokenize."""
        import ctypes
        import ctypes.util

        from saturn_tpu.data.lm_dataset import (
            _word_tokenize_python,
            word_tokenize_file,
        )

        libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6")
        libc.setlocale.restype = ctypes.c_char_p
        LC_CTYPE = 0
        prev = libc.setlocale(LC_CTYPE, None)
        set_to = None
        for loc in (b"C.UTF-8", b"en_US.UTF-8"):
            if libc.setlocale(LC_CTYPE, loc):
                set_to = loc
                break
        if set_to is None:
            pytest.skip("no UTF-8 locale available on this host")
        try:
            text = "Müller naïve Σigma ß — weird bytes\n" * 6
            p = tmp_path / "loc.txt"
            p.write_text(text, encoding="utf-8")
            ids, vocab = word_tokenize_file(
                str(p), max_vocab=128, cache_dir=str(tmp_path / "cl")
            )
            py_ids, py_vocab = _word_tokenize_python(text.encode("utf-8"), 128)
            assert vocab == py_vocab
            np.testing.assert_array_equal(ids, py_ids)
        finally:
            libc.setlocale(LC_CTYPE, prev)
