"""Elastic scale-up + active defragmentation (round 24).

Hardware-free units plus two service-level integration runs:

- grow-flap hysteresis interplay with mid-interval blinks (the storm case
  the twin campaign exercises end-to-end);
- the occupancy-driven defrag planner (victim relocation, headroom math,
  fail-open without a capacity model, determinism);
- the ``GrowCoordinator`` (occupancy gate verdicts, opportunistic polling,
  guardian short-circuit, two-phase wave execution and its journal trail);
- admission ``revisit_on`` classes, the DEFER pool, and ``job_deferred``
  journal dedup;
- kill-replay at the three ``defrag.*`` kill-points: every
  ``migration_intent`` resolves exactly once on replay (resume iff the
  victim's checkpoint published after the intent, else rollback);
- a 3-seed flap-storm + kill-mid-migration campaign: zero lost jobs, zero
  duplicate admissions, bit-identical resumed trajectories;
- the real ``SaturnService`` running a defrag wave end-to-end (a blocked
  gang drains), then the same scenario killed mid-wave and recovered.
"""

import json
import os
import threading
import time

import pytest

from saturn_tpu.core.mesh import Block, SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.durability import Journal, replay, replay_service_state
from saturn_tpu.resilience import (
    CrashInjector,
    DefragMove,
    DefragWave,
    FaultEvent,
    FaultInjector,
    FaultKind,
    FleetHealthMonitor,
    GrowCoordinator,
    SimulatedKill,
    default_resident_bytes,
    plan_defrag_wave,
    run_to_kill,
)

pytestmark = pytest.mark.grow

CAP = 100          # modeled per-device HBM bytes (SATURN_TPU_HBM_BYTES)
PIN = 60           # bytes each live task pins
NEED = 80          # bytes the blocked gang needs per device


class FakeDev:
    platform = "cpu"
    process_index = 0


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)], slice_size=n)


class RecordingTech(BaseTechnique):
    name = "grow-fake"

    def __init__(self, per_batch=0.001):
        self.per_batch = per_batch
        self.calls = []
        self.lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        with self.lock:
            self.calls.append((task.name, override_batch_count or 1))
        time.sleep(self.per_batch * (override_batch_count or 1))

    def search(self, task, devices, tid):
        return {}, self.per_batch


class PinnedTask:
    """Duck-typed task whose device-resident live state pins HBM.

    ``resident_bytes``/``_live_state`` follow the executor's convention:
    pinned while ``_live_state`` is set, free after ``release_live_state``.
    """

    def __init__(self, name, sizes, resident=0, tech=None, total_batches=10,
                 pbt=0.001):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = 1000
        self.hints = {"resident_bytes": resident} if resident else {}
        self.chip_range = None
        tech = tech or RecordingTech(pbt)
        self.strategies = {
            g: Strategy(tech, g, {}, pbt * total_batches, pbt) for g in sizes
        }
        self.selected_strategy = None
        if resident:
            self._live_state = object()
        self.released = 0

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length

    def release_live_state(self):
        self._live_state = None
        self.released += 1


class _Slot:
    def __init__(self, block):
        self.block = block


class FakePlan:
    def __init__(self, assignments):
        self.assignments = assignments


def _scenario():
    """Two live tasks pin opposite ends of the ring; a 4-device gang with
    NEED bytes/device fits nowhere until one victim relocates."""
    a = PinnedTask("live-a", (2,), resident=PIN)
    b = PinnedTask("live-b", (2,), resident=PIN)
    gang = PinnedTask("gang-big", (4,), resident=NEED)
    plan = FakePlan({"live-a": _Slot(Block(0, 2)),
                     "live-b": _Slot(Block(4, 2))})
    return [a, b], gang, plan


@pytest.fixture(autouse=True)
def _hbm_env(monkeypatch):
    monkeypatch.setenv("SATURN_TPU_HBM_BYTES", str(CAP))


# -------------------------------------------------- hysteresis interplay
class TestBlinkHysteresis:
    """The storm case: loss and return land inside ONE poll window (a
    mid-interval preemption whose outage expires by the next interval).
    The unsurfaced shrink cancels, but the return still matures through
    hysteresis — the grow's re-solve re-admits the requeued work."""

    def test_in_window_blink_surfaces_grow_after_hysteresis(self):
        mon = FleetHealthMonitor(8, grow_hysteresis=2)
        mon.mark_lost([4, 5], cause="slice_preemption")   # mid-interval
        mon.mark_restored([4, 5])                         # next interval
        assert mon.poll() is None                         # no shrink: back up
        c = mon.poll()
        assert c is not None and c.kind == "grow" and c.gained == (4, 5)

    def test_blink_then_real_loss_still_shrinks(self):
        mon = FleetHealthMonitor(8, grow_hysteresis=2)
        mon.mark_lost([4], cause="slice_preemption")
        mon.mark_restored([4])
        mon.mark_lost([6], cause="device_loss")
        c = mon.poll()
        assert c.kind == "shrink" and c.lost == (6,)
        assert c.gained == (4,)  # candidate flushed into the shrink
        assert mon.poll() is None

    def test_unsurfaced_blink_then_reloss_surfaces_shrink(self):
        # Blink inside one window (shrink cancelled — the consumer still
        # believes the device alive), then the device dies again while
        # serving out hysteresis. The re-loss must NOT be swallowed: the
        # original loss was never surfaced, so swallowing would leave the
        # plan scheduling on a dead device forever.
        mon = FleetHealthMonitor(8, grow_hysteresis=2)
        mon.mark_lost([4], cause="slice_preemption")   # in-window
        mon.mark_restored([4])                         # blink: cancelled
        mon.mark_lost([4], cause="device_loss")        # dead again
        c = mon.poll()
        assert c is not None and c.kind == "shrink" and c.lost == (4,)
        assert mon.poll() is None
        assert mon.alive_indices() == [0, 1, 2, 3, 5, 6, 7]

    def test_surfaced_loss_reloss_mid_hysteresis_stays_swallowed(self):
        # The flap-storm contract is unchanged when the original loss WAS
        # surfaced: the consumer has seen the device dead the whole time,
        # so a re-loss mid-hysteresis emits nothing new.
        mon = FleetHealthMonitor(8, grow_hysteresis=2)
        mon.mark_lost([4], cause="device_loss")
        c = mon.poll()
        assert c.kind == "shrink" and c.lost == (4,)   # surfaced
        mon.mark_restored([4])
        mon.mark_lost([4], cause="device_loss")        # mid-hysteresis
        assert mon.poll() is None                      # one shrink total
        assert mon.poll() is None


# --------------------------------------------------------- defrag planner
class TestDefragPlanner:
    def test_wave_relocates_victim_and_admits_gang(self):
        live, gang, plan = _scenario()
        wave = plan_defrag_wave([gang], live, topo(8), plan,
                                default_resident_bytes, cap_bytes=CAP)
        assert wave.admitted == {"gang-big": (0, 4)}
        assert wave.still_blocked == []
        (mv,) = wave.moves
        assert mv.task == "live-a"
        assert mv.from_block == (0, 2) and mv.to_block == (6, 2)
        assert mv.pinned_bytes == PIN

    def test_wave_deterministic(self):
        outs = []
        for _ in range(2):
            live, gang, plan = _scenario()
            w = plan_defrag_wave([gang], live, topo(8), plan,
                                 default_resident_bytes, cap_bytes=CAP)
            outs.append(([(m.task, m.from_block, m.to_block) for m in w.moves],
                         dict(w.admitted), list(w.still_blocked)))
        assert outs[0] == outs[1]

    def test_fail_open_without_capacity_model(self):
        live, gang, plan = _scenario()
        wave = plan_defrag_wave([gang], live, topo(8), plan,
                                default_resident_bytes, cap_bytes=0)
        assert wave.empty and wave.still_blocked == ["gang-big"]

    def test_still_blocked_when_no_relocation_has_headroom(self):
        # Both halves pinned at 90: no victim can move anywhere.
        a = PinnedTask("heavy-a", (4,), resident=90)
        b = PinnedTask("heavy-b", (4,), resident=90)
        gang = PinnedTask("gang", (4,), resident=NEED)
        plan = FakePlan({"heavy-a": _Slot(Block(0, 4)),
                         "heavy-b": _Slot(Block(4, 4))})
        wave = plan_defrag_wave([gang], [a, b], topo(8), plan,
                                default_resident_bytes, cap_bytes=CAP)
        assert wave.moves == [] and wave.still_blocked == ["gang"]

    def test_unpinned_live_tasks_are_invisible(self):
        # A task with no live state neither blocks nor gets moved.
        free = PinnedTask("free", (4,), resident=0)
        gang = PinnedTask("gang", (4,), resident=NEED)
        plan = FakePlan({"free": _Slot(Block(0, 4))})
        wave = plan_defrag_wave([gang], [free], topo(8), plan,
                                default_resident_bytes, cap_bytes=CAP)
        assert wave.moves == [] and wave.admitted == {"gang": (0, 4)}


# -------------------------------------------------------- grow coordinator
class TestGrowCoordinator:
    def test_occupancy_gate_blocks_then_opens_after_wave(self, tmp_path):
        live, gang, plan = _scenario()
        jnl = Journal(str(tmp_path / "wal"))
        coord = GrowCoordinator(journal=jnl, poll_every=0)
        gate = coord.occupancy_gate(lambda: live + [gang], lambda: plan)
        before = gate(gang, topo(8))
        assert before == {"fits": False, "free_bytes": CAP - PIN,
                          "need_bytes": NEED}
        wave = coord.plan_wave([gang], live, topo(8), plan)
        wid = coord.execute_wave(wave, {t.name: t for t in live}, 3,
                                 publish_fn=lambda t: True)
        assert wid is not None
        assert live[0].released == 1  # live-a's pinned state freed
        after = gate(gang, topo(8))
        assert after is not None and after["fits"] is True
        jnl.close()
        kinds = [r["kind"] for r in replay(str(tmp_path / "wal"))]
        assert kinds.count("migration_intent") == 1
        assert kinds.count("migration_done") == 1
        assert "defrag_wave" in kinds

    def test_occupancy_gate_prices_need_per_gang_size(self, monkeypatch):
        # A smaller gang shards state over FEWER devices and needs MORE
        # bytes per device. The gate must price each candidate size with
        # its own memlens fit — a single largest-gang estimate would admit
        # a 2-device placement using the 4-device (smaller) need and OOM.
        from saturn_tpu.analysis.memlens import passes as ml_passes

        per_size_need = {4: 50, 2: 110}
        monkeypatch.setattr(
            ml_passes, "migration_fits",
            lambda task, topology, g, cap: {"peak_bytes": per_size_need[g]},
        )
        live = [PinnedTask("live-a", (2,), resident=PIN),
                PinnedTask("live-b", (2,), resident=PIN)]
        gang = PinnedTask("gang", (2, 4), resident=NEED)
        # Pins land in both 4-blocks: free 40 < 50 at size 4. Every empty
        # 2-block has free 100 >= the stale 50 but < the true 110.
        plan = FakePlan({"live-a": _Slot(Block(0, 2)),
                         "live-b": _Slot(Block(4, 2))})
        coord = GrowCoordinator(poll_every=0)
        verdict = coord.occupancy_gate(lambda: live, lambda: plan)(
            gang, topo(8))
        assert verdict is not None and verdict["fits"] is False
        # And the per-size need still admits when a block truly fits it.
        per_size_need[2] = 90  # 2-device apportionment now fits free=100
        verdict = coord.occupancy_gate(lambda: live, lambda: plan)(
            gang, topo(8))
        assert verdict["fits"] is True and verdict["need_bytes"] == 90

    def test_occupancy_gate_fails_open(self, monkeypatch):
        live, gang, plan = _scenario()
        coord = GrowCoordinator(poll_every=0)
        # no plan yet -> None
        assert coord.occupancy_gate(lambda: live, lambda: None)(
            gang, topo(8)) is None
        # nothing pinned -> None
        empty = FakePlan({})
        assert coord.occupancy_gate(lambda: [], lambda: empty)(
            gang, topo(8)) is None
        # no capacity model -> None
        monkeypatch.delenv("SATURN_TPU_HBM_BYTES", raising=False)
        assert coord.occupancy_gate(lambda: live, lambda: plan)(
            gang, topo(8)) is None

    def test_defrag_due_on_grow_and_poll_interval(self):
        coord = GrowCoordinator(poll_every=4)
        assert coord.defrag_due(1, grew=True)
        assert not coord.defrag_due(1, grew=False)
        assert coord.defrag_due(4, grew=False)
        assert coord.defrag_due(8, grew=False)
        assert not coord.defrag_due(0, grew=False)
        assert not GrowCoordinator(poll_every=0).defrag_due(8, grew=False)

    def test_note_grow_short_circuits_guardian(self, tmp_path):
        from saturn_tpu.health import GuardianConfig, TrainingGuardian

        jnl = Journal(str(tmp_path / "wal"))
        g = TrainingGuardian(GuardianConfig(backoff_base=64, backoff_cap=64),
                             journal=jnl)
        g._benched["parked-a"] = 99
        g._benched["parked-b"] = 120
        streaks = {("parked-a", "nonfinite"): 2}
        g._streak.update(streaks)
        coord = GrowCoordinator(journal=jnl, poll_every=0)
        mon = FleetHealthMonitor(8, grow_hysteresis=1)
        mon.mark_lost([7])
        mon.poll()
        mon.mark_restored([7])
        change = mon.poll()
        released = coord.note_grow(change, 5, guardian=g, n_deferred=2,
                                   capacity=8)
        assert released == ["parked-a", "parked-b"]
        assert not g.benched("parked-a", 5)   # bench short-circuited
        assert g._streak == streaks           # fault history intact
        jnl.close()
        recs = replay(str(tmp_path / "wal"))
        (ge,) = [r for r in recs if r["kind"] == "grow_event"]
        assert ge["data"]["gained"] == [7]
        assert ge["data"]["n_parked"] == 2
        assert ge["data"]["unbenched"] == ["parked-a", "parked-b"]
        (ub,) = [r for r in recs if r["kind"] == "health_unbench"]
        assert ub["data"]["tasks"] == ["parked-a", "parked-b"]
        assert ub["data"]["cause"] == "grow"

    def test_publish_failure_rolls_back_without_touching_state(self, tmp_path):
        live, gang, plan = _scenario()
        jnl = Journal(str(tmp_path / "wal"))
        coord = GrowCoordinator(journal=jnl, poll_every=0)
        wave = coord.plan_wave([gang], live, topo(8), plan)
        coord.execute_wave(wave, {t.name: t for t in live}, 1,
                           publish_fn=lambda t: False)
        assert live[0].released == 0  # victim state untouched
        jnl.close()
        recs = replay(str(tmp_path / "wal"))
        kinds = [r["kind"] for r in recs]
        assert "migration_rollback" in kinds
        assert "migration_done" not in kinds
        state = replay_service_state(str(tmp_path / "wal"))
        assert state.pending_migrations == {}  # rollback closed the intent


# ----------------------------------------------------- admission revisit_on
class TestAdmissionRevisit:
    def _ctrl(self, t, journal=None):
        from saturn_tpu.service.admission import AdmissionController
        from saturn_tpu.service.queue import SubmissionQueue

        q = SubmissionQueue()
        ctrl = AdmissionController(t, q)
        ctrl.journal = journal
        return ctrl, q

    def _submit(self, q, task, **kw):
        from saturn_tpu.service.queue import JobRequest

        return q.submit(JobRequest(task, **kw))

    def test_degraded_mesh_defers_with_grow_revisit(self):
        from saturn_tpu.service.admission import DEFER, REVISIT_GROW

        ctrl, q = self._ctrl(topo(8))
        rec = self._submit(q, PinnedTask("d", (8,)))
        dec = ctrl.admit(rec, topo(4))
        assert dec.action == DEFER and dec.revisit_on == REVISIT_GROW
        assert ctrl.deferred[rec.job_id]["revisit_on"] == REVISIT_GROW

    def test_occupancy_defers_with_defrag_revisit_and_journal_dedup(
            self, tmp_path):
        from saturn_tpu.service.admission import (
            ADMIT, DEFER, REVISIT_DEFRAG,
        )

        t8 = topo(8)
        jnl = Journal(str(tmp_path / "wal"))
        ctrl, q = self._ctrl(t8, journal=jnl)
        verdict = {"fits": False, "free_bytes": 40, "need_bytes": NEED}
        ctrl.occupancy_gate = lambda task, topology: verdict
        rec = self._submit(q, PinnedTask("gang", (4,)))
        dec = ctrl.admit(rec, t8)
        assert dec.action == DEFER and dec.revisit_on == REVISIT_DEFRAG
        assert "occupancy" in dec.reason and "defrag" in dec.reason
        first_at = ctrl.deferred[rec.job_id]["deferred_at"]
        # Re-defer on the same grounds: pool count bumps, NO new record.
        q.requeue(rec)
        dec2 = ctrl.admit(rec, t8)
        assert dec2.action == DEFER
        assert ctrl.deferred[rec.job_id]["count"] == 2
        assert ctrl.deferred[rec.job_id]["deferred_at"] == first_at
        # The gate opens: the job admits and leaves the pool.
        ctrl.occupancy_gate = lambda task, topology: {"fits": True,
                                                      "free_bytes": CAP,
                                                      "need_bytes": NEED}
        q.requeue(rec)
        dec3 = ctrl.admit(rec, t8)
        assert dec3.action == ADMIT
        assert rec.job_id not in ctrl.deferred
        jnl.commit()
        jnl.close()
        deferred_recs = [r for r in replay(str(tmp_path / "wal"))
                         if r["kind"] == "job_deferred"]
        assert len(deferred_recs) == 1  # deduped: one record per class
        assert deferred_recs[0]["data"]["revisit_on"] == REVISIT_DEFRAG

    def test_gate_exception_fails_open(self):
        from saturn_tpu.service.admission import ADMIT

        t8 = topo(8)
        ctrl, q = self._ctrl(t8)

        def boom(task, topology):
            raise RuntimeError("gate crashed")

        ctrl.occupancy_gate = boom
        rec = self._submit(q, PinnedTask("ok", (2,)))
        assert ctrl.admit(rec, t8).action == ADMIT


class TestDeferPoolCancelReconcile:
    """A deferred job that leaves the queue terminally WITHOUT a later
    ADMIT/REJECT (cancel) must not leak its DEFER-pool entry — a leaked
    entry inflates n_deferred, the backlog views, and defrag blocked_ids
    forever."""

    def _deferred_job(self, svc, name):
        from saturn_tpu.service.admission import DEFER
        from saturn_tpu.service.queue import JobRequest

        rec = svc.queue.submit(JobRequest(
            PinnedTask(name, (4,), resident=NEED)))
        svc.admission.occupancy_gate = lambda task, topology: {
            "fits": False, "free_bytes": 0, "need_bytes": NEED}
        svc.admission.begin_pass()
        dec = svc.admission.admit(rec, topo(8))
        assert dec.action == DEFER
        svc.queue.requeue(rec)
        assert rec.job_id in svc.admission.deferred
        return rec

    def test_queue_side_cancel_evict_reconciles(self):
        # queue.cancel evicts a QUEUED job immediately, bypassing the
        # admission verdict that would normally pop the pool entry; the
        # next drain pass reconciles against the terminal exit.
        from saturn_tpu.service import SaturnService

        svc = SaturnService(topology=topo(8), interval=0.2, poll_s=0.01)
        rec = self._deferred_job(svc, "gang-cancel-q")
        assert svc.queue.cancel(rec.job_id) is True
        svc._drain_arrivals({}, topo(8), 0, None)
        assert rec.job_id not in svc.admission.deferred

    def test_cancel_requested_in_drain_pops_entry(self):
        # A cancel that lands as a flag (race with the drain) is honored
        # inside _drain_arrivals itself: EVICTED + pool entry popped.
        from saturn_tpu.service import SaturnService
        from saturn_tpu.service.queue import JobState

        svc = SaturnService(topology=topo(8), interval=0.2, poll_s=0.01)
        rec = self._deferred_job(svc, "gang-cancel-flag")
        rec.cancel_requested = True  # flag only: still on the arrival queue
        svc._drain_arrivals({}, topo(8), 0, None)
        assert rec.state is JobState.EVICTED
        assert rec.job_id not in svc.admission.deferred


# ------------------------------------------------------------- kill-replay
class TestDefragKillReplay:
    """A kill between ``migration_intent`` and ``migration_done`` resolves
    exactly once on replay: resume iff the victim's checkpoint published
    after the intent, else rollback — and a second replay is a no-op."""

    def _run_wave(self, wal, barrier=None, publish=True):
        live, gang, plan = _scenario()
        jnl = Journal(wal, barrier=barrier)
        coord = GrowCoordinator(journal=jnl, poll_every=0)
        wave = coord.plan_wave([gang], live, topo(8), plan)

        def publish_fn(task):
            if not publish:
                return False
            # the server's republish: a durable ckpt_published AFTER the
            # move's intent is the recovery arbitration signal
            jnl.log("ckpt_published", task=task.name, path="ck",
                    wave_republish=True)
            return True

        coord.execute_wave(wave, {t.name: t for t in live}, 7,
                           publish_fn=publish_fn)
        jnl.close()

    def _recover_and_close(self, wal):
        """The server's recovery closure, exactly once per open intent."""
        state = replay_service_state(wal)
        resume, rollback = state.resolve_pending_migrations()
        jnl = Journal(wal)
        for rec in resume:
            jnl.log("migration_done", wave=rec["wave"], task=rec["task"],
                    recovered=True)
        for rec in rollback:
            jnl.log("migration_rollback", wave=rec["wave"],
                    task=rec["task"], cause="recovery", recovered=True)
        jnl.close()
        return resume, rollback

    def test_kill_pre_publish_rolls_back(self, tmp_path):
        wal = str(tmp_path / "wal")
        inj = CrashInjector("defrag.pre-publish")
        with pytest.raises(SimulatedKill):
            self._run_wave(wal, barrier=inj.barrier)
        state = replay_service_state(wal)
        assert len(state.pending_migrations) == 1
        resume, rollback = self._recover_and_close(wal)
        assert resume == [] and len(rollback) == 1
        state2 = replay_service_state(wal)
        assert state2.pending_migrations == {}       # closed exactly once
        assert state2.migrations_rolled_back == 1
        assert self._recover_and_close(wal) == ([], [])  # replay is a no-op

    def test_kill_pre_commit_resumes(self, tmp_path):
        wal = str(tmp_path / "wal")
        inj = CrashInjector("defrag.pre-commit")
        with pytest.raises(SimulatedKill):
            self._run_wave(wal, barrier=inj.barrier)
        # intent AND ckpt_published are durable; migration_done is not
        kinds = [r["kind"] for r in replay(wal)]
        assert "ckpt_published" in kinds and "migration_done" not in kinds
        resume, rollback = self._recover_and_close(wal)
        assert len(resume) == 1 and rollback == []
        state = replay_service_state(wal)
        assert state.pending_migrations == {}
        assert state.migrations_done == 1
        assert self._recover_and_close(wal) == ([], [])

    def test_kill_post_commit_is_a_noop(self, tmp_path):
        wal = str(tmp_path / "wal")
        inj = CrashInjector("defrag.post-commit")
        with pytest.raises(SimulatedKill):
            self._run_wave(wal, barrier=inj.barrier)
        state = replay_service_state(wal)
        assert state.pending_migrations == {}  # done committed pre-kill
        assert state.migrations_done == 1
        assert self._recover_and_close(wal) == ([], [])
        # strict replay: the kill tore nothing
        recs = replay(wal, strict=True)
        assert [r["seq"] for r in recs] == sorted(r["seq"] for r in recs)


# ------------------------------------------------------- 3-seed campaign
class TestGrowChaosCampaign:
    """Flap storm + seeded kill-mid-migration, three seeds: zero lost jobs
    (every intent closes), zero duplicate admissions (each drained job
    appears once), and the resumed trajectory is bit-identical across two
    runs of the same seed."""

    POINTS = ("defrag.pre-publish", "defrag.pre-commit",
              "defrag.post-commit")

    def _campaign(self, wal, seed):
        # flap storm against the monitor: exactly one shrink, one grow
        mon = FleetHealthMonitor(8, grow_hysteresis=2)
        surfaced = []
        mon.mark_lost([6, 7], cause="slice_preemption")
        surfaced.append(mon.poll())
        for _ in range(3):
            mon.mark_restored([6, 7])
            surfaced.append(mon.poll())
            mon.mark_lost([6, 7], cause="slice_preemption")
            surfaced.append(mon.poll())
        mon.mark_restored([6, 7])
        surfaced.append(mon.poll())
        surfaced.append(mon.poll())
        events = [c.kind for c in surfaced if c is not None]
        assert events == ["shrink", "grow"], events

        jnl = Journal(wal)
        coord = GrowCoordinator(journal=jnl, poll_every=0)
        grow = [c for c in surfaced if c is not None][-1]
        coord.note_grow(grow, 9, n_deferred=1, capacity=8)

        # kill mid-wave at a seeded point, then recover
        inj = CrashInjector.seeded(seed, max_hit=1, points=self.POINTS)
        live, gang, plan = _scenario()
        jnl2 = Journal(wal, barrier=inj.barrier)
        coord2 = GrowCoordinator(journal=jnl2, poll_every=0)
        wave = coord2.plan_wave([gang], live, topo(8), plan)

        def publish_fn(task):
            jnl2.log("ckpt_published", task=task.name, path="ck",
                     wave_republish=True)
            return True

        with pytest.raises(SimulatedKill):
            coord2.execute_wave(wave, {t.name: t for t in live}, 9,
                                publish_fn=publish_fn)

        # recovery incarnation: close intents, finish the drain
        state = replay_service_state(wal)
        resume, rollback = state.resolve_pending_migrations()
        jnl3 = Journal(wal)
        for rec in resume:
            jnl3.log("migration_done", wave=rec["wave"], task=rec["task"],
                     recovered=True)
        for rec in rollback:
            jnl3.log("migration_rollback", wave=rec["wave"],
                     task=rec["task"], cause="recovery", recovered=True)
        coord3 = GrowCoordinator(journal=jnl3, poll_every=0)
        coord3.note_drained([gang.name], 10, trigger="grow")
        jnl3.close()

    def _trajectory(self, wal):
        """The deterministic face of the journal: kinds + data, no ts/seq
        (seq shifts with incarnation segment headers)."""
        return [(r["kind"], json.dumps(r["data"], sort_keys=True))
                for r in replay(wal)
                if r["kind"] not in ("segment_open", "recovery")]

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_seeded_kill_campaign(self, tmp_path, seed):
        wal_a = str(tmp_path / f"a{seed}")
        wal_b = str(tmp_path / f"b{seed}")
        self._campaign(wal_a, seed)
        self._campaign(wal_b, seed)

        # zero lost jobs: every intent closed, exactly once
        state = replay_service_state(wal_a)
        assert state.pending_migrations == {}
        assert state.migrations_done + state.migrations_rolled_back >= 1
        recs = replay(wal_a)
        closures = {}
        for r in recs:
            if r["kind"] in ("migration_done", "migration_rollback"):
                key = (r["data"]["wave"], r["data"]["task"])
                closures[key] = closures.get(key, 0) + 1
        assert closures and all(n == 1 for n in closures.values()), closures

        # zero duplicate admissions: each drained job appears once
        drained = [j for r in recs if r["kind"] == "backlog_drain"
                   for j in r["data"]["jobs"]]
        assert drained == sorted(set(drained))

        # bit-identical resumed trajectory across two runs of the seed
        assert self._trajectory(wal_a) == self._trajectory(wal_b)


# ----------------------------------------------------- recovery folding
class TestRecoveryFolding:
    def test_grow_records_fold(self, tmp_path):
        wal = str(tmp_path / "wal")
        j = Journal(wal)
        j.append("grow_event", interval=2, gained=[7], cause="device_return")
        j.append("backlog_drain", interval=2, jobs=["j1", "j2"],
                 trigger="grow")
        j.append("job_deferred", job="j9", task="t9", tenant="acme",
                 reason="occupancy", revisit_on="defrag", at=1.0)
        j.commit()
        j.close()
        state = replay_service_state(wal)
        assert state.grow_events == 1
        assert state.backlog_drained == 2
        assert state.deferred["j9"]["revisit_on"] == "defrag"

    def test_resolution_arbitrates_on_ckpt_seq(self):
        from saturn_tpu.durability.recovery import ServiceRecovery

        s = ServiceRecovery()
        s.pending_migrations[("w", "early")] = {"wave": "w", "task": "early",
                                                "seq": 5}
        s.pending_migrations[("w", "late")] = {"wave": "w", "task": "late",
                                               "seq": 5}
        s.last_ckpt_seq = {"early": 6, "late": 4}
        resume, rollback = s.resolve_pending_migrations()
        assert [r["task"] for r in resume] == ["early"]
        assert [r["task"] for r in rollback] == ["late"]


# -------------------------------------------------- service integration
def _mk_service(wal, monkeypatch=None, barrier=None, provider=None,
                fleet=None):
    from saturn_tpu.service import SaturnService

    mon = inj = None
    if fleet is not None:
        mon, inj = fleet
    return SaturnService(
        topology=topo(8), interval=0.2, poll_s=0.02,
        durability_dir=wal, task_provider=provider,
        crash_barrier=barrier, health_monitor=mon, fault_injector=inj,
    )


def _grow_provider(tech):
    def provide(spec):
        return PinnedTask(
            spec["task"], spec["spec"]["sizes"], tech=tech,
            resident=spec["spec"].get("resident", 0),
            total_batches=spec["remaining_batches"],
        )
    return provide


@pytest.mark.slow
class TestServiceDefragIntegration:
    # Pins must still be RUNNING (live state pinned) when the gang's
    # admission pass fires, so give them many intervals of work:
    # 2500 batches at 1 ms over 0.2 s intervals ≈ 13 intervals.
    PIN_BATCHES = 2500

    def _submit_scenario(self, client, tech):
        ids = {}
        for name, blk in (("pin-a", 30), ("pin-b", 30)):
            ids[name] = client.submit(
                PinnedTask(name, (4,), resident=blk, tech=tech,
                           total_batches=self.PIN_BATCHES),
                spec={"sizes": [4], "resident": blk},
            )
        return ids

    def _seed_ckpts(self, svc, tmp_path, names):
        # Stand in for the interval-boundary checkpoint republish: the
        # victims' checkpoints exist on disk (in the real on-disk format,
        # so recovery's verification accepts them) and the server knows
        # them.
        from saturn_tpu.utils import checkpoint as ckpt_mod

        for n in names:
            p = str(tmp_path / f"{n}.ckpt")
            ckpt_mod.save(p, {"task": n, "step": 0})
            svc._last_ckpt[n] = p

    def test_blocked_gang_drains_through_defrag_wave(self, tmp_path,
                                                     monkeypatch):
        from saturn_tpu.service import ServiceClient

        monkeypatch.setenv("SATURN_TPU_GROW_POLL", "2")
        wal = str(tmp_path / "wal")
        tech = RecordingTech(per_batch=0.001)
        svc = _mk_service(wal, provider=_grow_provider(tech))
        svc.start()
        client = ServiceClient(svc)
        try:
            ids = self._submit_scenario(client, tech)
            deadline = time.monotonic() + 30
            while not (client.status(ids["pin-a"])["state"] == "RUNNING"
                       and client.status(ids["pin-b"])["state"] == "RUNNING"):
                assert time.monotonic() < deadline, "pins never ran"
                time.sleep(0.02)
            self._seed_ckpts(svc, tmp_path, ["pin-a", "pin-b"])
            # per-device need 80 vs 100 cap with 30 pinned on each half:
            # blocked until a victim relocates
            ids["gang"] = client.submit(
                PinnedTask("gang", (4,), resident=NEED, tech=tech,
                           total_batches=20),
                spec={"sizes": [4], "resident": NEED},
            )
            outs = {k: client.wait(j, timeout=90) for k, j in ids.items()}
        finally:
            svc.stop(timeout=60)
        assert all(o["state"] == "DONE" for o in outs.values()), outs
        recs = replay(wal)
        kinds = [r["kind"] for r in recs]
        assert "job_deferred" in kinds       # the gang was occupancy-blocked
        assert "migration_intent" in kinds and "migration_done" in kinds
        assert "defrag_wave" in kinds
        drains = [r["data"] for r in recs if r["kind"] == "backlog_drain"]
        assert any(ids["gang"] in d["jobs"] for d in drains)
        # the operator view agrees and sees no unresolved intents
        from saturn_tpu.analysis.cli import _fold_grow_records

        folded = _fold_grow_records(recs)
        assert folded["unresolved_intents"] == []
        assert folded["drained_jobs"] >= 1

    def test_kill_mid_wave_recovers_without_losing_jobs(self, tmp_path,
                                                        monkeypatch):
        from saturn_tpu.service import ServiceClient

        monkeypatch.setenv("SATURN_TPU_GROW_POLL", "2")
        wal = str(tmp_path / "wal")
        tech = RecordingTech(per_batch=0.001)
        inj = CrashInjector("defrag.pre-commit", hit=1, armed=False)
        svc = _mk_service(wal, barrier=inj.barrier,
                          provider=_grow_provider(tech))
        svc.start()
        client = ServiceClient(svc)
        ids = self._submit_scenario(client, tech)
        deadline = time.monotonic() + 30
        while not (client.status(ids["pin-a"])["state"] == "RUNNING"
                   and client.status(ids["pin-b"])["state"] == "RUNNING"):
            assert time.monotonic() < deadline, "pins never ran"
            time.sleep(0.02)
        self._seed_ckpts(svc, tmp_path, ["pin-a", "pin-b"])
        ids["gang"] = client.submit(
            PinnedTask("gang", (4,), resident=NEED, tech=tech,
                       total_batches=20),
            spec={"sizes": [4], "resident": NEED},
        )
        run_to_kill(inj, svc)
        assert svc.killed

        # incarnation 2: recovery closes the open intent, everything runs
        svc2 = _mk_service(wal, provider=_grow_provider(tech))
        svc2.start()
        client2 = ServiceClient(svc2)
        try:
            outs = {k: client2.wait(j, timeout=90) for k, j in ids.items()}
        finally:
            svc2.stop(timeout=60)
        assert all(o["state"] == "DONE" for o in outs.values()), outs
        recs = replay(wal)
        done = [r["data"] for r in recs if r["kind"] == "migration_done"]
        assert any(d.get("recovered") for d in done)  # closed by recovery
        closures = {}
        for r in recs:
            if r["kind"] in ("migration_done", "migration_rollback"):
                key = (r["data"]["wave"], r["data"]["task"])
                closures[key] = closures.get(key, 0) + 1
        assert all(n == 1 for n in closures.values()), closures
        state = replay_service_state(wal)
        assert state.pending_migrations == {}


@pytest.mark.slow
class TestServiceGrowShortCircuit:
    def test_benched_job_readmits_on_grow(self, tmp_path):
        """A guardian-benched job restarts in the grow interval, well before
        its backoff would expire naturally."""
        from saturn_tpu.health import (
            GuardianConfig, NumericFaultError, TrainingGuardian, sentinel,
        )
        from saturn_tpu.service import SaturnService, ServiceClient

        class FaultOnceTech(RecordingTech):
            def __init__(self):
                super().__init__(per_batch=0.002)
                self.faulted = False

            def execute(self, task, devices, tid, override_batch_count=None):
                if task.name == "sick" and not self.faulted:
                    self.faulted = True
                    raise NumericFaultError(
                        task.name, 0, sentinel.CAUSE_NONFINITE, step=0,
                        loss=float("nan"), batch_indices=(), bad_count=1,
                    )
                super().execute(task, devices, tid, override_batch_count)

        wal = str(tmp_path / "wal")
        t8 = topo(8)
        mon = FleetHealthMonitor.for_topology(t8)
        injector = FaultInjector(schedule=[
            FaultEvent(3, FaultKind.DEVICE_LOSS, devices=(7,)),
            FaultEvent(4, FaultKind.DEVICE_RETURN, devices=(7,)),
        ])
        tech = FaultOnceTech()
        guardian = TrainingGuardian(
            GuardianConfig(retry_budget=9, backoff_base=64, backoff_cap=64)
        )
        svc = SaturnService(
            topology=t8, interval=0.2, poll_s=0.02, durability_dir=wal,
            health_monitor=mon, fault_injector=injector,
            health_guardian=guardian,
        ).start()
        client = ServiceClient(svc)
        t0 = time.monotonic()
        try:
            jid = client.submit(
                PinnedTask("sick", (2,), tech=tech, total_batches=40),
                spec={"sizes": [2]},
            )
            out = client.wait(jid, timeout=60)
        finally:
            svc.stop(timeout=60)
        elapsed = time.monotonic() - t0
        assert out["state"] == "DONE"
        # without the short-circuit the 64-interval bench alone would hold
        # the job for ~13s of 0.2s intervals
        assert elapsed < 12.0, elapsed
        recs = replay(wal)
        kinds = [r["kind"] for r in recs]
        assert "health_backoff" in kinds   # it really was benched
        assert "grow_event" in kinds
        (ub,) = [r for r in recs if r["kind"] == "health_unbench"]
        assert ub["data"]["tasks"] == ["sick"]
