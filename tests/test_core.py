"""Unit tests for core representations, mesh topology, library, checkpointing."""

import numpy as np
import pytest

from saturn_tpu import HParams, Strategy, Task, library
from saturn_tpu.core.mesh import Block, SliceTopology, make_submesh
from saturn_tpu.core.technique import BaseTechnique


class TestHParams:
    def test_epochs_xor_batch_count(self):
        HParams(epochs=1)
        HParams(batch_count=5)
        with pytest.raises(ValueError):
            HParams()  # neither
        with pytest.raises(ValueError):
            HParams(epochs=1, batch_count=5)  # both

    def test_unknown_optimizer(self):
        with pytest.raises(ValueError):
            HParams(batch_count=1, optimizer="nope")

    def test_optimizer_factory(self):
        import optax

        tx = HParams(batch_count=1, optimizer="adamw").make_optimizer()
        assert isinstance(tx, optax.GradientTransformation)
        tx2 = HParams(batch_count=1, optimizer=lambda lr: optax.sgd(lr)).make_optimizer()
        assert isinstance(tx2, optax.GradientTransformation)


class TestStrategy:
    def test_feasible(self):
        assert not Strategy(None, 4, None, 1e6).feasible
        assert Strategy(object(), 4, {}, 10.0).feasible

    def test_bad_apportionment(self):
        with pytest.raises(ValueError):
            Strategy(None, 0, None, 1.0)


class TestBlocks:
    def test_alignment(self):
        Block(0, 4)
        Block(4, 4)
        with pytest.raises(ValueError):
            Block(2, 4)  # misaligned
        with pytest.raises(ValueError):
            Block(0, 3)  # not pow2

    def test_overlap_nesting(self):
        # buddy property: blocks either nest or are disjoint
        assert Block(0, 4).overlaps(Block(0, 2))
        assert Block(0, 4).overlaps(Block(2, 2))
        assert not Block(0, 4).overlaps(Block(4, 4))


class TestTopology:
    def test_sizes_and_blocks(self, devices8):
        topo = SliceTopology(devices8)
        assert topo.capacity == 8
        assert topo.valid_sizes() == [1, 2, 4, 8]
        assert len(topo.blocks(2)) == 4
        assert [b.offset for b in topo.blocks(4)] == [0, 4]

    def test_non_pow2_devices(self, devices8):
        topo = SliceTopology(devices8[:6])
        assert topo.capacity == 4

    def test_make_submesh(self, devices8):
        mesh = make_submesh(devices8[:4], ("data",))
        assert mesh.devices.shape == (4,)
        mesh2 = make_submesh(devices8, ("data", "model"), (4, 2))
        assert mesh2.devices.shape == (4, 2)
        mesh3 = make_submesh(devices8, ("data", "model"), (-1, 2))
        assert mesh3.devices.shape == (4, 2)
        with pytest.raises(ValueError):
            make_submesh(devices8, ("data", "model"), (3, 2))


class FakeDev:
    """Stand-in device with the attrs SliceTopology groups by."""

    def __init__(self, id, process_index):
        self.id = id
        self.process_index = process_index

    def __repr__(self):
        return f"d{self.id}@p{self.process_index}"


class TestMultiSliceTopology:
    def mk(self, n_slices=2, per_slice=8, interleave=False):
        devs = [
            FakeDev(s * per_slice + i, process_index=s)
            for s in range(n_slices)
            for i in range(per_slice)
        ]
        if interleave:
            devs = devs[::2] + devs[1::2]  # scrambled arrival order
        return SliceTopology(devices=devs)

    def test_slice_detection_and_ordering(self):
        topo = self.mk(interleave=True)
        assert topo.slice_size == 8 and topo.capacity == 16
        # re-sorted slice-major: first 8 devices all process 0
        assert [d.process_index for d in topo.devices] == [0] * 8 + [1] * 8

    def test_ici_blocks_never_cross_dcn(self):
        topo = self.mk()
        for size in (1, 2, 4, 8):
            for blk in topo.blocks(size):
                assert not topo.crosses_dcn(blk), (size, blk)
        assert topo.crosses_dcn(topo.blocks(16)[0])

    def test_data_axis_spans_dcn(self):
        """For a DCN-crossing block, the leading (data) mesh axis is the one
        that crosses slices — the multi-slice grad-allreduce recipe."""
        topo = self.mk()
        blk = topo.blocks(16)[0]
        mesh = make_submesh(topo.block_devices(blk), ("data", "model"), (2, 8))
        import numpy as np

        procs = np.vectorize(lambda d: d.process_index)(mesh.devices)
        assert (procs[0] == 0).all() and (procs[1] == 1).all()

    def test_single_host_is_one_domain(self):
        topo = SliceTopology(devices=[FakeDev(i, 0) for i in range(8)])
        assert topo.slice_size == 8
        assert not topo.crosses_dcn(topo.blocks(8)[0])

    def test_non_pow2_groups_fall_back(self):
        devs = [FakeDev(i, i % 3) for i in range(9)]  # 3 groups of 3
        topo = SliceTopology(devices=devs)
        assert topo.slice_size == 9  # one domain; buddy alloc still valid
        assert topo.capacity == 8


class TestLibrary:
    def test_register_type_check(self):
        with pytest.raises(TypeError):
            library.register("bad", object)

    def test_register_retrieve_deregister(self):
        class Dummy(BaseTechnique):
            name = "dummy"

            def execute(self, task, devices, tid, override_batch_count=None):
                pass

            def search(self, task, devices, tid):
                return {}, 1.0

        library.register("dummy", Dummy)
        assert library.retrieve("dummy") is Dummy
        assert Dummy in library.retrieve(["dummy"])
        library.deregister("dummy")
        with pytest.raises(KeyError):
            library.retrieve("dummy")

    def test_default_library(self):
        names = library.register_default_library()
        assert "dp" in names and "fsdp" in names and "tp" in names
        for n in names:
            assert issubclass(library.retrieve(n), BaseTechnique)

    def test_dill_persistence(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SATURN_TPU_LIBRARY_PATH", str(tmp_path))

        class Dummy2(BaseTechnique):
            def execute(self, task, devices, tid, override_batch_count=None):
                pass

            def search(self, task, devices, tid):
                return {}, 1.0

        library.register("dummy2", Dummy2)
        assert (tmp_path / "dummy2.udp").exists()
        # wipe in-process registry entry; retrieve must reload from disk
        library._REGISTRY.pop("dummy2")
        cls = library.retrieve("dummy2")
        assert cls.__name__ == "Dummy2"
        library.deregister("dummy2")
        assert not (tmp_path / "dummy2.udp").exists()


class TestTask:
    def test_task_basics(self, tiny_task):
        t = tiny_task
        assert t.epoch_length == 8
        assert t.total_batches == 16
        assert len(t.name) == 16  # random hex name, reference Task.py:107-109
        b = t.batch_at(0)
        assert b.shape == (8, 64)
        # O(1) wraparound access
        assert np.array_equal(t.batch_at(t.epoch_length), t.batch_at(0))

    def test_reconfigure_wraps(self, tiny_task):
        tiny_task.reconfigure(5)
        assert tiny_task.current_batch == 5
        tiny_task.reconfigure(6)
        assert tiny_task.current_batch == 3  # (5+6) % 8

    def test_select_strategy(self, tiny_task):
        s = Strategy(object(), 2, {}, 5.0)
        tiny_task.strategies[2] = s
        tiny_task.select_strategy(2)
        assert tiny_task.selected_strategy is s
        assert tiny_task.feasible_strategies() == {2: s}

    def test_clone(self, tiny_task):
        """lr fan-out without re-profiling (reference WikiText103.py:87-99)."""
        tiny_task.strategies[2] = Strategy(object(), 2, {"remat": True}, 5.0,
                                           per_batch_time=0.5)
        c = tiny_task.clone(name="cloned", lr=3e-4)
        assert c.name == "cloned" and c.hparams.lr == 3e-4
        assert tiny_task.hparams.lr != 3e-4  # original untouched
        # profile carried over, but Strategy objects are copies, not aliases:
        # forecast mutates per-task remaining runtime.
        assert c.strategies[2].runtime == 5.0
        c.strategies[2].runtime = 1.0
        assert tiny_task.strategies[2].runtime == 5.0
        # dataset instance is shared (no re-tokenization per clone)...
        assert c.get_dataset() is tiny_task.get_dataset()
        assert c.epoch_length == tiny_task.epoch_length
        # ...but the real factory is preserved for a fresh rebuild.
        assert c._get_dataloader is tiny_task._get_dataloader


class TestCheckpoint:
    def test_roundtrip_and_template_restore(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from saturn_tpu.utils import checkpoint as ckpt

        tree = {
            "params": {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)},
            "step": jnp.asarray(7, dtype=jnp.int32),
        }
        p = str(tmp_path / "c.npz")
        ckpt.save(p, tree)
        template = jax.eval_shape(lambda: tree)
        out = ckpt.restore(p, template)
        assert np.array_equal(out["params"]["w"], np.arange(6).reshape(2, 3))
        assert out["step"] == 7

    def test_dtype_follows_template(self, tmp_path):
        import jax.numpy as jnp
        import jax

        from saturn_tpu.utils import checkpoint as ckpt

        tree = {"w": jnp.ones((4,), dtype=jnp.bfloat16)}
        p = str(tmp_path / "c.npz")
        ckpt.save(p, tree)
        out = ckpt.restore(p, jax.eval_shape(lambda: tree))
        assert out["w"].dtype == jnp.bfloat16

    def test_shape_mismatch_raises(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from saturn_tpu.utils import checkpoint as ckpt

        p = str(tmp_path / "c.npz")
        ckpt.save(p, {"w": jnp.ones((4,))})
        with pytest.raises(ValueError):
            ckpt.restore(p, jax.eval_shape(lambda: {"w": jnp.ones((5,))}))


class TestTechniquesEnum:
    """VERDICT r1 weak item 5: the enum must be consumed, not decorative."""

    def test_builtins_carry_enum(self):
        from saturn_tpu.core.strategy import Techniques
        from saturn_tpu.parallel import BUILTIN_TECHNIQUES

        want = {
            "dp": Techniques.DP, "fsdp": Techniques.FSDP,
            "tp": Techniques.TENSOR, "pp": Techniques.PIPELINE,
            "offload": Techniques.OFFLOAD, "ring": Techniques.RING,
            "ulysses": Techniques.ULYSSES, "ep": Techniques.EXPERT,
        }
        for name, member in want.items():
            assert BUILTIN_TECHNIQUES[name].technique is member

    def test_retrieve_by_enum(self):
        from saturn_tpu import library
        from saturn_tpu.core.strategy import Techniques
        from saturn_tpu.parallel.fsdp import FSDP

        library.register_default_library()
        assert library.retrieve(Techniques.FSDP) is FSDP
        library.deregister("ulysses")
        try:
            with pytest.raises(KeyError):
                library.retrieve(Techniques.ULYSSES)
        finally:
            library.register_default_library()

    def test_strategy_surfaces_enum(self):
        from saturn_tpu.core.strategy import Strategy, Techniques
        from saturn_tpu.parallel.dp import DataParallel

        s = Strategy(DataParallel(), 2, {}, 10.0)
        assert s.technique is Techniques.DP
        assert Strategy(None, 2, None, 10.0).technique is None
