"""Unit tests for bench.py's probe sentinel and timeout short-circuit.

bench.py lives at the repo root (not in the package) so it is loaded via
importlib; its module level only imports stdlib, so this is cheap — the
heavy jax imports are inside main() and never run here.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    sentinel = tmp_path / "probe.json"
    monkeypatch.setattr(mod, "_probe_sentinel_path", lambda: str(sentinel))
    monkeypatch.setattr(mod, "_boot_key", lambda: "boot-A")
    monkeypatch.delenv("SATURN_BENCH_PROBE_CACHE", raising=False)
    monkeypatch.delenv("SATURN_BENCH_PROBE_TTL", raising=False)
    return mod


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestProbeSentinel:
    def test_hit_within_ttl_and_miss_after(self, bench, monkeypatch):
        clock = FakeClock()
        monkeypatch.setattr(bench.time, "time", clock)
        bench._store_probe("tpu")
        assert bench._cached_probe() == ("tpu",)
        clock.t += bench._PROBE_TTL_S - 1
        assert bench._cached_probe() == ("tpu",)
        clock.t += 2  # past the TTL: tunnels do recover, re-probe
        assert bench._cached_probe() is None

    def test_negative_age_is_a_miss(self, bench, monkeypatch):
        # A sentinel stamped in the future (clock skew) must not be trusted.
        clock = FakeClock()
        monkeypatch.setattr(bench.time, "time", clock)
        bench._store_probe(None)
        clock.t -= 10
        assert bench._cached_probe() is None

    def test_boot_key_mismatch_is_a_miss(self, bench, monkeypatch):
        clock = FakeClock()
        monkeypatch.setattr(bench.time, "time", clock)
        bench._store_probe("cpu")
        monkeypatch.setattr(bench, "_boot_key", lambda: "boot-B")
        assert bench._cached_probe() is None

    def test_cache_disable_env(self, bench, monkeypatch):
        monkeypatch.setattr(bench.time, "time", FakeClock())
        bench._store_probe("tpu")
        monkeypatch.setenv("SATURN_BENCH_PROBE_CACHE", "0")
        assert bench._cached_probe() is None

    def test_store_records_none_platform(self, bench, monkeypatch):
        monkeypatch.setattr(bench.time, "time", FakeClock())
        bench._store_probe(None)
        assert bench._cached_probe() == (None,)


class TestProbeTimeoutShortCircuit:
    def test_timeout_stops_retry_loop(self, bench, monkeypatch):
        """A probe that burns its full timeout is a wedged tunnel: the retry
        budget must NOT be spent on it (BENCH_r05 paid 2 x 75 s doing so),
        and the failure must land in the sentinel immediately so the next
        run in this session skips the probe entirely."""
        clock = FakeClock()
        monkeypatch.setattr(bench.time, "time", clock)
        calls = []

        def fake_run(cmd, **kw):
            calls.append(cmd)
            raise subprocess.TimeoutExpired(cmd=cmd, timeout=kw.get("timeout"))

        monkeypatch.setattr(bench.subprocess, "run", fake_run)
        sleeps = []
        monkeypatch.setattr(bench.time, "sleep", sleeps.append)

        assert bench._probe_backend(timeout_s=75.0, retries=3) is None
        assert len(calls) == 1  # short-circuited: no retries after a timeout
        assert sleeps == []
        # Sentinel recorded the failure inline, not just at main()'s store.
        assert bench._cached_probe() == (None,)

    def test_fast_failure_still_retries(self, bench, monkeypatch):
        """rc != 0 failures are genuinely transient (UNAVAILABLE through the
        tunnel, BENCH_r01) and keep the retry budget."""
        monkeypatch.setattr(bench.time, "time", FakeClock())
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        calls = []

        def fake_run(cmd, **kw):
            calls.append(cmd)

            class R:
                returncode = 1
                stdout = ""
                stderr = "UNAVAILABLE: tunnel"

            return R()

        monkeypatch.setattr(bench.subprocess, "run", fake_run)
        assert bench._probe_backend(timeout_s=75.0, retries=2) is None
        assert len(calls) == 3  # initial + 2 retries
        # A fast failure does NOT write the sentinel from inside the probe
        # (main() records the final outcome once).
        assert bench._cached_probe() is None

    def test_success_returns_platform(self, bench, monkeypatch):
        monkeypatch.setattr(bench.time, "time", FakeClock())

        def fake_run(cmd, **kw):
            class R:
                returncode = 0
                stdout = "PLATFORM=tpu\n"
                stderr = ""

            return R()

        monkeypatch.setattr(bench.subprocess, "run", fake_run)
        assert bench._probe_backend() == "tpu"


class TestBenchGuard:
    @pytest.fixture()
    def guard(self):
        spec = importlib.util.spec_from_file_location(
            "bench_guard_under_test",
            os.path.join(REPO, "benchmarks", "bench_guard.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _write_record(self, root, n, parsed):
        with open(os.path.join(root, f"BENCH_r{n:02d}.json"), "w") as f:
            json.dump({"n": n, "rc": 0, "parsed": parsed}, f)

    def test_latest_record_picks_highest_round(self, guard, tmp_path, monkeypatch):
        monkeypatch.setattr(guard, "REPO", str(tmp_path))
        self._write_record(tmp_path, 3, {"value": 30.0, "platform": "cpu"})
        self._write_record(tmp_path, 5, {"value": 48.2, "platform": "cpu"})
        n, parsed = guard.latest_record()
        assert n == 5 and parsed["value"] == 48.2

    def test_latest_record_skips_unparsed(self, guard, tmp_path, monkeypatch):
        monkeypatch.setattr(guard, "REPO", str(tmp_path))
        self._write_record(tmp_path, 3, {"value": 30.0, "platform": "cpu"})
        with open(tmp_path / "BENCH_r07.json", "w") as f:
            json.dump({"n": 7, "rc": 124, "parsed": None}, f)
        n, _ = guard.latest_record()
        assert n == 3

    def test_regression_and_ok_verdicts(self, guard, tmp_path, monkeypatch, capsys):
        monkeypatch.setattr(guard, "REPO", str(tmp_path))
        shape = {"platform": "cpu", "batch_size": 2, "seq_len": 256}
        self._write_record(tmp_path, 5, {"value": 50.0, **shape})

        monkeypatch.setattr(guard, "run_bench", lambda: {"value": 44.0, **shape})
        assert guard.main() == 1  # 12% down: regression
        assert json.loads(capsys.readouterr().out)["status"] == "regression"

        monkeypatch.setattr(guard, "run_bench", lambda: {"value": 46.0, **shape})
        assert guard.main() == 0  # 8% down: within the 10% band
        assert json.loads(capsys.readouterr().out)["status"] == "ok"

    def test_shape_mismatch_skips(self, guard, tmp_path, monkeypatch, capsys):
        monkeypatch.setattr(guard, "REPO", str(tmp_path))
        self._write_record(
            tmp_path, 5,
            {"value": 50.0, "platform": "cpu", "batch_size": 2, "seq_len": 256},
        )
        monkeypatch.setattr(
            guard, "run_bench", lambda: {"value": 9000.0, "platform": "tpu"}
        )
        assert guard.main() == 0
        assert json.loads(capsys.readouterr().out)["status"] == "skipped"


class TestPipelineScheduleRow:
    """Round 20: the pipeline-schedule bench row contract."""

    GOOD = {
        "metric": "pipeline_schedule",
        "stages": 4,
        "microbatches": 4,
        "devices": 8,
        "gpipe_ms": 158.1,
        "f1b_ms": 75.4,
        "speedup_1f1b_vs_gpipe": 2.0981,
        "bubble_gpipe": 3 / 7,
        "bubble_1f1b": 3 / 10,
        "status": "ok",
    }

    @pytest.fixture()
    def guard(self):
        spec = importlib.util.spec_from_file_location(
            "bench_guard_pp_row",
            os.path.join(REPO, "benchmarks", "bench_guard.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_good_row_passes(self, guard):
        assert guard.validate_pipeline_row(dict(self.GOOD)) == []

    def test_missing_key_and_non_dict(self, guard):
        row = dict(self.GOOD)
        del row["f1b_ms"]
        assert any("f1b_ms" in p for p in guard.validate_pipeline_row(row))
        assert guard.validate_pipeline_row([1]) != []

    def test_bool_in_count_field_flagged(self, guard):
        row = dict(self.GOOD, stages=True)
        assert any("is bool" in p for p in guard.validate_pipeline_row(row))

    def test_speedup_below_one_fails_the_bar(self, guard):
        row = dict(self.GOOD, speedup_1f1b_vs_gpipe=0.97)
        assert any("beat GPipe" in p for p in
                   guard.validate_pipeline_row(row))

    def test_bubble_ordering_enforced(self, guard):
        row = dict(self.GOOD, bubble_1f1b=0.5)  # >= bubble_gpipe 0.4286
        assert any("smaller one" in p for p in
                   guard.validate_pipeline_row(row))
        row = dict(self.GOOD, bubble_gpipe=1.4)
        assert any("outside" in p for p in guard.validate_pipeline_row(row))
