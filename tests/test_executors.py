"""Executor tests on the 8-virtual-device CPU mesh: real pjit programs with
real shardings — the TPU-native analog of multi-node tests (SURVEY.md §4)."""

import numpy as np
import pytest

from saturn_tpu.parallel.dp import DataParallel
from saturn_tpu.parallel.fsdp import FSDP
from saturn_tpu.parallel.tp import TensorParallel
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.utils import checkpoint as ckpt


# Multi-device-compile-heavy on the 1-core CI host (VERDICT r3 item 7):
# these mesh suites are the slow tier; run with -m slow (or no -m filter).
pytestmark = pytest.mark.slow


def run_search_and_execute(tech, task, devices, n_batches=3):
    params, t = tech.search(task, devices, tid=0)
    assert params is not None, f"{tech.name} found no feasible config"
    assert t is not None and t > 0
    task.strategies[len(devices)] = Strategy(tech, len(devices), params, 100.0, t)
    task.select_strategy(len(devices))
    tech.execute(task, devices, tid=0, override_batch_count=n_batches)
    assert task.has_ckpt()
    return params, t


class TestDataParallel:
    def test_search_execute_ckpt(self, tiny_task, devices8):
        run_search_and_execute(DataParallel(), tiny_task, devices8[:4])

    def test_single_device(self, tiny_task, devices8):
        run_search_and_execute(DataParallel(), tiny_task, devices8[:1])

    def test_resume_advances_step(self, tiny_task, devices8):
        tech = DataParallel()
        run_search_and_execute(tech, tiny_task, devices8[:2], n_batches=2)
        state1 = ckpt.load_arrays(tiny_task.ckpt_path)
        assert state1["step"] == 2
        # resume on a DIFFERENT submesh size — reshard from checkpoint
        tech.execute(tiny_task, devices8[:4], tid=0, override_batch_count=3)
        ckpt.flush()  # execute()'s disk write is async
        state2 = ckpt.load_arrays(tiny_task.ckpt_path)
        assert state2["step"] == 5

    def test_params_replicated(self, tiny_task, devices8):
        """DP must replicate params: sharding of a param leaf covers 1 shard."""
        tech = DataParallel()
        bundle = tech.build(tiny_task, devices8[:4], {"remat": False})
        sh = bundle.state_shardings["params"]["wte"]
        assert sh.is_fully_replicated

    def test_completed_task_releases_bundles(self, tiny_task, devices8):
        """VERDICT r2 weak #7: a finished task must free its compiled
        programs, not just its live device state."""
        tech = DataParallel()
        run_search_and_execute(tech, tiny_task, devices8[:2], n_batches=1)
        assert any(k[0] == tiny_task.name for k in tech._bundles)
        # retry path: live state freed, compiled programs KEPT (a retried
        # task must not pay a recompile)
        tiny_task.release_live_state()
        assert tiny_task._live_state is None
        assert any(k[0] == tiny_task.name for k in tech._bundles)
        # completion path: compiled programs freed too
        tiny_task.release_compiled()
        assert not any(k[0] == tiny_task.name for k in tech._bundles)

    def test_bundle_cache_lru_cap(self, tiny_task, devices8):
        """The cache must not grow beyond bundle_cache_cap compiled programs."""
        tech = DataParallel()
        tech.bundle_cache_cap = 2
        tech.build(tiny_task, devices8[:1], {"remat": False})
        tech.build(tiny_task, devices8[:2], {"remat": False})
        tech.build(tiny_task, devices8[:4], {"remat": False})
        assert len(tech._bundles) == 2
        # most-recent entries survive
        sizes = {len(k[2]) for k in tech._bundles}
        assert sizes == {2, 4}


class TestFSDP:
    def test_search_execute_ckpt(self, tiny_task, devices8):
        run_search_and_execute(FSDP(), tiny_task, devices8[:4])

    def test_params_sharded(self, tiny_task, devices8):
        tech = FSDP()
        bundle = tech.build(tiny_task, devices8[:4], {"remat": False, "offload": False})
        sh = bundle.state_shardings["params"]["blocks"]["qkv"]["kernel"]
        assert not sh.is_fully_replicated
        # optimizer state shards identically to params (ZeRO-3)
        opt = bundle.state_shardings["opt_state"]
        flat = [s for s in np.array(list(np_tree_leaves(opt)), dtype=object)]
        assert any(not s.is_fully_replicated for s in flat if hasattr(s, "spec"))

    def test_cross_technique_switch(self, tiny_task, devices8):
        """Train under FSDP, resume under DP — the interval-boundary
        technique switch that is the system's central trick."""
        fsdp, dp = FSDP(), DataParallel()
        run_search_and_execute(fsdp, tiny_task, devices8[:4], n_batches=2)
        tiny_task.strategies[2] = Strategy(dp, 2, {"remat": False}, 50.0, 0.1)
        tiny_task.select_strategy(2)
        dp.execute(tiny_task, devices8[:2], tid=0, override_batch_count=2)
        ckpt.flush()  # execute()'s disk write is async
        state = ckpt.load_arrays(tiny_task.ckpt_path)
        assert state["step"] == 4


def np_tree_leaves(tree):
    import jax

    return jax.tree.leaves(tree)


class TestTensorParallel:
    def test_search_execute_ckpt(self, tiny_task, devices8):
        run_search_and_execute(TensorParallel(), tiny_task, devices8[:4])

    def test_tp_matches_dp_loss(self, tiny_task, devices8):
        """TP and DP must compute the same math: same loss trajectory from
        the same init/data (SPMD correctness check)."""
        import jax

        dp, tp = DataParallel(), TensorParallel()
        b_dp = dp.build(tiny_task, devices8[:2], {"remat": False})
        b_tp = tp.build(tiny_task, devices8[:2], {"tp": 2, "remat": False, "zero": False})
        s_dp, s_tp = b_dp.init(), b_tp.init()
        batch = tiny_task.batch_at(0)
        bd = jax.device_put(batch, b_dp.batch_sharding)
        bt = jax.device_put(batch, b_tp.batch_sharding)
        _, l_dp = b_dp.step(s_dp, bd)
        _, l_tp = b_tp.step(s_tp, bt)
        np.testing.assert_allclose(float(l_dp), float(l_tp), rtol=2e-2)

    def test_infeasible_on_one_device(self, tiny_task, devices8):
        params, t = TensorParallel().search(tiny_task, devices8[:1], tid=0)
        assert params is None  # tp needs >= 2 devices


class TestHostOffload:
    def test_search_execute_ckpt(self, tiny_task, devices8):
        from saturn_tpu.parallel.offload import HostOffload

        run_search_and_execute(HostOffload(), tiny_task, devices8[:2])

    def test_stream_matches_bulk_loss(self, tiny_task, devices8):
        """Streaming per-layer fetch must compute the same math as the bulk
        dense step (same init/data)."""
        import jax

        from saturn_tpu.parallel.offload import HostOffload

        tech = HostOffload()
        b_s = tech.build(tiny_task, devices8[:2], {"stream": True, "remat": True})
        b_b = tech.build(tiny_task, devices8[:2], {"stream": False, "remat": False})
        s_s, s_b = b_s.init(), b_b.init()
        batch = tiny_task.batch_at(0)
        _, l_s = b_s.step(s_s, jax.device_put(batch, b_s.batch_sharding))
        _, l_b = b_b.step(s_b, jax.device_put(batch, b_b.batch_sharding))
        np.testing.assert_allclose(float(l_s), float(l_b), rtol=2e-2)

    def test_billion_class_dmodel_streams(self, tmp_path, devices8):
        """VERDICT r3 item 4 (CPU side): the offload streaming path at a
        REAL billion-class d_model (gptj-1b3's 2048, layer count cut to 2)
        builds and takes a step — keeps the >=1B configuration covered off
        chip; benchmarks/billion_scale.py runs the full-depth chip row."""
        import jax

        from saturn_tpu import HParams, Task
        from saturn_tpu.data.lm_dataset import make_lm_dataset
        from saturn_tpu.models.gpt2 import build_gpt2
        from saturn_tpu.models.loss import pretraining_loss
        from saturn_tpu.parallel.offload import HostOffload

        task = Task(
            get_model=lambda **kw: build_gpt2(
                "gptj-1b3", n_layers=2, seq_len=128, vocab_size=2048, **kw
            ),
            get_dataloader=lambda: make_lm_dataset(
                context_length=128, batch_size=2, vocab_size=2048,
                n_tokens=128 * 2 * 4,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=1e-4, batch_count=2),
            save_dir=str(tmp_path / "ckpts"),
        )
        spec = task.get_model()
        assert spec.config.d_model == 2048 and spec.config.rotary
        tech = HostOffload()
        bundle = tech.build(task, devices8[:1], {"stream": True, "remat": True})
        state = bundle.init()
        batch = jax.device_put(task.batch_at(0), bundle.batch_sharding)
        state, loss = bundle.step(state, batch)
        assert np.isfinite(float(jax.device_get(loss)))

    def test_cross_technique_switch_from_offload(self, tiny_task, devices8):
        """Offload -> DP technique switch at an interval boundary (on the CPU
        test mesh state is device-resident — real pinned_host placement is
        TPU-only and covered by the TPU bench/verify drives)."""
        from saturn_tpu.parallel.offload import HostOffload

        off, dp = HostOffload(), DataParallel()
        run_search_and_execute(off, tiny_task, devices8[:1], n_batches=2)
        tiny_task.strategies[2] = Strategy(dp, 2, {"remat": False}, 50.0, 0.1)
        tiny_task.select_strategy(2)
        dp.execute(tiny_task, devices8[:2], tid=0, override_batch_count=2)
        ckpt.flush()  # execute()'s disk write is async
        state = ckpt.load_arrays(tiny_task.ckpt_path)
        assert state["step"] == 4


class TestAttentionAutotune:
    """VERDICT r1 item 3: the attention choice must be in the autotune grid
    so the trial runner can select flash from measurement."""

    def test_grid_crossed_when_flash_supported(self, tiny_task, monkeypatch):
        import saturn_tpu.ops.flash as flash
        from saturn_tpu.parallel.dp import DataParallel
        from saturn_tpu.parallel.fsdp import FSDP

        monkeypatch.setattr(flash, "flash_supported", lambda cfg=None: True)
        for tech in (DataParallel(), FSDP()):
            grid = tech.candidate_configs(tiny_task, 2)
            # both variants pinned EXPLICITLY (the model default is 'auto',
            # so an unpinned entry would duplicate flash on TPU)
            assert any(c.get("attention") == "flash" for c in grid)
            assert any(c.get("attention") == "dense" for c in grid)
            assert all("attention" in c for c in grid)
            # flash precedes its dense twin per base config (chip-measured
            # fastest; BASELINE.md attention table)
            flash_idx = min(
                i for i, c in enumerate(grid) if c.get("attention") == "flash"
            )
            dense_idx = min(
                i for i, c in enumerate(grid) if c.get("attention") == "dense"
            )
            assert flash_idx < dense_idx

    def test_grid_dense_only_off_tpu(self, tiny_task):
        from saturn_tpu.parallel.dp import DataParallel

        # CPU test mesh: flash_supported() is False, grid stays dense
        grid = DataParallel().candidate_configs(tiny_task, 2)
        assert all("attention" not in c for c in grid)

    def test_model_override_forwards_attention(self):
        from saturn_tpu.parallel.dp import DataParallel

        out = DataParallel()._model_overrides(
            {"remat": True, "attention": "flash"}
        )
        assert out == {"remat": True, "attention": "flash"}
        assert DataParallel()._model_overrides({"remat": False}) == {
            "remat": False
        }
