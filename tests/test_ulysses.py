"""Ulysses all-to-all sequence parallelism on the 8-virtual-device CPU mesh."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from saturn_tpu.ops.ulysses import ulysses_attention
from tests.test_ring import dense_causal_attention


# Multi-device-compile-heavy on the 1-core CI host (VERDICT r3 item 7):
# these mesh suites are the slow tier; run with -m slow (or no -m filter).
pytestmark = pytest.mark.slow


class TestUlyssesAttention:
    @pytest.mark.parametrize("sp", [2, 4])
    def test_matches_dense(self, devices8, sp):
        B, H, T, D = 2, 4, 32, 8
        rng = np.random.default_rng(0)
        q, k, v = (
            jax.numpy.asarray(rng.normal(size=(B, H, T, D)), dtype=jax.numpy.float32)
            for _ in range(3)
        )
        mesh = Mesh(np.array(devices8[:sp]), ("seq",))

        def local(q, k, v):
            return ulysses_attention(q, k, v, axis_name="seq", axis_size=sp)

        mapped = jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(None, None, "seq"),) * 3,
            out_specs=P(None, None, "seq"),
            check_vma=False,
        )
        out = jax.jit(mapped)(q, k, v)
        ref = dense_causal_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_head_divisibility_enforced(self, devices8):
        with pytest.raises(ValueError, match="not divisible"):
            q = jax.numpy.zeros((1, 3, 8, 4))
            ulysses_attention(q, q, q, axis_name="seq", axis_size=2)


class TestUlyssesTechnique:
    def test_search_execute_ckpt(self, tiny_task, devices8):
        from saturn_tpu.parallel.ulysses import UlyssesSequenceParallel
        from tests.test_executors import run_search_and_execute

        run_search_and_execute(UlyssesSequenceParallel(), tiny_task, devices8[:4])

    def test_matches_dp_loss(self, tiny_task, devices8):
        from saturn_tpu.parallel.dp import DataParallel
        from saturn_tpu.parallel.ulysses import UlyssesSequenceParallel

        dp, ul = DataParallel(), UlyssesSequenceParallel()
        b_dp = dp.build(tiny_task, devices8[:2], {"remat": False})
        b_ul = ul.build(tiny_task, devices8[:4], {"sp": 4, "remat": False})
        s_dp, s_ul = b_dp.init(), b_ul.init()
        batch = tiny_task.batch_at(0)
        _, l_dp = b_dp.step(s_dp, jax.device_put(batch, b_dp.batch_sharding))
        _, l_ul = b_ul.step(s_ul, jax.device_put(batch, b_ul.batch_sharding))
        np.testing.assert_allclose(float(l_dp), float(l_ul), rtol=2e-2)

    def test_sp_capped_by_heads(self, tiny_task, devices8):
        """test-tiny has 4 heads: sp=8 must not be proposed."""
        from saturn_tpu.parallel.ulysses import UlyssesSequenceParallel

        grid = UlyssesSequenceParallel().candidate_configs(tiny_task, 8)
        assert grid and all(c["sp"] <= 4 for c in grid)
