"""Multi-host path tests (VERDICT r1 item 7): SliceTopology slice inference
and a real 2-process ``jax.distributed`` rendezvous on CPU.

The reference's multi-node story was never tested either (its solver faked 8
GPUs/node, ``milp.py:57-62``); here slice inference is unit-tested with fake
multi-process devices and ``core/distributed.initialize`` is smoke-tested
with two real OS processes rendezvousing over localhost and running a
cross-process collective (Gloo under the CPU backend).
"""

import logging
import os
import pathlib
import socket
import subprocess
import sys
import textwrap

import pytest

from saturn_tpu.core.mesh import Block, SliceTopology


# Multi-device-compile-heavy on the 1-core CI host (VERDICT r3 item 7):
# these mesh suites are the slow tier; run with -m slow (or no -m filter).
pytestmark = pytest.mark.slow


class FakeDev:
    def __init__(self, process_index=0):
        self.process_index = process_index


class TestSliceInference:
    def test_two_hosts_infer_slice_size(self):
        # 2 processes x 4 devices, interleaved on purpose: constructor must
        # regroup slice-major (all of proc 0, then all of proc 1).
        devs = [FakeDev(i % 2) for i in range(8)]
        topo = SliceTopology(devs)
        assert topo.slice_size == 4
        assert topo.capacity == 8
        assert [d.process_index for d in topo.devices] == [0] * 4 + [1] * 4

    def test_single_host_one_slice(self):
        devs = [FakeDev(0) for _ in range(8)]
        topo = SliceTopology(devs)
        assert topo.slice_size == 8

    def test_uneven_groups_fall_back_to_one_slice(self):
        # 3 + 5 devices per process: not a uniform pow2 grouping
        devs = [FakeDev(0)] * 3 + [FakeDev(1)] * 5
        topo = SliceTopology(devs)
        assert topo.slice_size == 8

    def test_crosses_dcn(self):
        devs = [FakeDev(i // 4) for i in range(8)]
        topo = SliceTopology(devs)
        assert not topo.crosses_dcn(Block(0, 4))      # within slice 0
        assert not topo.crosses_dcn(Block(4, 4))      # within slice 1
        assert not topo.crosses_dcn(Block(2, 2))
        assert topo.crosses_dcn(Block(0, 8))          # spans both slices
        # aligned sub-slice blocks never straddle a slice boundary: with
        # pow2 slice sizes only whole-multiple-of-slice blocks cross DCN
        for size in (1, 2, 4):
            for blk in topo.blocks(size):
                assert not topo.crosses_dcn(blk)

    def test_stranded_devices_warn(self, caplog):
        with caplog.at_level(logging.WARNING, logger="saturn_tpu"):
            topo = SliceTopology([FakeDev(0) for _ in range(6)])
        assert topo.capacity == 4
        assert "stranded" in caplog.text

    def test_no_warning_on_pow2(self, caplog):
        with caplog.at_level(logging.WARNING, logger="saturn_tpu"):
            SliceTopology([FakeDev(0) for _ in range(8)])
        assert "stranded" not in caplog.text


_WORKER = textwrap.dedent("""
    import os, sys
    pid, port = int(sys.argv[1]), sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from saturn_tpu.core import distributed

    distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    # idempotency: a second call must not raise
    distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()

    topo = distributed.global_topology()
    assert topo.slice_size == 2, topo.slice_size
    assert topo.capacity == 4
    assert [d.process_index for d in topo.devices] == [0, 0, 1, 1]
    from saturn_tpu.core.mesh import Block
    assert not topo.crosses_dcn(Block(0, 2))
    assert topo.crosses_dcn(Block(0, 4))

    # cross-process collective through a global mesh (DCN-analog path)
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from saturn_tpu.core.mesh import make_submesh

    mesh = make_submesh(topo.devices, ("data",))
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), np.full((4, 2), pid + 1.0, np.float32)
    )
    total = jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh, P()))(x)
    # proc0 rows sum 1.0*4*2, proc1 rows 2.0*4*2 -> 24
    assert abs(float(total) - 24.0) < 1e-6, float(total)
    print(f"OK {pid}")
""")


class TestTwoProcessRendezvous:
    def test_initialize_and_collective(self, tmp_path):
        script = tmp_path / "worker.py"
        script.write_text(_WORKER)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        # The workers run from tmp_path, so the script's directory (what
        # `python worker.py` puts on sys.path) does not contain saturn_tpu;
        # export the repo root via PYTHONPATH so the import works from a
        # clean checkout without installing the package.
        repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(pid), str(port)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=repo_root,
                env=env,
            )
            for pid in (0, 1)
        ]
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=150)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            outs.append(out)
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {pid} failed:\n{out[-2000:]}"
            assert f"OK {pid}" in out


_ORCH_WORKER = textwrap.dedent("""
    import os, sys
    pid, port, ckdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
        + " --xla_cpu_collective_call_terminate_timeout_seconds=600"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from saturn_tpu.core import distributed

    distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2,
        process_id=pid,
    )

    import numpy as np
    from saturn_tpu import HParams, Task, orchestrate
    from saturn_tpu.core.strategy import Strategy
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss
    from saturn_tpu.parallel.dp import DataParallel

    topo = distributed.global_topology()
    dp = DataParallel()

    def mk(name, app):
        t = Task(
            get_model=lambda **kw: build_gpt2("test-tiny", **kw),
            get_dataloader=lambda: make_lm_dataset(
                context_length=64, batch_size=8, vocab_size=256,
                n_tokens=64 * 8 * 4,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=1e-3, batch_count=2),
            name=name,
            save_dir=ckdir,
        )
        # Preset identical strategies on every rank (profiling wall-clock
        # is per-process; the multihost contract is rank-identical inputs).
        t.strategies[app] = Strategy(dp, app, {"remat": False}, 1.0, 0.5)
        return t

    # cross: spans both processes' devices; local: a 2-device block that
    # lands entirely on one process's slice.
    tasks = [mk("mh-cross", 4), mk("mh-local", 2)]
    res = orchestrate(tasks, interval=60.0, topology=topo, log=True,
                      solver_time_limit=2.0)
    assert sorted(res["completed"]) == ["mh-cross", "mh-local"], res
    assert not res["failed"], res
    for t in tasks:
        from saturn_tpu.utils import checkpoint as _ck
        ck = _ck.load_arrays(t.ckpt_path)
        assert int(ck["step"]) == 2, (t.name, int(ck["step"]))
    print(f"ORCH_OK {pid}")
""")


class TestMultihostOrchestrate:
    def test_two_process_orchestrate_end_to_end(self, tmp_path):
        """Full multi-host control plane: coordinator-solved broadcast plan,
        sequential deterministic execution (cross-process AND host-local
        blocks), writer-rank checkpoints, interval-end flush barrier."""
        script = tmp_path / "orch_worker.py"
        script.write_text(_ORCH_WORKER)
        ckdir = str(tmp_path / "ckpts")
        os.makedirs(ckdir, exist_ok=True)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(pid), str(port), ckdir],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                cwd=repo_root, env=env,
            )
            for pid in (0, 1)
        ]
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            outs.append(out)
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
            assert f"ORCH_OK {pid}" in out, out[-3000:]


class TestMultihostDryrun:
    def test_train_step_and_rank0_checkpoint(self):
        """VERDICT r3 item 9: 2 processes x 2 CPU devices — real train step
        over the cross-process mesh, rank-0-gated checkpoint write, restore
        on every rank. Delegates to ``__graft_entry__.dryrun_multihost`` so
        CI and the driver exercise the same path."""
        sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
        try:
            import __graft_entry__ as graft
        finally:
            sys.path.pop(0)
        graft.dryrun_multihost(n_processes=2, devices_per_process=2)
