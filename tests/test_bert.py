"""BERT-class encoder family: bidirectionality, MLM objective, executor parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from saturn_tpu.models.bert import (
    MASK_OFFSET,
    MASK_STRIDE,
    build_bert,
    mlm_loss,
)

# Model-build + executor compiles dominate on the 1-core host: slow tier.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bert_spec():
    return build_bert("bert-test-tiny")


@pytest.fixture()
def bert_task(tmp_path):
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset

    return Task(
        get_model=lambda **kw: build_bert("bert-test-tiny", **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256, n_tokens=64 * 8 * 8,
            reserved_ids=1,  # keep the [MASK] id out of the data
        ),
        loss_fn=mlm_loss,
        hparams=HParams(lr=1e-3, batch_count=8),
        save_dir=str(tmp_path / "ckpts"),
    )


class TestBertModel:
    def test_presets(self):
        for name in ("bert-base", "bert-large", "bert-test-tiny"):
            assert build_bert(name).config.causal is False
        with pytest.raises(KeyError):
            build_bert("bert-huge")

    def test_forward_shape(self, bert_spec):
        cfg = bert_spec.config
        params = bert_spec.init_fn(jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, cfg.seq_len), dtype=jnp.int32)
        assert bert_spec.apply_fn(params, tokens).shape == (
            2, cfg.seq_len, cfg.vocab_size,
        )

    def test_bidirectional(self, bert_spec):
        """Encoder: a LATER token change must affect EARLIER logits."""
        params = bert_spec.init_fn(jax.random.PRNGKey(0))
        t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 64), 0, 250)
        t2 = t1.at[0, 50].set((t1[0, 50] + 1) % 250)
        l1 = bert_spec.apply_fn(params, t1)
        l2 = bert_spec.apply_fn(params, t2)
        assert not np.allclose(np.asarray(l1[0, :10]), np.asarray(l2[0, :10]),
                               atol=1e-5)

    def test_masking_applied(self, bert_spec):
        """Changing a token at a MASKED position must not change the logits —
        the forward must see [MASK] there, not the token."""
        params = bert_spec.init_fn(jax.random.PRNGKey(0))
        pos = MASK_OFFSET  # a masked position
        t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 64), 0, 250)
        t2 = t1.at[0, pos].set((t1[0, pos] + 1) % 250)
        l1 = bert_spec.apply_fn(params, t1)
        l2 = bert_spec.apply_fn(params, t2)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-6)

    def test_mlm_loss_only_masked_positions(self):
        B, T, V = 2, 14, 11
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(B, T, V)), jnp.float32)
        tokens = jnp.asarray(rng.integers(0, V, size=(B, T)), jnp.int32)
        # perturb one class at a NON-masked position: loss must not change
        l0 = float(mlm_loss(logits, tokens))
        logits2 = logits.at[:, MASK_OFFSET + 1, 0].add(3.0)
        assert float(mlm_loss(logits2, tokens)) == pytest.approx(l0)
        # perturb one class at a masked position: loss changes
        logits3 = logits.at[:, MASK_OFFSET, 0].add(3.0)
        assert float(mlm_loss(logits3, tokens)) != pytest.approx(l0)

    def test_trains(self, bert_spec):
        import optax

        params = bert_spec.init_fn(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 64), 0, 250)
        tx = optax.adam(1e-3)
        opt = tx.init(params)

        @jax.jit
        def step(params, opt):
            loss, g = jax.value_and_grad(
                lambda p: mlm_loss(bert_spec.apply_fn(p, tokens), tokens)
            )(params)
            up, opt = tx.update(g, opt, params)
            return optax.apply_updates(params, up), opt, loss

        losses = []
        for _ in range(5):
            params, opt, loss = step(params, opt)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestBertExecutors:
    def test_dp_and_fsdp(self, bert_task, devices8):
        from saturn_tpu.parallel.dp import DataParallel
        from saturn_tpu.parallel.fsdp import FSDP
        from tests.test_executors import run_search_and_execute

        run_search_and_execute(DataParallel(), bert_task, devices8[:2])
        bert_task.clear_ckpt()
        run_search_and_execute(FSDP(), bert_task, devices8[:4])

    def test_pp_matches_dp_objective(self, bert_task, devices8):
        """Pipeline embed hint must apply [MASK] too — same loss as dp."""
        from saturn_tpu.parallel.dp import DataParallel
        from saturn_tpu.parallel.pp import Pipeline

        dp, pp = DataParallel(), Pipeline()
        b_dp = dp.build(bert_task, devices8[:2], {"remat": False})
        b_pp = pp.build(
            bert_task, devices8[:2], {"stages": 2, "microbatches": 2, "remat": False}
        )
        s_dp, s_pp = b_dp.init(), b_pp.init()
        batch = bert_task.batch_at(0)
        _, l_dp = b_dp.step(s_dp, jax.device_put(batch, b_dp.batch_sharding))
        _, l_pp = b_pp.step(s_pp, jax.device_put(batch, b_pp.batch_sharding))
        np.testing.assert_allclose(float(l_dp), float(l_pp), rtol=2e-2)

    def test_seq_parallel_infeasible(self, bert_task, devices8):
        """Encoder models must be infeasible for causal seq techniques."""
        from saturn_tpu.parallel.ring import RingSequenceParallel
        from saturn_tpu.parallel.ulysses import UlyssesSequenceParallel

        assert RingSequenceParallel().candidate_configs(bert_task, 8) == []
        assert UlyssesSequenceParallel().candidate_configs(bert_task, 8) == []


class TestMaskIdReservation:
    """ADVICE r1 (medium): the [MASK] id must never occur in the data."""

    def test_synthetic_reserved(self):
        from saturn_tpu.data.lm_dataset import make_lm_dataset

        ds = make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256,
            n_tokens=64 * 8 * 32, reserved_ids=1,
        )
        for i in range(len(ds)):
            assert ds.batch(i).max() < 255

    def test_byte_tokenizer_rejects_collision(self, tmp_path):
        from saturn_tpu.data.lm_dataset import make_lm_dataset

        p = tmp_path / "corpus.txt"
        p.write_bytes(bytes(range(256)) * 600)
        with pytest.raises(ValueError, match="byte tokenizer"):
            make_lm_dataset(
                context_length=64, batch_size=8, vocab_size=256,
                corpus_path=str(p), tokenizer="byte", reserved_ids=1,
            )
        # vocab 257 leaves the top id free: accepted.
        ds = make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=257,
            corpus_path=str(p), tokenizer="byte", reserved_ids=1,
        )
        assert ds.batch(0).max() <= 255

    def test_word_vocab_capped_below_mask(self, tmp_path):
        from saturn_tpu.data.lm_dataset import make_lm_dataset

        words = " ".join(f"w{i}" for i in range(300))
        p = tmp_path / "words.txt"
        p.write_text(words * 40)
        ds = make_lm_dataset(
            context_length=32, batch_size=4, vocab_size=128,
            corpus_path=str(p), tokenizer="word", reserved_ids=1,
        )
        for i in range(len(ds)):
            assert ds.batch(i).max() < 127

    def test_reserved_ids_validation(self):
        from saturn_tpu.data.lm_dataset import make_lm_dataset

        with pytest.raises(ValueError, match="reserved_ids"):
            make_lm_dataset(vocab_size=16, reserved_ids=16)
